"""Connectors v2: composable batch/observation transform pipelines.

Counterpart of the reference's connector framework
(reference: rllib/connectors/ — ConnectorV2 base connector_v2.py,
env-to-module pipelines applied by EnvRunners before the RLModule
forward, learner pipelines applied to train batches before the update;
wired via AlgorithmConfig.env_to_module_connector /
learner_connector). Same two hook points here:

- env-to-module: SingleAgentEnvRunner passes raw vector observations
  through the pipeline before every forward call; the transformed
  observations are what land in the sample batch.
- learner: algorithms pass each rollout batch through the pipeline
  BEFORE advantage postprocessing (so e.g. reward clipping shapes GAE
  too) and before the jitted update.

Connectors are host-side numpy transforms — exactly the work that should
NOT live inside the jitted step (dynamic shapes, python logic), which is
why the pipeline sits at the host/XLA boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np


class ConnectorV2:
    """One transform stage (reference: connectors/connector_v2.py)."""

    def __call__(self, data: Any, **kwargs) -> Any:
        raise NotImplementedError

    def reset(self) -> None:
        """Hook for connectors carrying episode-scoped state. The built-in
        vectorized runner uses same-step autoreset and shares one pipeline
        across envs, so it never calls this — custom sequential runners
        may; built-in connectors keep running (episode-agnostic) state."""


class ConnectorPipelineV2(ConnectorV2):
    """Ordered composition (reference: connector_pipeline_v2.py)."""

    def __init__(self, connectors: Sequence[ConnectorV2] = ()):
        self.connectors = list(connectors)

    def __call__(self, data: Any, **kwargs) -> Any:
        for c in self.connectors:
            data = c(data, **kwargs)
        return data

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def get_state(self) -> list:
        return [c.get_state() if hasattr(c, "get_state") else None
                for c in self.connectors]

    def set_state(self, states: list) -> None:
        if len(states) != len(self.connectors):
            raise ValueError(
                f"connector state has {len(states)} entries but the "
                f"pipeline has {len(self.connectors)} connectors — the "
                f"pipeline changed since the checkpoint was written"
            )
        for c, s in zip(self.connectors, states):
            if s is not None and hasattr(c, "set_state"):
                c.set_state(s)


class LambdaConnector(ConnectorV2):
    """Wrap a plain function as a connector."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, data: Any, **kwargs) -> Any:
        return self.fn(data)


class FlattenObservations(ConnectorV2):
    """[B, ...] -> [B, prod(...)] (reference:
    connectors/env_to_module/flatten_observations.py)."""

    def __call__(self, obs: np.ndarray, **kwargs) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        return obs.reshape(obs.shape[0], -1)


def _chan_combine(a: tuple, b: tuple) -> tuple:
    """Chan's parallel combine of two (count, mean, m2) triples."""
    n1, mean1, m21 = a
    n2, mean2, m22 = b
    if n2 == 0.0 or mean2 is None:
        return a
    if n1 == 0.0 or mean1 is None:
        return b
    n = n1 + n2
    delta = mean2 - mean1
    mean = mean1 + delta * (n2 / n)
    m2 = m21 + m22 + delta**2 * (n1 * n2 / n)
    return n, mean, m2


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (reference:
    connectors/env_to_module/mean_std_filter.py — MeanStdFilter with
    cross-runner syncing: each runner accumulates a DELTA since the last
    sync on top of a shared synced base; EnvRunnerGroup merges the
    deltas via Chan's parallel combine and broadcasts the merged stats
    back, so with num_env_runners>1 every runner normalizes with the
    same converged statistics and nothing is double-counted)."""

    def __init__(self, epsilon: float = 1e-8, clip: float | None = 10.0):
        self.eps = epsilon
        self.clip = clip
        # Effective stats (base ⊕ delta), used for normalization.
        self._count = 0.0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None
        # Shared base as of the last sync/restore.
        self._base = (0.0, None, None)
        # Locally accumulated since the last sync.
        self._d_count = 0.0
        self._d_mean: np.ndarray | None = None
        self._d_m2: np.ndarray | None = None

    def __call__(self, obs: np.ndarray, *, update: bool = True, **kwargs):
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.zeros(obs.shape[1:], np.float64)
        if self._d_mean is None:
            self._d_mean = np.zeros(obs.shape[1:], np.float64)
            self._d_m2 = np.zeros(obs.shape[1:], np.float64)
        if update:
            # Chan's parallel update: fold the whole [B, ...] block in one
            # vectorized step (no per-row Python loop on the hot path).
            block = obs.reshape(-1, *self._mean.shape).astype(np.float64)
            n_b = float(block.shape[0])
            if n_b > 0:
                mean_b = block.mean(axis=0)
                m2_b = ((block - mean_b) ** 2).sum(axis=0)
                self._count, self._mean, self._m2 = _chan_combine(
                    (self._count, self._mean, self._m2),
                    (n_b, mean_b, m2_b))
                self._d_count, self._d_mean, self._d_m2 = _chan_combine(
                    (self._d_count, self._d_mean, self._d_m2),
                    (n_b, mean_b, m2_b))
        var = self._m2 / max(self._count, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    def get_state(self) -> dict:
        """Snapshot AND harvest (reference: MeanStdFilter clears its sync
        buffer when gathered): the returned delta is consumed by the
        merge, so it must not be re-reported at the next gather — that
        would double-count if a runner ever missed a broadcast. After a
        harvest the effective stats are this runner's own running filter,
        NOT derivable from base ⊕ delta (they retain harvested samples
        the base only gains when the merged broadcast lands)."""
        state = {
            "kind": "normalize_obs",
            "count": self._count, "mean": self._mean, "m2": self._m2,
            "base": self._base,
            "delta": (self._d_count, self._d_mean, self._d_m2),
        }
        self._d_count = 0.0
        self._d_mean = None
        self._d_m2 = None
        return state

    def set_state(self, state: dict) -> None:
        """Adopt state as the new synced base; the local delta restarts
        at zero (sync-broadcast and checkpoint-restore both land here)."""
        self._count = state["count"]
        self._mean = None if state["mean"] is None else np.array(
            state["mean"], np.float64)
        self._m2 = None if state["m2"] is None else np.array(
            state["m2"], np.float64)
        self._base = (self._count, self._mean, self._m2)
        self._d_count = 0.0
        self._d_mean = None
        self._d_m2 = None

    @staticmethod
    def merge_states(states: "list[dict]") -> dict:
        """Freshest base ⊕ every runner's harvested delta. Bases can
        diverge when a runner misses a broadcast (partial failure) or is
        recreated mid-training; taking the largest-count base keeps the
        longest shared history, and because deltas are harvested at
        gather time no sample can be folded in twice. States written
        before the base/delta split (no 'delta' key) merge their
        effectives — only correct for a single runner, which is all that
        format ever held."""
        if all("delta" in s for s in states):
            acc = max((tuple(s.get("base", (0.0, None, None)))
                       for s in states), key=lambda b: b[0])
            for s in states:
                acc = _chan_combine(acc, tuple(s["delta"]))
        else:
            acc = (0.0, None, None)
            for s in states:
                acc = _chan_combine(acc, (s["count"], s["mean"], s["m2"]))
        return {"kind": "normalize_obs",
                "count": acc[0], "mean": acc[1], "m2": acc[2]}


class ClipRewards(ConnectorV2):
    """Learner-side reward clipping (reference:
    connectors/learner/... reward clipping used by Atari configs)."""

    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, batch, **kwargs):
        from ray_tpu.rllib.sample_batch import REWARDS

        if REWARDS in batch:
            batch[REWARDS] = np.clip(batch[REWARDS], -self.limit, self.limit)
        return batch


def merge_pipeline_states(per_runner: "list[list]"
                          ) -> "tuple[list, list[bool]] | tuple[None, None]":
    """Position-wise merge of pipeline states gathered from N runners.

    Stateful connectors publish a self-describing ``kind`` so the merge
    can happen group-side without the connector instances (the group only
    sees pickled state from remote runner actors). Unknown state kinds
    fall back to the first runner's copy — usable for a checkpoint, but
    NOT safe to broadcast back (it would clobber the other runners'
    independent state), hence the per-position ``mergeable`` mask.

    Returns (merged_states, mergeable_mask).
    """
    per_runner = [s for s in per_runner if s is not None]
    if not per_runner:
        return None, None
    merged: list = []
    mergeable: list[bool] = []
    for states in zip(*per_runner):
        non_null = [s for s in states if s is not None]
        if not non_null:
            merged.append(None)
            mergeable.append(True)  # nothing to clobber
        elif all(isinstance(s, dict) and s.get("kind") == "normalize_obs"
                 for s in non_null):
            merged.append(NormalizeObservations.merge_states(non_null))
            mergeable.append(True)
        else:
            merged.append(non_null[0])
            mergeable.append(False)
    return merged, mergeable


def build_pipeline(spec) -> ConnectorPipelineV2 | None:
    """Normalize user input: None | callable-factory | connector |
    list-of-connectors -> pipeline."""
    if spec is None:
        return None
    if isinstance(spec, ConnectorPipelineV2):
        return spec
    if isinstance(spec, ConnectorV2):
        return ConnectorPipelineV2([spec])
    if callable(spec):  # factory (reference passes factories for actors)
        return build_pipeline(spec())
    if isinstance(spec, (list, tuple)):
        return ConnectorPipelineV2([
            c if isinstance(c, ConnectorV2) else LambdaConnector(c)
            for c in spec
        ])
    raise TypeError(f"cannot build a connector pipeline from {spec!r}")
