"""Connectors v2: composable batch/observation transform pipelines.

Counterpart of the reference's connector framework
(reference: rllib/connectors/ — ConnectorV2 base connector_v2.py,
env-to-module pipelines applied by EnvRunners before the RLModule
forward, learner pipelines applied to train batches before the update;
wired via AlgorithmConfig.env_to_module_connector /
learner_connector). Same two hook points here:

- env-to-module: SingleAgentEnvRunner passes raw vector observations
  through the pipeline before every forward call; the transformed
  observations are what land in the sample batch.
- learner: algorithms pass each rollout batch through the pipeline
  BEFORE advantage postprocessing (so e.g. reward clipping shapes GAE
  too) and before the jitted update.

Connectors are host-side numpy transforms — exactly the work that should
NOT live inside the jitted step (dynamic shapes, python logic), which is
why the pipeline sits at the host/XLA boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np


class ConnectorV2:
    """One transform stage (reference: connectors/connector_v2.py)."""

    def __call__(self, data: Any, **kwargs) -> Any:
        raise NotImplementedError

    def reset(self) -> None:
        """Hook for connectors carrying episode-scoped state. The built-in
        vectorized runner uses same-step autoreset and shares one pipeline
        across envs, so it never calls this — custom sequential runners
        may; built-in connectors keep running (episode-agnostic) state."""


class ConnectorPipelineV2(ConnectorV2):
    """Ordered composition (reference: connector_pipeline_v2.py)."""

    def __init__(self, connectors: Sequence[ConnectorV2] = ()):
        self.connectors = list(connectors)

    def __call__(self, data: Any, **kwargs) -> Any:
        for c in self.connectors:
            data = c(data, **kwargs)
        return data

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def get_state(self) -> list:
        return [c.get_state() if hasattr(c, "get_state") else None
                for c in self.connectors]

    def set_state(self, states: list) -> None:
        if len(states) != len(self.connectors):
            raise ValueError(
                f"connector state has {len(states)} entries but the "
                f"pipeline has {len(self.connectors)} connectors — the "
                f"pipeline changed since the checkpoint was written"
            )
        for c, s in zip(self.connectors, states):
            if s is not None and hasattr(c, "set_state"):
                c.set_state(s)


class LambdaConnector(ConnectorV2):
    """Wrap a plain function as a connector."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, data: Any, **kwargs) -> Any:
        return self.fn(data)


class FlattenObservations(ConnectorV2):
    """[B, ...] -> [B, prod(...)] (reference:
    connectors/env_to_module/flatten_observations.py)."""

    def __call__(self, obs: np.ndarray, **kwargs) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        return obs.reshape(obs.shape[0], -1)


class NormalizeObservations(ConnectorV2):
    """Running mean/std normalization (reference:
    connectors/env_to_module/mean_std_filter.py — per-runner running
    filter, like the reference's MeanStdFilter; stats are checkpointed
    through the runner's connector state and seeded onto restored
    runners; concurrent runners accumulate independently, as in the
    reference without explicit filter syncing)."""

    def __init__(self, epsilon: float = 1e-8, clip: float | None = 10.0):
        self.eps = epsilon
        self.clip = clip
        self._count = 0.0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None

    def __call__(self, obs: np.ndarray, *, update: bool = True, **kwargs):
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.zeros(obs.shape[1:], np.float64)
        if update:
            # Chan's parallel update: fold the whole [B, ...] block in one
            # vectorized step (no per-row Python loop on the hot path).
            block = obs.reshape(-1, *self._mean.shape).astype(np.float64)
            n_b = float(block.shape[0])
            if n_b > 0:
                mean_b = block.mean(axis=0)
                m2_b = ((block - mean_b) ** 2).sum(axis=0)
                delta = mean_b - self._mean
                total = self._count + n_b
                self._mean += delta * (n_b / total)
                self._m2 += m2_b + delta**2 * (self._count * n_b / total)
                self._count = total
        var = self._m2 / max(self._count, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipRewards(ConnectorV2):
    """Learner-side reward clipping (reference:
    connectors/learner/... reward clipping used by Atari configs)."""

    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, batch, **kwargs):
        from ray_tpu.rllib.sample_batch import REWARDS

        if REWARDS in batch:
            batch[REWARDS] = np.clip(batch[REWARDS], -self.limit, self.limit)
        return batch


def build_pipeline(spec) -> ConnectorPipelineV2 | None:
    """Normalize user input: None | callable-factory | connector |
    list-of-connectors -> pipeline."""
    if spec is None:
        return None
    if isinstance(spec, ConnectorPipelineV2):
        return spec
    if isinstance(spec, ConnectorV2):
        return ConnectorPipelineV2([spec])
    if callable(spec):  # factory (reference passes factories for actors)
        return build_pipeline(spec())
    if isinstance(spec, (list, tuple)):
        return ConnectorPipelineV2([
            c if isinstance(c, ConnectorV2) else LambdaConnector(c)
            for c in spec
        ])
    raise TypeError(f"cannot build a connector pipeline from {spec!r}")
