"""JaxLearner + LearnerGroup: the jitted update path.

Counterpart of the reference's Learner (rllib/core/learner/learner.py:107 —
compute_losses :887, compute_gradients :459, apply_gradients :602, update
:971) and LearnerGroup (learner_group.py:72). Redesign: where TorchLearner
wraps modules in DDP over NCCL (torch_learner.py:436-539), JaxLearner runs
ONE jitted step; scaling across chips is a `data`-axis NamedSharding on the
batch, XLA inserting the gradient all-reduce over ICI (SURVEY.md §2.4
"Async RL parallelism" row). A LearnerGroup of remote actors exists for
host-level scale-out (each actor drives its own mesh)."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.sample_batch import SampleBatch


def make_optimizer(cfg) -> optax.GradientTransformation:
    """The shared algorithm optimizer: adam(lr) with optional global-norm
    clipping (reference: Learner._configure_optimizers default)."""
    tx = optax.adam(cfg.lr)
    if cfg.grad_clip is not None:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip), tx)
    return tx


class JaxLearner:
    """Owns (params, opt_state) and a compiled update step.

    `loss_fn(params, apply_fn, batch) -> (loss, metrics_dict)` is supplied
    by the algorithm (PPO/IMPALA define theirs)."""

    def __init__(
        self,
        module,  # RLModule: provides .params and .apply
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        mesh: Optional[jax.sharding.Mesh] = None,
        data_axis: str = "data",
        seed: int = 0,
    ):
        self.module = module
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.opt_state = optimizer.init(module.params)
        self.mesh = mesh
        self._metrics: dict = {}
        # Advances across update_epochs calls: fresh minibatch permutations
        # every training_step.
        self._rng = np.random.default_rng(seed)

        apply_fn = module.apply

        def _update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, apply_fn, batch
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(mesh, P())
            batch_sharded = NamedSharding(mesh, P(data_axis))
            self._jit_update = jax.jit(
                _update,
                in_shardings=(replicated, replicated, batch_sharded),
                out_shardings=(replicated, replicated, replicated),
                donate_argnums=(0, 1),
            )
        else:
            self._jit_update = jax.jit(_update, donate_argnums=(0, 1))

    # ------------------------------------------------------------------

    def update(self, batch: SampleBatch) -> dict:
        """One gradient step on `batch` (already minibatched by the algo).
        Values may be nested pytrees (off-policy algos pass rng keys /
        precomputed target structures alongside the flat columns)."""
        jbatch = jax.tree.map(jnp.asarray, dict(batch))
        self.module.params, self.opt_state, metrics = self._jit_update(
            self.module.params, self.opt_state, jbatch
        )
        self._metrics = {k: float(v) for k, v in metrics.items()}
        return self._metrics

    def update_epochs(
        self,
        batch: SampleBatch,
        *,
        num_epochs: int,
        minibatch_size: int,
        rng: np.random.Generator | None = None,
    ) -> dict:
        """SGD epochs over shuffled minibatches (reference: Learner.update
        with minibatching)."""
        rng = rng or self._rng
        last: dict = {}
        for _ in range(num_epochs):
            shuffled = batch.shuffle(rng)
            for mb in shuffled.minibatches(minibatch_size):
                last = self.update(mb)
        return last

    def get_weights(self):
        return self.module.get_weights()

    def set_weights(self, weights) -> None:
        self.module.set_weights(weights)
        # Optimizer state refers to the old param tree only by structure;
        # moments keep their values (intended for weight broadcast where
        # structure is unchanged).

    def get_state(self) -> dict:
        return {
            "params": jax.tree.map(np.asarray, self.module.params),
            "opt_state": jax.tree.map(
                lambda x: np.asarray(x) if isinstance(x, jax.Array) else x,
                self.opt_state,
            ),
        }

    def set_state(self, state: dict) -> None:
        self.module.set_weights(state["params"])
        self.opt_state = jax.tree.map(
            lambda ref, x: jnp.asarray(x) if isinstance(ref, jax.Array) else x,
            self.opt_state,
            state["opt_state"],
        )


class LearnerGroup:
    """Local learner or remote learner actors (reference:
    rllib/core/learner/learner_group.py:72 — update :194).

    With num_learners == 0 the learner lives in the driver process (the
    common TPU mode: the driver owns the chips). With N > 0, N actors each
    update on a batch shard and the group averages the resulting weights
    (host-level DP over DCN)."""

    def __init__(self, learner_factory: Callable[[], JaxLearner], num_learners: int = 0):
        import ray_tpu

        self.num_learners = num_learners
        if num_learners == 0:
            self.local = learner_factory()
            self.remotes = []
        else:
            self.local = None
            actor_cls = ray_tpu.remote(num_cpus=1)(_LearnerActor)
            self.remotes = [actor_cls.remote(learner_factory) for _ in range(num_learners)]

    def update_epochs(self, batch: SampleBatch, **kw) -> dict:
        import ray_tpu

        if self.local is not None:
            return self.local.update_epochs(batch, **kw)
        n = self.num_learners
        if len(batch) < n:
            # Too few rows to shard: replicate (identical updates beat
            # empty shards whose mean() would be NaN).
            refs = [r.update_epochs.remote(batch, **kw) for r in self.remotes]
        else:
            # np.array_split-style bounds: remainder rows spread over the
            # first shards, nothing dropped.
            bounds = np.linspace(0, len(batch), n + 1, dtype=int)
            refs = [
                r.update_epochs.remote(batch.slice(int(bounds[i]), int(bounds[i + 1])), **kw)
                for i, r in enumerate(self.remotes)
            ]
        metrics = ray_tpu.get(refs)
        self._average_weights()
        return metrics[0]

    def _average_weights(self) -> None:
        import ray_tpu

        all_w = ray_tpu.get([r.get_weights.remote() for r in self.remotes])
        avg = jax.tree.map(lambda *xs: np.mean(np.stack(xs), axis=0), *all_w)
        ray_tpu.get([r.set_weights.remote(avg) for r in self.remotes])

    def get_weights(self):
        import ray_tpu

        if self.local is not None:
            return self.local.get_weights()
        return ray_tpu.get(self.remotes[0].get_weights.remote())

    def set_weights(self, weights) -> None:
        import ray_tpu

        if self.local is not None:
            self.local.set_weights(weights)
        else:
            ray_tpu.get([r.set_weights.remote(weights) for r in self.remotes])

    def get_state(self) -> dict:
        import ray_tpu

        if self.local is not None:
            return self.local.get_state()
        return ray_tpu.get(self.remotes[0].get_state.remote())

    def set_state(self, state: dict) -> None:
        import ray_tpu

        if self.local is not None:
            self.local.set_state(state)
        else:
            ray_tpu.get([r.set_state.remote(state) for r in self.remotes])

    def stop(self) -> None:
        import ray_tpu

        for r in self.remotes:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


class _LearnerActor:
    """Actor wrapper so a JaxLearner can live in a worker process."""

    def __init__(self, factory: Callable[[], JaxLearner]):
        self.learner = factory()

    def update_epochs(self, batch, **kw):
        return self.learner.update_epochs(batch, **kw)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, s):
        self.learner.set_state(s)
