"""RLModule: the framework-agnostic policy-network container, JAX edition.

Counterpart of the reference's RLModule (rllib/core/rl_module/rl_module.py:260
— forward_inference/forward_exploration/forward_train over a spec) rebuilt on
flax: parameters are an explicit pytree (no module-owned mutable state), so
the same apply function serves the env runner (host CPU / single chip) and
the learner (sharded mesh) — weight sync is just shipping the pytree.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class RLModuleSpec:
    """Reference: rllib/core/rl_module/rl_module.py RLModuleSpec."""

    observation_dim: int
    action_dim: int
    hidden: Sequence[int] = (64, 64)
    module_class: "type[RLModule] | None" = None

    def build(self, seed: int = 0) -> "RLModule":
        cls = self.module_class or DiscreteActorCriticModule
        return cls(self, seed)


class RLModule:
    """Holds a param pytree + pure apply fns. Subclasses define the net."""

    def __init__(self, spec: RLModuleSpec, seed: int = 0):
        self.spec = spec
        self.params = self.init_params(jax.random.PRNGKey(seed))
        self._jit_inference = jax.jit(self.apply)

    # --- subclass surface (pure functions of (params, obs)) ---

    def init_params(self, rng) -> Any:
        raise NotImplementedError

    def apply(self, params, obs) -> dict:
        """Returns at least {"action_dist_inputs": logits, "vf_preds": v}."""
        raise NotImplementedError

    # --- shared ---

    def forward_inference(self, obs: np.ndarray) -> dict:
        out = self._jit_inference(self.params, jnp.asarray(obs))
        return {k: np.asarray(v) for k, v in out.items()}

    forward_exploration = forward_inference

    def get_weights(self) -> Any:
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, weights)


def _mlp_init(rng, sizes: Sequence[int]):
    params = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, k = jax.random.split(rng)
        # Orthogonal init, standard for PPO stability.
        w = jax.nn.initializers.orthogonal(scale=np.sqrt(2))(k, (m, n), jnp.float32)
        params.append({"w": w, "b": jnp.zeros((n,), jnp.float32)})
    return params


def _mlp_apply(layers, x, activate_last: bool = False):
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1 or activate_last:
            x = jnp.tanh(x)
    return x


class DiscreteActorCriticModule(RLModule):
    """Shared-torso MLP with categorical policy + value heads
    (reference analogue: rllib default MLP catalog for PPO)."""

    def init_params(self, rng) -> Any:
        s = self.spec
        k1, k2, k3 = jax.random.split(rng, 3)
        torso_sizes = [s.observation_dim, *s.hidden]
        pi_head = _mlp_init(k2, [s.hidden[-1], s.action_dim])
        vf_head = _mlp_init(k3, [s.hidden[-1], 1])
        # Small final policy layer → near-uniform initial policy.
        pi_head[-1]["w"] = pi_head[-1]["w"] * 0.01
        return {
            "torso": _mlp_init(k1, torso_sizes),
            "pi": pi_head,
            "vf": vf_head,
        }

    def apply(self, params, obs) -> dict:
        h = _mlp_apply(params["torso"], obs, activate_last=True)
        logits = _mlp_apply(params["pi"], h)
        value = _mlp_apply(params["vf"], h)[..., 0]
        return {"action_dist_inputs": logits, "vf_preds": value}


def categorical_logp(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]


def categorical_entropy(logits: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def sample_categorical(rng_key, logits: jnp.ndarray) -> jnp.ndarray:
    return jax.random.categorical(rng_key, logits, axis=-1)
