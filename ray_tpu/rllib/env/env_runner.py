"""EnvRunners: CPU actors that step vectorized envs with the current policy.

Counterpart of the reference's SingleAgentEnvRunner
(rllib/env/single_agent_env_runner.py:68) and EnvRunnerGroup
(rllib/env/env_runner_group.py:71 — remote actors, foreach/async fanout).
Redesign notes: env stepping stays host-side numpy; policy inference is one
jitted batched forward per vector step (the TPU/XLA-friendly shape — no
per-env Python forward). Episode bookkeeping uses SAME_STEP autoreset
semantics implemented locally so value bootstrapping is exact for
truncations and version-stable across gymnasium releases."""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    BEHAVIOR_LOGITS,
    LOGP,
    NEXT_OBS,
    OBS,
    REWARDS,
    TERMINATEDS,
    TRUNCATEDS,
    VF_PREDS,
    SampleBatch,
)


class _SyncVectorEnv:
    """N single envs stepped together with immediate (same-step) reset.

    On done, the returned obs is the NEXT episode's initial observation and
    the terminal observation is kept in `final_obs` for bootstrapping."""

    def __init__(self, env_fns: list[Callable[[], Any]], seed: int = 0):
        self.envs = [fn() for fn in env_fns]
        self.n = len(self.envs)
        self._seed = seed

    def reset(self) -> np.ndarray:
        obs = [e.reset(seed=self._seed + i)[0] for i, e in enumerate(self.envs)]
        return np.stack(obs).astype(np.float32)

    def step(self, actions: np.ndarray):
        obs_out, rewards, terms, truncs, final_obs = [], [], [], [], [None] * self.n
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            obs, r, term, trunc, _ = env.step(a)
            if term or trunc:
                final_obs[i] = np.asarray(obs, np.float32)
                obs = env.reset()[0]
            obs_out.append(obs)
            rewards.append(r)
            terms.append(term)
            truncs.append(trunc)
        return (
            np.stack(obs_out).astype(np.float32),
            np.asarray(rewards, np.float32),
            np.asarray(terms, bool),
            np.asarray(truncs, bool),
            final_obs,
        )

    def close(self):
        for e in self.envs:
            try:
                e.close()
            except Exception:
                pass


def _make_env_fn(env: Any) -> Callable[[], Any]:
    if callable(env):
        return env
    if isinstance(env, str):
        import gymnasium

        return lambda: gymnasium.make(env)
    raise TypeError(f"env must be a gym id or callable, got {type(env)}")


class SingleAgentEnvRunner:
    """Samples fixed-length rollouts (reference:
    rllib/env/single_agent_env_runner.py:68 sample())."""

    def __init__(self, config: "AlgorithmConfig", seed: int = 0):  # noqa: F821
        self.config = config
        self.num_envs = config.num_envs_per_env_runner
        self.rollout_len = config.rollout_fragment_length
        self.vec = _SyncVectorEnv(
            [_make_env_fn(config.env) for _ in range(self.num_envs)], seed=seed
        )
        self.module = config.rl_module_spec().build(seed=seed)
        # env-to-module connector pipeline (reference: EnvRunner applying
        # the env_to_module connector before the RLModule forward;
        # transformed observations are what lands in the sample batch).
        from ray_tpu.rllib.connectors import build_pipeline

        self._obs_pipe = build_pipeline(
            getattr(config, "env_to_module_connector", None))
        self.obs = self._connect(self.vec.reset())
        self._rng = np.random.default_rng(seed)
        # Per-env running episode stats.
        self._ep_return = np.zeros(self.num_envs, np.float64)
        self._ep_len = np.zeros(self.num_envs, np.int64)
        self._completed_returns: list[float] = []
        self._completed_lengths: list[int] = []

    # ------------------------------------------------------------------

    def _connect(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        if self._obs_pipe is None:
            return obs
        return np.asarray(self._obs_pipe(obs, update=update))

    def _connected_next(self, next_obs, final_obs):
        """(t_next, next_for_value): next obs through the connector, with
        terminal rows substituted by their true final observation run
        through the pipeline WITHOUT updating running stats (their values
        are only bootstrapped, never acted on)."""
        t_next = self._connect(next_obs)
        next_for_value = t_next.copy()
        done_idx = [i for i, fo in enumerate(final_obs) if fo is not None]
        if done_idx:
            finals = self._connect(
                np.stack([final_obs[i] for i in done_idx]), update=False)
            for j, i in enumerate(done_idx):
                next_for_value[i] = finals[j]
        return t_next, next_for_value

    def set_weights(self, weights) -> None:
        self.module.set_weights(weights)

    def get_weights(self):
        return self.module.get_weights()

    def get_connector_state(self):
        return self._obs_pipe.get_state() if self._obs_pipe else None

    def set_connector_state(self, state) -> None:
        if self._obs_pipe is not None and state is not None:
            self._obs_pipe.set_state(state)

    def sample(self, weights=None) -> SampleBatch:
        """One rollout of [T, B] transitions, flattened to [T*B] with GAE
        inputs attached (vf_preds, bootstrap via next_obs values)."""
        if weights is not None:
            self.module.set_weights(weights)
        # Modules owning their exploration (epsilon-greedy DQN, squashed
        # gaussian SAC, any continuous policy) take the generic path: the
        # module decides actions/extras, the runner just steps envs.
        if hasattr(self.module, "explore_actions"):
            return self._sample_generic()
        T, B = self.rollout_len, self.num_envs
        obs_buf = np.empty((T, B) + self.obs.shape[1:], np.float32)
        act_buf = np.empty((T, B), np.int64)
        logp_buf = np.empty((T, B), np.float32)
        vf_buf = np.empty((T, B), np.float32)
        logits_buf: np.ndarray | None = None
        rew_buf = np.empty((T, B), np.float32)
        term_buf = np.empty((T, B), bool)
        trunc_buf = np.empty((T, B), bool)
        next_obs_buf = np.empty_like(obs_buf)

        for t in range(T):
            out = self.module.forward_exploration(self.obs)
            logits = out["action_dist_inputs"]
            if logits_buf is None:
                logits_buf = np.empty((T, B, logits.shape[-1]), np.float32)
            actions, logp = gumbel_sample_logits(logits, self._rng)
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = logp
            vf_buf[t] = out[VF_PREDS]
            logits_buf[t] = logits
            next_obs, rewards, terms, truncs, final_obs = self.vec.step(actions)
            # Bootstrapping for truncated (time-limit) episodes uses the
            # true terminal observation, not the post-reset one.
            t_next, next_for_value = self._connected_next(next_obs, final_obs)
            rew_buf[t], term_buf[t], trunc_buf[t] = rewards, terms, truncs
            next_obs_buf[t] = next_for_value
            self._track_episodes(rewards, terms, truncs)
            self.obs = t_next

        flat = lambda a: a.reshape((T * B,) + a.shape[2:])  # noqa: E731
        return SampleBatch(
            {
                OBS: flat(obs_buf),
                ACTIONS: flat(act_buf),
                LOGP: flat(logp_buf),
                VF_PREDS: flat(vf_buf),
                BEHAVIOR_LOGITS: flat(logits_buf),
                REWARDS: flat(rew_buf),
                TERMINATEDS: flat(term_buf),
                TRUNCATEDS: flat(trunc_buf),
                NEXT_OBS: flat(next_obs_buf),
                "t": np.tile(np.arange(T)[:, None], (1, B)).reshape(-1),
                "env_id": np.tile(np.arange(B)[None, :], (T, 1)).reshape(-1),
            }
        )

    def _sample_generic(self) -> SampleBatch:
        """Rollout driven by module.explore_actions(obs, rng) ->
        (actions, extras). Collects the off-policy transition tuple
        (obs, action, reward, terminated, truncated, next_obs) plus any
        per-step extras the module returns (e.g. logp)."""
        T, B = self.rollout_len, self.num_envs
        obs_buf = np.empty((T, B) + self.obs.shape[1:], np.float32)
        next_obs_buf = np.empty_like(obs_buf)
        rew_buf = np.empty((T, B), np.float32)
        term_buf = np.empty((T, B), bool)
        trunc_buf = np.empty((T, B), bool)
        act_buf = None
        extra_bufs: dict[str, np.ndarray] = {}

        for t in range(T):
            actions, extras = self.module.explore_actions(self.obs, self._rng)
            actions = np.asarray(actions)
            if act_buf is None:
                act_buf = np.empty((T,) + actions.shape, actions.dtype)
            act_buf[t] = actions
            for k, v in (extras or {}).items():
                v = np.asarray(v)
                if k not in extra_bufs:
                    extra_bufs[k] = np.empty((T,) + v.shape, v.dtype)
                extra_bufs[k][t] = v
            next_obs, rewards, terms, truncs, final_obs = self.vec.step(actions)
            t_next, next_for_value = self._connected_next(next_obs, final_obs)
            obs_buf[t] = self.obs
            rew_buf[t], term_buf[t], trunc_buf[t] = rewards, terms, truncs
            next_obs_buf[t] = next_for_value
            self._track_episodes(rewards, terms, truncs)
            self.obs = t_next

        flat = lambda a: a.reshape((T * B,) + a.shape[2:])  # noqa: E731
        out = SampleBatch({
            OBS: flat(obs_buf),
            ACTIONS: flat(act_buf),
            REWARDS: flat(rew_buf),
            TERMINATEDS: flat(term_buf),
            TRUNCATEDS: flat(trunc_buf),
            NEXT_OBS: flat(next_obs_buf),
        })
        for k, buf in extra_bufs.items():
            out[k] = flat(buf)
        return out

    def _track_episodes(self, rewards, terms, truncs) -> None:
        self._ep_return += rewards
        self._ep_len += 1
        done = terms | truncs
        for i in np.nonzero(done)[0]:
            self._completed_returns.append(float(self._ep_return[i]))
            self._completed_lengths.append(int(self._ep_len[i]))
            self._ep_return[i] = 0.0
            self._ep_len[i] = 0

    def get_metrics(self) -> dict:
        """Drain episode stats (reference: env runner metrics logger)."""
        rets, lens = self._completed_returns, self._completed_lengths
        self._completed_returns, self._completed_lengths = [], []
        return summarize_episodes(rets, lens)

    def stop(self) -> None:
        self.vec.close()


def summarize_episodes(returns: list[float], lengths: list[int]) -> dict:
    if not returns:
        return {"num_episodes": 0}
    return {
        "num_episodes": len(returns),
        "episode_return_mean": float(np.mean(returns)),
        "episode_return_max": float(np.max(returns)),
        "episode_return_min": float(np.min(returns)),
        "episode_len_mean": float(np.mean(lengths)),
    }


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


def gumbel_sample_logits(
    logits: np.ndarray, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray]:
    """Sample categorical actions host-side via gumbel-max (avoids device
    rng state) and return (actions, logp_of_actions)."""
    g = rng.gumbel(size=logits.shape).astype(np.float32)
    actions = np.argmax(logits + g, axis=-1)
    logp_all = logits - _logsumexp(logits)
    return actions, np.take_along_axis(logp_all, actions[..., None], -1)[..., 0]


def merge_episode_metrics(per: list[dict]) -> dict:
    """Episode-count-weighted merge of per-runner summarize_episodes dicts."""
    merged: dict = {"num_episodes": sum(m.get("num_episodes", 0) for m in per)}
    with_eps = [m for m in per if "episode_return_mean" in m]
    if with_eps:
        w = [m["num_episodes"] for m in with_eps]
        merged["episode_return_mean"] = float(
            np.average([m["episode_return_mean"] for m in with_eps], weights=w)
        )
        merged["episode_return_max"] = max(m["episode_return_max"] for m in with_eps)
        merged["episode_return_min"] = min(m["episode_return_min"] for m in with_eps)
        merged["episode_len_mean"] = float(
            np.average([m["episode_len_mean"] for m in with_eps], weights=w)
        )
    return merged


class EnvRunnerGroup:
    """Remote env-runner actors + local fallback (reference:
    rllib/env/env_runner_group.py:71). Subclasses swap ``runner_cls``
    (multi-agent group) without re-implementing the fan-out."""

    runner_cls: type = None  # set below (class defined later in this file)

    def __init__(self, config: "AlgorithmConfig"):  # noqa: F821
        import ray_tpu

        self.config = config
        self.num_remote = config.num_env_runners
        if self.num_remote == 0:
            self.local_runner = self.runner_cls(config, seed=config.seed)
            self.remote_runners = []
        else:
            self.local_runner = None
            cls = ray_tpu.remote(num_cpus=config.num_cpus_per_env_runner)(
                self.runner_cls
            )
            self.remote_runners = [
                cls.remote(config, seed=config.seed + 1000 * (i + 1))
                for i in range(self.num_remote)
            ]

    def sample(self, weights=None) -> SampleBatch:
        return SampleBatch.concat_samples(self.sample_batches(weights))

    def sample_batches(self, weights=None) -> list[SampleBatch]:
        """Per-runner batches. Each keeps its own [T*B] t-major layout, so
        time-structured postprocessing (GAE/vtrace) must happen per batch
        BEFORE concatenation."""
        import ray_tpu

        if self.local_runner is not None:
            return [self.local_runner.sample(weights)]
        ref = ray_tpu.put(weights) if weights is not None else None
        return ray_tpu.get([r.sample.remote(ref) for r in self.remote_runners])

    def sample_async(self, weights=None) -> list:
        """Kick off sampling on every remote runner; returns refs
        (reference: foreach_env_runner_async — the IMPALA path)."""
        import ray_tpu

        ref = ray_tpu.put(weights) if weights is not None else None
        return [(r, r.sample.remote(ref)) for r in self.remote_runners]

    def get_metrics(self) -> dict:
        import ray_tpu

        if self.local_runner is not None:
            per = [self.local_runner.get_metrics()]
        else:
            per = ray_tpu.get([r.get_metrics.remote() for r in self.remote_runners])
        return merge_episode_metrics(per)

    def get_connector_state(self):
        """Merged pipeline state across ALL runners (reference:
        MeanStdFilter sync semantics): gather every runner's state,
        combine stateful connectors (Chan's parallel combine for running
        normalizers), and broadcast the merged stats back so each runner
        keeps normalizing with the shared statistics."""
        import ray_tpu

        from ray_tpu.rllib.connectors import merge_pipeline_states

        if self.local_runner is not None:
            return self.local_runner.get_connector_state()
        if not self.remote_runners:
            return None
        states = ray_tpu.get([r.get_connector_state.remote()
                              for r in self.remote_runners])
        merged, mergeable = merge_pipeline_states(states)
        if merged is not None:
            # Gathering HARVESTED each runner's delta, so the merged
            # result must go back even with one runner or the samples
            # would vanish from every future merge. Broadcast ONLY
            # genuinely merged positions; unmergeable (unknown-kind)
            # connector state stays per-runner — a None entry is skipped
            # by ConnectorPipelineV2.set_state.
            broadcast = [m if ok else None
                         for m, ok in zip(merged, mergeable)]
            if any(b is not None for b in broadcast):
                ray_tpu.get([r.set_connector_state.remote(broadcast)
                             for r in self.remote_runners])
        return merged

    def sync_connector_states(self) -> None:
        """Periodic cross-runner stats sync (called by Algorithm.step);
        no-op with a local runner or no stateful connectors."""
        if self.local_runner is None and len(self.remote_runners) > 1:
            self.get_connector_state()

    def set_connector_state(self, state) -> None:
        """Seed every runner's pipeline (restore path)."""
        import ray_tpu

        if state is None:
            return
        if self.local_runner is not None:
            self.local_runner.set_connector_state(state)
        else:
            ray_tpu.get([r.set_connector_state.remote(state)
                         for r in self.remote_runners])

    def stop(self) -> None:
        import ray_tpu

        if self.local_runner is not None:
            self.local_runner.stop()
        for r in self.remote_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


EnvRunnerGroup.runner_cls = SingleAgentEnvRunner
