"""Multi-agent RL: env API, env runner, and learner fan-out.

Counterpart of the reference's MultiAgentEnv (rllib/env/multi_agent_env.py),
MultiAgentEnvRunner (rllib/env/multi_agent_env_runner.py) and the
multi-module paths of Learner/LearnerGroup (rllib/core/learner/learner.py
operates on a MultiRLModule keyed by ModuleID). Redesign notes:

- Policies are plain RLModules keyed by module id; mapping from agent id to
  module id is ``policy_mapping_fn(agent_id, env_index)`` exactly as in the
  reference (AlgorithmConfig.multi_agent, algorithm_config.py).
- Env stepping stays host-side numpy. Per vector step the runner batches
  every (env, agent) observation routed to the same module into ONE forward
  call, so policy inference remains a handful of jitted batched calls per
  step regardless of agent count — the XLA-friendly shape.
- Trajectories are collected per (env, agent) and emitted as *fragments*:
  contiguous-time SampleBatches with per-step NEXT_OBS, so GAE runs
  per-fragment with exact bootstrapping (same math as the single-agent
  [T, B] path with B=1).
- Turn-based envs are supported: an agent whose action produced no
  immediate next observation keeps its transition open, accumulating any
  rewards credited to it, until it observes again or the episode ends
  (reference: AgentCollector semantics in env_runner_v2).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rllib.env.env_runner import (
    EnvRunnerGroup,
    gumbel_sample_logits,
    summarize_episodes,
)
from ray_tpu.rllib.sample_batch import (
    ACTIONS,
    LOGP,
    NEXT_OBS,
    OBS,
    REWARDS,
    TERMINATEDS,
    TRUNCATEDS,
    VF_PREDS,
    SampleBatch,
)

DEFAULT_MODULE_ID = "default_policy"


def shared_policy_mapping_fn(agent_id, env_index=0, **kw) -> str:
    """Every agent maps to one shared module (reference default)."""
    return DEFAULT_MODULE_ID


class MultiAgentEnv:
    """Dict-in/dict-out env (reference: rllib/env/multi_agent_env.py).

    Subclasses define:
      - ``possible_agents``: list of all agent ids that may ever appear.
      - ``observation_dims`` / ``action_dims``: dicts agent_id -> int
        (flat obs dim / discrete action count). Gym spaces are optional.
      - ``reset(seed=None) -> (obs_dict, info_dict)``
      - ``step(action_dict) -> (obs, rewards, terminateds, truncateds,
        infos)`` where ``terminateds``/``truncateds`` carry the special
        ``"__all__"`` key ending the episode for everyone.

    Only agents present in ``obs`` act next step; rewards may be credited
    to any agent (turn-based games pay the previous mover).
    """

    possible_agents: list = []
    observation_dims: dict = {}
    action_dims: dict = {}

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def close(self) -> None:
        pass


class _OpenTransition:
    __slots__ = ("obs", "action", "logp", "vf", "reward")

    def __init__(self, obs, action, logp, vf):
        self.obs = obs
        self.action = action
        self.logp = logp
        self.vf = vf
        self.reward = 0.0


class _AgentTrajectory:
    """Per-(env, agent) fragment under construction."""

    __slots__ = ("rows", "open")

    def __init__(self):
        self.rows: list[tuple] = []  # (obs, act, logp, vf, rew, term, trunc, next_obs)
        self.open: _OpenTransition | None = None

    def close_open(self, next_obs, terminated: bool, truncated: bool) -> None:
        tr = self.open
        if tr is None:
            return
        self.rows.append((tr.obs, tr.action, tr.logp, tr.vf, tr.reward,
                          terminated, truncated, next_obs))
        self.open = None

    def pop_fragment(self) -> SampleBatch | None:
        if not self.rows:
            return None
        cols = list(zip(*self.rows))
        batch = SampleBatch({
            OBS: np.stack(cols[0]).astype(np.float32),
            ACTIONS: np.asarray(cols[1], np.int64),
            LOGP: np.asarray(cols[2], np.float32),
            VF_PREDS: np.asarray(cols[3], np.float32),
            REWARDS: np.asarray(cols[4], np.float32),
            TERMINATEDS: np.asarray(cols[5], bool),
            TRUNCATEDS: np.asarray(cols[6], bool),
            NEXT_OBS: np.stack(cols[7]).astype(np.float32),
        })
        self.rows = []
        return batch


class MultiAgentEnvRunner:
    """Steps N multi-agent envs, routing agents to modules via the policy
    mapping fn (reference: rllib/env/multi_agent_env_runner.py).

    ``sample()`` returns ``{module_id: [fragment SampleBatch, ...]}``; each
    fragment is contiguous in time for one (env, agent) pair.
    """

    def __init__(self, config: "AlgorithmConfig", seed: int = 0):  # noqa: F821
        self.config = config
        self.num_envs = config.num_envs_per_env_runner
        self.rollout_len = config.rollout_fragment_length
        env_fn = config.env if callable(config.env) else None
        if env_fn is None:
            raise TypeError("multi-agent env must be a callable returning MultiAgentEnv")
        if getattr(config, "env_to_module_connector", None) is not None:
            raise NotImplementedError(
                "env_to_module_connector is not yet supported by the "
                "multi-agent runner (per-module pipelines pending); "
                "preprocess observations in the env"
            )
        self.envs = [env_fn() for _ in range(self.num_envs)]
        self.mapping_fn: Callable = config.policy_mapping_fn
        specs = config.rl_module_specs()
        self.modules = {mid: spec.build(seed=seed + i)
                        for i, (mid, spec) in enumerate(specs.items())}
        self._rng = np.random.default_rng(seed)
        # Live episode state per env.
        self.cur_obs: list[dict] = [
            env.reset(seed=seed + i)[0] for i, env in enumerate(self.envs)
        ]
        self.traj: list[dict[Any, _AgentTrajectory]] = [
            {} for _ in range(self.num_envs)
        ]
        self._agent_to_module: list[dict] = [{} for _ in range(self.num_envs)]
        self._ep_return = np.zeros(self.num_envs, np.float64)
        self._ep_len = np.zeros(self.num_envs, np.int64)
        self._completed_returns: list[float] = []
        self._completed_lengths: list[int] = []

    # ------------------------------------------------------------------

    def _module_for(self, env_i: int, agent_id) -> str:
        cache = self._agent_to_module[env_i]
        if agent_id not in cache:
            mid = self.mapping_fn(agent_id, env_i)
            if mid not in self.modules:
                raise ValueError(
                    f"policy_mapping_fn returned {mid!r} for agent "
                    f"{agent_id!r}, which is not a configured module id "
                    f"{sorted(self.modules)}"
                )
            cache[agent_id] = mid
        return cache[agent_id]

    def set_weights(self, weights: dict) -> None:
        for mid, w in weights.items():
            if mid in self.modules:
                self.modules[mid].set_weights(w)

    def get_weights(self) -> dict:
        return {mid: m.get_weights() for mid, m in self.modules.items()}

    def get_connector_state(self):
        # Connector pipelines are not yet supported multi-agent (rejected
        # in __init__); the checkpoint path still probes via the shared
        # EnvRunnerGroup surface.
        return None

    def set_connector_state(self, state) -> None:
        pass

    def sample(self, weights: dict | None = None) -> dict[str, list[SampleBatch]]:
        if weights is not None:
            self.set_weights(weights)
        out: dict[str, list[SampleBatch]] = {mid: [] for mid in self.modules}

        for _ in range(self.rollout_len):
            # 1. Batch all acting (env, agent) pairs by module: one jitted
            #    forward per module per vector step.
            per_module: dict[str, list[tuple[int, Any]]] = {}
            for env_i, obs_dict in enumerate(self.cur_obs):
                for agent_id in obs_dict:
                    per_module.setdefault(
                        self._module_for(env_i, agent_id), []
                    ).append((env_i, agent_id))
            actions_by_env: list[dict] = [{} for _ in range(self.num_envs)]
            for mid, pairs in per_module.items():
                obs_mat = np.stack([
                    np.asarray(self.cur_obs[e][a], np.float32).reshape(-1)
                    for e, a in pairs
                ])
                fwd = self.modules[mid].forward_exploration(obs_mat)
                logits = fwd["action_dist_inputs"]
                acts, logp = gumbel_sample_logits(logits, self._rng)
                vf = fwd.get(VF_PREDS, np.zeros(len(pairs), np.float32))
                for j, (e, a) in enumerate(pairs):
                    actions_by_env[e][a] = int(acts[j])
                    t = self.traj[e].setdefault(a, _AgentTrajectory())
                    # A still-open transition means this agent acted before
                    # without observing since (cannot happen: observing is
                    # the precondition to act) — close defensively.
                    t.close_open(obs_mat[j], False, False)
                    t.open = _OpenTransition(
                        np.asarray(self.cur_obs[e][a], np.float32).reshape(-1),
                        int(acts[j]), float(logp[j]), float(vf[j]),
                    )

            # 2. Step every env.
            for env_i, env in enumerate(self.envs):
                obs, rew, term, trunc, _ = env.step(actions_by_env[env_i])
                done = bool(term.get("__all__", False)) or bool(
                    trunc.get("__all__", False)
                )
                self._ep_return[env_i] += float(sum(rew.values()))
                self._ep_len[env_i] += 1
                trajs = self.traj[env_i]
                # Credit rewards to whichever open transition earned them.
                for agent_id, r in rew.items():
                    t = trajs.get(agent_id)
                    if t is not None and t.open is not None:
                        t.open.reward += float(r)
                ended_all = done
                for agent_id, t in trajs.items():
                    if t.open is None:
                        continue
                    a_term = bool(term.get(agent_id, False)) or bool(
                        term.get("__all__", False)
                    )
                    a_trunc = bool(trunc.get(agent_id, False)) or bool(
                        trunc.get("__all__", False)
                    )
                    if agent_id in obs and not (a_term or a_trunc):
                        t.close_open(
                            np.asarray(obs[agent_id], np.float32).reshape(-1),
                            False, False,
                        )
                    elif a_term or a_trunc or ended_all:
                        last = obs.get(agent_id, t.open.obs)
                        t.close_open(
                            np.asarray(last, np.float32).reshape(-1),
                            a_term or (ended_all and not a_trunc), a_trunc,
                        )
                    # else: agent did not observe, episode continues —
                    # transition stays open accumulating rewards.
                if done:
                    for agent_id, t in trajs.items():
                        frag = t.pop_fragment()
                        if frag is not None:
                            out[self._module_for(env_i, agent_id)].append(frag)
                    self._completed_returns.append(float(self._ep_return[env_i]))
                    self._completed_lengths.append(int(self._ep_len[env_i]))
                    self._ep_return[env_i] = 0.0
                    self._ep_len[env_i] = 0
                    self.traj[env_i] = {}
                    self._agent_to_module[env_i] = {}
                    obs = env.reset()[0]
                else:
                    # Per-agent done while the episode continues: the env
                    # may include the dead agent's FINAL observation in
                    # obs (reference convention); it must not act again.
                    obs = {
                        a: o for a, o in obs.items()
                        if not (bool(term.get(a, False)) or bool(trunc.get(a, False)))
                    }
                self.cur_obs[env_i] = obs

        # 3. Rollout boundary: flush fragments, truncating open transitions
        #    (their own next obs is unknown yet; GAE bootstraps from the
        #    transition's recorded next_obs with the lambda-chain cut).
        for env_i, trajs in enumerate(self.traj):
            for agent_id, t in trajs.items():
                if t.open is not None:
                    nxt = self.cur_obs[env_i].get(agent_id, t.open.obs)
                    t.close_open(
                        np.asarray(nxt, np.float32).reshape(-1), False, True
                    )
                frag = t.pop_fragment()
                if frag is not None:
                    out[self._module_for(env_i, agent_id)].append(frag)
        return out

    def get_metrics(self) -> dict:
        rets, lens = self._completed_returns, self._completed_lengths
        self._completed_returns, self._completed_lengths = [], []
        return summarize_episodes(rets, lens)

    def stop(self) -> None:
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass


class MultiAgentEnvRunnerGroup(EnvRunnerGroup):
    """Remote multi-agent runner fan-out (reference: EnvRunnerGroup with
    MultiAgentEnvRunner workers). Inherits construction, metrics merge and
    teardown; multi-agent sampling returns per-module fragment lists, so
    the single-agent sample()/sample_batches() surface is replaced."""

    runner_cls = MultiAgentEnvRunner

    def sample_fragments(self, weights=None) -> dict[str, list[SampleBatch]]:
        import ray_tpu

        if self.local_runner is not None:
            results = [self.local_runner.sample(weights)]
        else:
            ref = ray_tpu.put(weights) if weights is not None else None
            results = ray_tpu.get(
                [r.sample.remote(ref) for r in self.remote_runners]
            )
        merged: dict[str, list[SampleBatch]] = {}
        for res in results:
            for mid, frags in res.items():
                merged.setdefault(mid, []).extend(frags)
        return merged

    def sample(self, weights=None):
        raise NotImplementedError(
            "multi-agent groups produce per-module fragments; "
            "use sample_fragments()"
        )

    sample_batches = sample
    sample_async = sample


class MultiAgentLearnerGroup:
    """One JaxLearner per module id (reference: Learner over MultiRLModule,
    learner.py — per-module optimizers, ``policies_to_train`` filter)."""

    def __init__(self, learner_factories: dict[str, Callable],
                 policies_to_train: Optional[list[str]] = None):
        self.learners = {mid: f() for mid, f in learner_factories.items()}
        self.policies_to_train = (
            set(policies_to_train) if policies_to_train is not None
            else set(self.learners)
        )

    def update_epochs(self, batches: dict[str, SampleBatch], **kw) -> dict:
        metrics: dict = {}
        for mid, batch in batches.items():
            if mid not in self.learners or mid not in self.policies_to_train:
                continue
            # A module's share of the sampled rows can undershoot the
            # configured minibatch size (many policies / short fragments);
            # shrink so every module still takes gradient steps instead of
            # silently skipping (SampleBatch.minibatches drops remainders).
            # The shrunken size is bucketed to a power of two so the jitted
            # update sees a bounded set of shapes across iterations.
            module_kw = kw
            if "minibatch_size" in kw and len(batch) < kw["minibatch_size"]:
                bucket = 1 << (max(len(batch), 1).bit_length() - 1)
                module_kw = {**kw, "minibatch_size": bucket}
            m = self.learners[mid].update_epochs(batch, **module_kw)
            metrics[mid] = m
        # Flat aggregates for schedulers/loggers expecting scalars.
        per_module = dict(metrics)
        if per_module:
            keys = {k for m in per_module.values() for k in m}
            for k in keys:
                vals = [m[k] for m in per_module.values() if k in m]
                if vals:
                    metrics[k] = float(np.mean(vals))
        return metrics

    def get_weights(self) -> dict:
        return {mid: l.get_weights() for mid, l in self.learners.items()}

    def set_weights(self, weights: dict) -> None:
        for mid, w in weights.items():
            if mid in self.learners:
                self.learners[mid].set_weights(w)

    def get_state(self) -> dict:
        return {mid: l.get_state() for mid, l in self.learners.items()}

    def set_state(self, state: dict) -> None:
        for mid, s in state.items():
            if mid in self.learners:
                self.learners[mid].set_state(s)

    def stop(self) -> None:
        pass
