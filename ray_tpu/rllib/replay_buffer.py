"""Replay buffers for off-policy algorithms (DQN/SAC).

Counterpart of the reference's replay buffer stack
(rllib/utils/replay_buffers/ — EpisodeReplayBuffer and the
MultiAgentReplayBuffer used by DQN/SAC). TPU-reframed: storage is flat
preallocated numpy rings on the host (replay never touches the chip);
sampled minibatches are handed to the jitted learner step as one batched
device_put.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO ring over column arrays, preallocated on first add."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:], v.dtype)
        if n > self.capacity:
            batch = batch.slice(n - self.capacity, n)
            n = self.capacity
        idx = (self._next + np.arange(n)) % self.capacity
        for k, col in self._cols.items():
            col[idx] = np.asarray(batch[k])
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)

    def sample(self, num_items: int) -> SampleBatch:
        if self._size == 0:
            raise ValueError("cannot sample from an empty replay buffer")
        idx = self._rng.integers(0, self._size, size=num_items)
        return SampleBatch({k: col[idx] for k, col in self._cols.items()})

    def state(self) -> dict:
        return {
            "cols": {k: v[: self._size].copy() for k, v in self._cols.items()},
            "next": self._next, "size": self._size,
        }
