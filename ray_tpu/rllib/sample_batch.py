"""SampleBatch: columnar rollout storage.

Counterpart of the reference's rllib/policy/sample_batch.py SampleBatch
(dict of parallel arrays keyed by OBS/ACTIONS/REWARDS/...) — kept numpy
host-side; converted to jax arrays only at the learner's jit boundary."""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
NEXT_OBS = "next_obs"
BEHAVIOR_LOGITS = "behavior_logits"


class SampleBatch(dict):
    """dict[str, np.ndarray] with batch helpers. All columns share dim 0."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return int(v.shape[0])
        return 0

    @property
    def count(self) -> int:
        return len(self)

    @staticmethod
    def concat_samples(batches: list["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch(
            {k: np.concatenate([b[k] for b in batches], axis=0) for k in keys}
        )

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(len(self))
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = len(self)
        for i in range(0, n - size + 1, size):
            yield SampleBatch({k: v[i : i + size] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def truncate(self, max_rows: int) -> "SampleBatch":
        """Drop rows beyond max_rows (keeps shapes jit-static across iters)."""
        return self if len(self) <= max_rows else self.slice(0, max_rows)


def compute_gae(
    rewards: np.ndarray,  # [T, B]
    values: np.ndarray,  # [T, B]  V(s_t)
    next_values: np.ndarray,  # [T, B]  V(s_{t+1}) — at truncation, V(terminal obs)
    terminateds: np.ndarray,  # [T, B] bool — true end, no bootstrap
    truncateds: np.ndarray,  # [T, B] bool — time limit, bootstrap but cut λ-chain
    gamma: float,
    lam: float,
) -> tuple[np.ndarray, np.ndarray]:
    """GAE(λ) advantages + value targets (reference:
    rllib/evaluation/postprocessing.py compute_advantages). Time-major
    numpy recursion host-side — O(T·B), negligible next to the jitted
    learner step. Per-step next_values make mid-rollout resets exact:
    at termination the bootstrap is zeroed; at truncation the terminal
    observation's value bootstraps but the λ-chain is cut (the following
    row belongs to a fresh episode)."""
    T, B = rewards.shape
    adv = np.zeros((T, B), np.float32)
    gae = np.zeros(B, np.float32)
    for t in range(T - 1, -1, -1):
        not_term = 1.0 - terminateds[t].astype(np.float32)
        chain = not_term * (1.0 - truncateds[t].astype(np.float32))
        delta = rewards[t] + gamma * next_values[t] * not_term - values[t]
        gae = delta + gamma * lam * chain * gae
        adv[t] = gae
    targets = adv + values
    return adv, targets
