"""ray-tpu CLI: start/status/submit/list/timeline.

Counterpart of the reference's CLI (python/ray/scripts/scripts.py —
`ray start` :647, `ray status`, `ray submit`, `ray timeline`, `ray list`
via util.state). `start --head` runs a standalone head service;
`start --address` joins as a node agent.

    ray-tpu start --head --port 6380 --num-cpus 8
    ray-tpu start --address 127.0.0.1:6380 --num-cpus 4
    ray-tpu status --address 127.0.0.1:6380
    ray-tpu submit --address 127.0.0.1:6380 -- python my_job.py
    ray-tpu list tasks --address 127.0.0.1:6380
    ray-tpu timeline --address 127.0.0.1:6380 -o trace.json
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys


def _connect(address: str) -> None:
    import ray_tpu

    ray_tpu.init(address=address)


def cmd_start(args) -> int:
    if args.head:
        from ray_tpu._private.config import Config
        from ray_tpu._private.head_shards import create_head

        cfg = Config()
        cfg.head_host = args.host
        cfg.head_port = args.port
        if args.object_store_memory:
            cfg.object_store_memory = int(args.object_store_memory)
        if getattr(args, "snapshot_path", None):
            # Head FT: persist durable tables; a restart with the same
            # path restores them (reference: redis-backed GCS state).
            cfg.gcs_snapshot_path = args.snapshot_path
        if getattr(args, "external_store", None):
            # Cross-node head HA: durable state in a shared store; a
            # fresh head anywhere restores it (redis_store_client.h:111).
            cfg.gcs_external_store = args.external_store
        head = create_head(
            cfg, num_cpus=args.num_cpus, num_tpus=args.num_tpus,
            resources=json.loads(args.resources) if args.resources else None)
        host, port = head.address
        if host == "0.0.0.0":
            import socket

            try:
                shown = socket.gethostbyname(socket.gethostname())
            except OSError:
                shown = "<this-host>"
        else:
            shown = host
        print(f"ray_tpu head up at {shown}:{port}", flush=True)
        print(f"  connect: ray_tpu.init(address='{shown}:{port}')", flush=True)
        print(f"  join:    ray-tpu start --address {shown}:{port}", flush=True)
        try:
            import threading

            threading.Event().wait()  # serve forever
        except KeyboardInterrupt:
            head.shutdown()
        return 0
    if not args.address:
        print("either --head or --address is required", file=sys.stderr)
        return 2
    from ray_tpu._private.node_agent import NodeAgent

    host, port = args.address.rsplit(":", 1)
    agent = NodeAgent(
        (host, int(port)),
        num_cpus=args.num_cpus,
        num_tpus=args.num_tpus,
        resources=json.loads(args.resources) if args.resources else None,
        node_id=args.node_id,
        force_remote_objects=args.force_remote_objects,
    )
    print(f"node agent up: node_id={agent.node_id}", flush=True)
    try:
        agent.run_forever()
    except KeyboardInterrupt:
        agent.shutdown()
    return 0


def cmd_status(args) -> int:
    import ray_tpu

    _connect(args.address)
    info = {
        "resources_total": ray_tpu.cluster_resources(),
        "resources_available": ray_tpu.available_resources(),
        "nodes": ray_tpu.nodes(),
    }
    print(json.dumps(info, indent=2, default=str))
    return 0


def cmd_submit(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(address=args.address)
    entry = args.entrypoint
    if entry and entry[0] == "--":
        entry = entry[1:]
    entrypoint = shlex.join(entry)
    job_id = client.submit_job(entrypoint=entrypoint)
    print(f"submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id, timeout_s=args.timeout)
        print(f"{job_id}: {status}")
        print(client.get_job_logs(job_id), end="")
        return 0 if status == "SUCCEEDED" else 1
    return 0


def cmd_list(args) -> int:
    from ray_tpu.util import state as us

    _connect(args.address)
    fn = {
        "tasks": us.list_tasks,
        "actors": us.list_actors,
        "objects": us.list_objects,
        "workers": us.list_workers,
        "nodes": us.list_nodes,
    }[args.kind]
    print(json.dumps(fn(limit=args.limit), indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    from ray_tpu.util import state as us

    _connect(args.address)
    kind = getattr(args, "kind", "tasks") or "tasks"
    fn = {"tasks": us.summarize_tasks, "actors": us.summarize_actors,
          "objects": us.summarize_objects}[kind]
    print(json.dumps(fn(), indent=2))
    return 0


_MEM_UNITS = {"B": 1, "KB": 1024, "MB": 1024 ** 2, "GB": 1024 ** 3}


def _fmt_bytes(n, units: str) -> str:
    div = _MEM_UNITS.get(units, 1)
    if div == 1:
        return str(int(n or 0))
    return f"{(n or 0) / div:.2f}{units}"


def _render_memory_groups(summary: dict, group_by: str, sort_by: str,
                          units: str) -> list:
    """The `ray memory`-style grouped table (reference: `ray memory
    --group-by ...`): one row per callsite / node / state with object
    counts and live bytes, sorted by size (default) or count."""
    rows: list = []
    if group_by == "callsite":
        src = summary.get("groups") or {}
        items = [(site, g.get("count", 0), g.get("bytes", 0),
                  g.get("unawaited", 0),
                  ",".join(sorted(g.get("kinds") or {})))
                 for site, g in src.items()]
    elif group_by == "node":
        items = []
        for node, states in (summary.get("by_node") or {}).items():
            count = sum(s.get("count", 0) for s in states.values())
            size = sum(s.get("bytes", 0) for s in states.values())
            items.append((node, count, size, "",
                          ",".join(sorted(states))))
    else:  # state
        items = [(state, s.get("count", 0), s.get("bytes", 0), "", "")
                 for state, s in (summary.get("by_state") or {}).items()]
    items.sort(key=lambda r: r[1] if sort_by == "count" else r[2],
               reverse=True)
    label = group_by.upper()
    hdr = f"{label:58} {'OBJECTS':>8} {'SIZE':>12} {'UNAWAITED':>9} KINDS"
    rows.append(f"=== Grouped by {group_by} (sort: {sort_by}) ===")
    rows.append(hdr)
    rows.append("-" * len(hdr))
    for name, count, size, unawaited, kinds in items:
        rows.append(f"{str(name)[:58]:58} {count:>8} "
                    f"{_fmt_bytes(size, units):>12} {str(unawaited):>9} "
                    f"{kinds}")
    if not items:
        rows.append("(no census reports yet — owners report every "
                    "rpc_report_interval_s)")
    return rows


def _render_memory_leaks(suspects: list, units: str) -> list:
    rows = ["=== Leak suspects ==="]
    if not suspects:
        rows.append("(none)")
        return rows
    for s in suspects:
        where = s.get("callsite") or s.get("object_id") or "?"
        trend = s.get("trend_bytes")
        extra = f"  trend={trend}" if trend else ""
        rows.append(f"[{s.get('kind')}] {where}  "
                    f"bytes={_fmt_bytes(s.get('bytes', 0), units)}  "
                    f"owner={s.get('owner', '')}  "
                    f"{s.get('detail', '')}{extra}")
    return rows


def _render_lineage(chain: dict, indent: int = 0) -> list:
    rows = []
    pad = "  " * indent
    task = chain.get("task")
    if task is None:
        rows.append(f"{pad}{chain.get('object_id')}  (no lineage "
                    f"recorded — put() or evicted entry)")
        return rows
    rows.append(f"{pad}{chain.get('object_id')}  <- task "
                f"{task.get('name')} [{task.get('task_id')}] "
                f"{task.get('state') or ''} on {task.get('node_id') or '?'}")
    for arg in chain.get("args") or ():
        rows.extend(_render_lineage(arg, indent + 1))
    if chain.get("args_truncated"):
        rows.append(f"{pad}  ... {chain['args_truncated']} more arg(s)")
    return rows


def cmd_memory(args) -> int:
    """Cluster memory report (reference: `ray memory` —
    _private/internal_api.py memory_summary): callsite-grouped live-ref
    census, per-object table, shm-store pin/fragmentation stats, leak
    suspects, and per-object lineage drill-down."""
    from ray_tpu.util import state as us

    _connect(args.address)
    as_json = args.json or getattr(args, "format", None) == "json"
    units = getattr(args, "units", "B") or "B"
    if getattr(args, "object_id", None):
        obj = us.get_object(args.object_id)
        if obj is None:
            print(f"object {args.object_id} not found (freed and no "
                  f"lineage recorded)")
            return 1
        if as_json:
            print(json.dumps({"object": obj}, indent=2, default=str))
            return 0
        print(f"object   {obj.get('object_id')}")
        for key in ("state", "size", "owner", "node_id", "callsite",
                    "refcount", "borrowers", "task_pins",
                    "container_pins", "read_pins", "reads", "age_s",
                    "owner_resident", "task_id"):
            if obj.get(key) not in (None, [], {}):
                print(f"{key:14} {obj[key]}")
        print("lineage:")
        for ln in _render_lineage(obj.get("lineage") or
                                  {"object_id": obj.get("object_id")}, 1):
            print(ln)
        return 0
    objs = us.list_objects(limit=args.limit)
    stats = us.object_store_stats()
    summary = us.memory_summary()
    if as_json:
        print(json.dumps({"objects": objs, "store": stats,
                          "summary": summary,
                          "leaks": summary.get("leak_suspects") or []},
                         indent=2, default=str))
        return 0
    group_by = getattr(args, "group_by", "callsite") or "callsite"
    sort_by = getattr(args, "sort_by", "size") or "size"
    for ln in _render_memory_groups(summary, group_by, sort_by, units):
        print(ln)
    print()
    hdr = f"{'OBJECT ID':42} {'STATE':10} {'SIZE':>12} {'REFS':>5} " \
          f"{'PINS':>5} {'OWNER':18} CALLSITE"
    print(hdr)
    print("-" * len(hdr))
    key = (lambda o: int(o.get("size") or 0)) if sort_by == "size" \
        else (lambda o: o.get("created_at") or 0)
    total = 0
    for o in sorted(objs, key=key, reverse=True):
        size = int(o.get("size") or 0)
        total += size
        pins = int(o.get("container_pins") or 0) + int(o.get("task_pins")
                                                       or 0)
        print(f"{o['object_id']:42} {o['state']:10} "
              f"{_fmt_bytes(size, units):>12} "
              f"{o.get('refcount', 0):>5} {pins:>5} "
              f"{str(o.get('owner', ''))[:18]:18} "
              f"{o.get('callsite', '')}")
    print(f"\n{len(objs)} objects, {total} bytes referenced; store: "
          f"{stats.get('in_use', 0)}/{stats.get('capacity', 0)} "
          f"bytes used, {stats.get('num_objects', 0)} resident, "
          f"{_fmt_bytes(stats.get('pinned_bytes', 0), units)} pinned / "
          f"{_fmt_bytes(stats.get('reclaimable_bytes', 0), units)} "
          f"reclaimable, {stats.get('eviction_candidates', 0)} eviction "
          f"candidate(s), {_fmt_bytes(stats.get('fragmented_free', 0), units)} "
          f"fragmented free")
    suspects = summary.get("leak_suspects") or []
    if suspects or getattr(args, "leaks", False):
        print()
        for ln in _render_memory_leaks(suspects, units):
            print(ln)
    return 0


def cmd_logs(args) -> int:
    """List or tail cluster worker logs (reference: `ray logs [file]`).
    --node routes through that node's agent (remote-node log access);
    --trace greps every log on every node for one request's
    [trace=<id>]-stamped lines (trace-correlated logs)."""
    from ray_tpu._private.worker_context import global_runtime

    _connect(args.address)
    conn = global_runtime().conn
    node_id = getattr(args, "node", None)
    base = {"node_id": node_id} if node_id else {}
    if getattr(args, "trace", None):
        return _grep_trace_logs(conn, args)
    if not args.name:
        reply = conn.call("log_index", dict(base))
        if reply.get("error"):
            print(reply["error"], file=sys.stderr)
            return 1
        for e in reply["logs"]:
            print(f"{e['bytes']:>10}  {e['name']}")
        return 0
    reply = conn.call("log_tail", {"name": args.name,
                                   "max_bytes": args.max_bytes, **base})
    if reply.get("error"):
        print(reply["error"], file=sys.stderr)
        return 1
    lines = reply["lines"][-args.tail:] if args.tail > 0 else []
    for ln in lines:
        print(ln)
    return 0


def _grep_trace_logs(conn, args) -> int:
    """Client-side grep for one trace's log lines: walk the head's
    session logs plus every node agent's log dir, tail each file, and
    keep the [trace=<id>]-prefixed lines (stamped by the workers'
    logging filter while a traced task executes)."""
    from ray_tpu.util import state as us

    needle = f"[trace={args.trace}]"
    sources = [(None, "head")]
    try:
        sources += [(n["node_id"], n["node_id"]) for n in us.list_nodes()]
    except Exception:
        pass
    hits = 0
    for node_id, label in sources:
        body = {"node_id": node_id} if node_id else {}
        try:
            index = conn.call("log_index", dict(body)).get("logs") or []
        except Exception:
            continue
        for e in index:
            reply = conn.call("log_tail", {
                "name": e["name"], "max_bytes": args.max_bytes, **body})
            for ln in reply.get("lines") or []:
                if needle in ln:
                    print(f"{label}/{e['name']}: {ln}")
                    hits += 1
    if not hits:
        print(f"no log lines found for trace {args.trace}")
    return 0


def cmd_trace(args) -> int:
    """Causal trace waterfall (`ray-tpu trace <id>`), or the retained
    trace list with no id. --perfetto exports one trace as a Chrome
    JSON trace (open in Perfetto / chrome://tracing) with one row per
    process and proper parent nesting."""
    from ray_tpu.util import state as us

    _connect(args.address)
    if not args.trace_id:
        rows = us.list_traces(limit=args.limit,
                              exemplars_only=args.exemplars)
        if not rows:
            print("no traces retained")
            return 0
        print(f"{'TRACE':<34} {'ROOT':<24} {'SPANS':>5} "
              f"{'DUR_MS':>8}  FLAGS")
        for r in rows:
            flags = ",".join(f for f in ("error", "shed", "slow")
                             if r.get(f)) or "-"
            print(f"{r['trace_id']:<34} {r.get('root') or '?':<24} "
                  f"{r['spans']:>5} {r['duration_s'] * 1e3:>8.1f}  "
                  f"{flags}")
        return 0
    tr = us.get_trace(args.trace_id)
    if tr is None:
        print(f"no trace {args.trace_id!r} retained (folded, or never "
              f"sampled — see `ray-tpu trace` for the retained set)")
        return 1
    spans = tr.get("spans_detail") or []
    if args.perfetto:
        _write_perfetto(args.perfetto, tr, spans)
        print(f"wrote {args.perfetto}")
        return 0
    flags = ",".join(f for f in ("error", "shed", "slow")
                     if tr.get(f)) or "-"
    print(f"trace {tr['trace_id']}  root={tr.get('root') or '?'}  "
          f"spans={tr['spans']}  dur={tr['duration_s'] * 1e3:.1f}ms  "
          f"flags={flags}")
    _print_waterfall(spans, tr.get("start") or 0.0,
                     max(tr.get("duration_s") or 0.0, 1e-9))
    return 0


def _print_waterfall(spans: list, t0: float, total: float) -> None:
    """Indented causal tree, one line per span, with an offset/duration
    bar scaled to the trace: `<indent><name> [pid/node] |--=====--|`."""
    by_id = {s["span_id"]: s for s in spans}
    children: dict = {}
    roots = []
    for s in spans:
        p = s.get("parent_span_id") or ""
        if p and p in by_id:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    width = 40

    def bar(s):
        off = int((max(0.0, s["start"] - t0) / total) * width)
        dur = max(1, int(((s["end"] - s["start"]) / total) * width))
        off = min(off, width - 1)
        dur = min(dur, width - off)
        return "." * off + "=" * dur + "." * (width - off - dur)

    def walk(s, depth):
        where = s.get("worker_id") or s.get("node_id") \
            or (f"pid:{s['pid']}" if s.get("pid") else "?")
        ms = (s["end"] - s["start"]) * 1e3
        mark = " FAILED" if s.get("failed") else ""
        print(f"  {'  ' * depth}{s.get('name'):<{30 - 2 * min(depth, 8)}}"
              f" |{bar(s)}| {ms:>8.1f}ms  [{s.get('kind', '?')}"
              f" {where}]{mark}")
        for c in sorted(children.get(s["span_id"], []),
                        key=lambda x: x["start"]):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x["start"]):
        walk(r, 0)


def _write_perfetto(path: str, tr: dict, spans: list) -> None:
    """Chrome JSON trace: complete ("X") events, one pid row per
    process, span hierarchy recoverable via the id args."""
    events = []
    for s in spans:
        events.append({
            "name": s.get("name"),
            "cat": s.get("kind", "span"),
            "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": max(0.0, (s["end"] - s["start"]) * 1e6),
            "pid": s.get("pid") or 0,
            "tid": s.get("worker_id") or s.get("task_id") or 0,
            "args": {k: s.get(k) for k in
                     ("span_id", "parent_span_id", "task_id",
                      "worker_id", "node_id", "attributes", "failed")
                     if s.get(k) is not None},
        })
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms",
                   "otherData": {"trace_id": tr["trace_id"]}}, f)


def cmd_crashes(args) -> int:
    """Post-mortem crash reports (`ray-tpu crashes [worker_id]`):
    classified worker/node deaths from the head's forensics table;
    a worker_id argument prints the full report (stack excerpt, log
    tail, beacon)."""
    from ray_tpu.util import state as us

    _connect(args.address)
    if args.worker_id:
        report = us.get_crash_report(args.worker_id)
        if report is None:
            print(f"no crash report for {args.worker_id}")
            return 1
        if args.json:
            print(json.dumps(report, indent=2, default=str))
            return 0
        print(f"worker   {report.get('worker_id')}  "
              f"(pid {report.get('pid')}, node {report.get('node_id')})")
        print(f"reason   {report.get('exit_type')}: "
              f"{report.get('exit_detail')}")
        sig = report.get("signal_name") or report.get("term_signal")
        print(f"status   exit_code={report.get('exit_code')} "
              f"signal={sig}")
        lt = report.get("last_task")
        if lt:
            print(f"last task  {lt.get('name')} [{lt.get('task_id')}]")
        if report.get("beacon"):
            print(f"beacon   {json.dumps(report['beacon'])}")
        prof = report.get("profile")
        if prof:
            # Profiling-plane sidecar: the worker's last sampled window
            # — "what it was burning CPU on" at the end of its life.
            print(f"\n--- last profile window ({prof.get('samples', 0)} "
                  f"samples, {prof.get('role', 'worker')}) ---")
            top = sorted((prof.get("folded") or {}).items(),
                         key=lambda kv: -kv[1])[:8]
            for stack, hits in top:
                label = stack if len(stack) <= 90 else "…" + stack[-89:]
                print(f"  {hits:>6}  {label}")
        for title, key in (("post-mortem stack", "stack"),
                           ("log tail", "log_tail")):
            lines = report.get(key) or []
            if lines:
                print(f"\n--- {title} ---")
                for ln in lines:
                    print(f"  {ln}")
        return 0
    rows = us.list_crash_reports(limit=args.limit)
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return 0
    hdr = f"{'WORKER':24} {'NODE':16} {'REASON':20} {'SIG/CODE':>8} " \
          f"{'LAST TASK':24} DETAIL"
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        sig = r.get("signal_name") or r.get("exit_code")
        lt = (r.get("last_task") or {}).get("name") or ""
        print(f"{r.get('worker_id', ''):24} {r.get('node_id') or '':16} "
              f"{r.get('exit_type', ''):20} {str(sig if sig is not None else ''):>8} "
              f"{lt:24} {r.get('exit_detail', '')}")
    print(f"\n{len(rows)} report(s)")
    return 0


def _merged_folded(windows: list, cap: int = 4096) -> dict:
    from ray_tpu._private import profplane

    merged: dict = {}
    for w in windows:
        profplane.merge_folded(merged, w.get("folded") or {}, cap=cap)
    return merged


def _print_folded(folded: dict, top: int, total_hint=None) -> None:
    total = total_hint if total_hint is not None else \
        sum(abs(v) for v in folded.values()) or 1
    width = 30
    rows = sorted(folded.items(), key=lambda kv: -abs(kv[1]))[:top]
    for stack, hits in rows:
        share = abs(hits) / total
        bar = "#" * max(1, int(share * width)) if hits else ""
        # Deep stacks: keep the leafward frames (where the time IS).
        label = stack if len(stack) <= 100 else "…" + stack[-99:]
        val = f"{hits:+.2%}" if isinstance(hits, float) else f"{hits:>6}"
        print(f"  {val}  {bar:<{width}}  {label}")


def cmd_profile(args) -> int:
    """Continuous-profiling plane (`ray-tpu profile`): render the
    head's merged cluster profile table as a text flamegraph summary —
    always-on duty-cycled samples from every runtime process, merged
    by (node, role, window). `--diff A B` prints the differential
    folded output between two window indexes (per-sample share, so a
    busy and a quiet window compare honestly)."""
    from ray_tpu._private import profplane
    from ray_tpu.util import state as us

    _connect(args.address)
    prof = us.cluster_profile(role=args.role, node=args.node,
                              window=args.window)
    windows = prof.get("windows") or []
    if args.json:
        print(json.dumps(prof, indent=2, default=str))
        return 0
    if args.diff:
        a_win, b_win = (int(x) for x in args.diff)
        a = _merged_folded([w for w in windows if w["window"] == a_win])
        b = _merged_folded([w for w in windows if w["window"] == b_win])
        if not a or not b:
            print(f"no profile data for window "
                  f"{a_win if not a else b_win}")
            return 1
        d = profplane.diff_folded(a, b)
        print(f"differential profile: window {a_win} -> {b_win} "
              f"(signed per-sample share; + = grew)")
        _print_folded(d, args.top, total_hint=1.0)
        return 0
    if not windows:
        print("no profile windows yet (plane disabled via "
              "RAY_TPU_PROFILING_ENABLED=0, or no window elapsed — "
              "windows ship every profiling_window_s on the amortized "
              "report casts)")
        return 1
    merged = _merged_folded(windows)
    if args.output:
        with open(args.output, "w") as f:
            for stack, hits in merged.items():
                f.write(f"{stack} {hits}\n")
        print(f"wrote {len(merged)} collapsed stacks to {args.output}")
    if args.speedscope:
        us.save_speedscope({"folded": merged, "worker_id": "cluster"},
                           args.speedscope, name="ray_tpu cluster")
        print(f"wrote speedscope profile to {args.speedscope}")
    if args.output or args.speedscope:
        return 0
    stats = prof.get("stats") or {}
    roles = sorted({w["role"] for w in windows})
    nodes = sorted({w["node"] for w in windows})
    pids = sorted({p for w in windows for p in (w.get("pids") or ())})
    samples = sum(w.get("samples") or 0 for w in windows)
    cost = sum(w.get("sample_cost_s") or 0.0 for w in windows)
    print(f"cluster profile: {len(windows)} window(s), {samples} samples "
          f"across {len(pids)} pid(s)  [roles: {', '.join(roles)};"
          f" nodes: {', '.join(nodes)}]")
    print(f"  plane: {stats.get('windows_total', 0)} windows merged, "
          f"{stats.get('dropped_windows', 0)} evicted, "
          f"{stats.get('pinned', 0)} pinned (phase regressions), "
          f"{stats.get('gil_exemplars', 0)} GIL exemplars; "
          f"sampling cost {cost:.3f}s")
    pinned = [w for w in windows if w.get("pinned")]
    for w in pinned:
        pin = w["pinned"]
        print(f"  PINNED window {w['window']} ({w['role']}@{w['node']}): "
              f"{pin['phase']} p95 {pin['p95'] * 1e3:.1f}ms vs trailing "
              f"median {pin['trailing_median'] * 1e3:.1f}ms")
    print("\ntop self-time frames (leaf hits):")
    _print_folded(profplane.self_time(merged), args.top)
    print("\ntop stacks:")
    _print_folded(merged, args.top)
    exemplars = prof.get("gil_exemplars") or []
    if exemplars:
        print("\nGIL-starvation exemplars (wall >> cpu tasks):")
        for ex in exemplars[-5:]:
            print(f"  {ex.get('name')} [{(ex.get('task_id') or '')[:16]}] "
                  f"wall {ex.get('wall_s')}s cpu {ex.get('cpu_s')}s "
                  f"({ex.get('role')}@{ex.get('node')})")
    return 0


def cmd_lint(args) -> int:
    """Invariant analysis (`ray-tpu lint`): the tools/rtlint static
    cross-checkers — wire-protocol kinds vs dispatch tables, env knobs
    vs the config registry, lock discipline and lock-order cycles,
    wall/monotonic clock splits, metric catalog + label cardinality,
    and the direct-plane head-frame budget. Exit 0 means every
    invariant holds (modulo the written baseline); findings exit 1
    with file:line callsites. Catalog: docs/INVARIANTS.md."""
    import os

    try:
        from tools.rtlint.__main__ import main as lint_main
    except ImportError:
        # running from an installed wheel won't find the repo-root
        # `tools` package on sys.path; a source checkout will.
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if not os.path.isdir(os.path.join(root, "tools", "rtlint")):
            print("ray-tpu lint runs against a source checkout "
                  "(tools/rtlint is not shipped in wheels)",
                  file=sys.stderr)
            return 2
        sys.path.insert(0, root)
        from tools.rtlint.__main__ import main as lint_main

    argv: list[str] = []
    if args.root is not None:
        argv += ["--root", args.root]
    if args.baseline is not None:
        argv += ["--baseline", args.baseline]
    for name in args.passes or ():
        argv += ["--pass", name]
    argv += ["--format", args.format]
    if args.write_baseline:
        argv += ["--write-baseline", args.write_baseline]
    return lint_main(argv)


def cmd_health(args) -> int:
    """Overload / retry-plane health view (`ray-tpu health`): pending
    budgets, deadline sheds, admission rejections, memory-pressured
    nodes, and per-target circuit breakers (open state + trip history)
    so an operator can see why traffic to a peer is being shed."""
    import time as _time

    from ray_tpu.util import state as us

    _connect(args.address)
    h = us.health_summary()
    if args.json:
        print(json.dumps(h, indent=2, default=str))
        return 0
    g = h["gauges"]
    print(f"nodes alive      {g.get('nodes_alive', '?')}  "
          f"(pressured: {g.get('mem_pressured_nodes', 0)})")
    print(f"workers alive    {g.get('workers_alive', '?')}")
    print(f"tasks pending    {g.get('admission_pending_total', 0)} "
          f"across {g.get('admission_pending_owners', 0)} owner(s)")
    print(f"admission        {h['counters'].get('admission_rejected', 0)} "
          f"rejected")
    if h["tasks_shed"]:
        shed = ", ".join(f"{k}={v}" for k, v in
                         sorted(h["tasks_shed"].items()))
        print(f"deadline sheds   {shed}")
    for nid, info in h["pressured_nodes"].items():
        used, total = info.get("used") or 0, info.get("total") or 0
        pct = f"{100.0 * used / total:.0f}%" if total else "?"
        print(f"PRESSURED node   {nid}  mem {pct} ({used}/{total})")
    if h["worker_deaths"]:
        deaths = ", ".join(f"{k}={v}" for k, v in
                           sorted(h["worker_deaths"].items()))
        print(f"worker deaths    {deaths}")
    if not h["breakers"]:
        print("breakers         all closed, no trips")
    for scope, table in h["breakers"].items():
        for target, b in table.items():
            age = b.get("last_trip_at")
            ago = (f"{_time.time() - age:.0f}s ago"
                   if age else "never")
            state = "OPEN" if b.get("open") else "closed"
            print(f"breaker          [{scope}] {target}: {state}, "
                  f"{b.get('trip_count', 0)} trip(s), last {ago}, "
                  f"{b.get('failures', 0)} consecutive failure(s)")
    return 0


_SPARK_BARS = "▁▂▃▄▅▆▇█"


def _sparkline(values: "list[float]", width: int = 24) -> str:
    """Tiny block-char sparkline (fixed palette, no deps). Values are
    resampled to ``width`` columns and scaled to the window's max."""
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        # Tail-biased resample: the most recent samples matter most.
        stride = len(vals) / width
        vals = [vals[min(len(vals) - 1, int(i * stride))]
                for i in range(width)]
    hi = max(vals)
    lo = min(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK_BARS[int((v - lo) / span * (len(_SPARK_BARS) - 1))]
        for v in vals)


def _counter_rates(series: "list[dict]") -> "list[float]":
    """Per-bucket rates from a query_metrics counter reply (summed
    across matching series, consecutive-bucket deltas / dt)."""
    buckets: dict[float, float] = {}
    for s in series:
        for b in s.get("points") or ():
            buckets[b[0]] = buckets.get(b[0], 0.0) + b[5]
    ordered = sorted(buckets.items())
    rates = []
    for (t0, v0), (t1, v1) in zip(ordered, ordered[1:]):
        dt = t1 - t0
        if dt > 0:
            rates.append(max(0.0, v1 - v0) / dt)
    return rates


def cmd_top(args) -> int:
    """Live cluster view (`ray-tpu top`): one refreshing screen with
    nodes, shards, tasks/s (with history sparkline from the embedded
    tsdb), phase p95s, firing alerts, and the hottest flamegraph leaf
    from the continuous profiler — the "is the cluster healthy right
    now" answer without a dashboard deployment."""
    import time as _time

    from ray_tpu._private.worker_context import global_runtime
    from ray_tpu.util import state as us

    _connect(args.address)
    iterations = 1 if args.once else (args.iterations or 0)
    shown = 0
    while True:
        snap = global_runtime().conn.call("runtime_stats", {},
                                          timeout=10)
        rate_q = us.query_metrics("ray_tpu_tasks_finished_total",
                                  start=_time.time() - 600)
        p95_q = us.query_metrics("ray_tpu_phase_p95_seconds",
                                 start=_time.time() - 120)
        load_q = us.query_metrics("ray_tpu_node_load1",
                                  start=_time.time() - 120)
        alerts = us.list_alerts()
        if args.json:
            print(json.dumps({
                "gauges": snap.get("gauges"),
                "counters": snap.get("counters"),
                "tasks_shed": snap.get("tasks_shed"),
                "telemetry": snap.get("telemetry"),
                "alerts": alerts,
                "tasks_per_s": _counter_rates(
                    rate_q.get("series") or []),
            }, indent=2, default=str))
        else:
            if not args.once and shown:
                print("\x1b[2J\x1b[H", end="")
            _render_top(snap, rate_q, p95_q, load_q, alerts)
        shown += 1
        if iterations and shown >= iterations:
            return 0
        try:
            _time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


def _render_top(snap: dict, rate_q: dict, p95_q: dict, load_q: dict,
                alerts: dict) -> None:
    import time as _time

    g = snap.get("gauges") or {}
    c = snap.get("counters") or {}
    print(f"ray-tpu top — {_time.strftime('%H:%M:%S')}")
    print(f"nodes {g.get('nodes_alive', '?')} "
          f"(pressured {g.get('mem_pressured_nodes', 0)})   "
          f"head shards {snap.get('head_shards', 1)}   "
          f"workers {g.get('workers_alive', '?')}   "
          f"actors {g.get('actors_alive', '?')}   "
          f"pending {g.get('tasks_pending', 0)}")
    rates = _counter_rates(rate_q.get("series") or [])
    spark = _sparkline(rates)
    now_rate = rates[-1] if rates else 0.0
    shed = sum((snap.get("tasks_shed") or {}).values())
    print(f"tasks: {c.get('tasks_finished', 0)} finished "
          f"({now_rate:.1f}/s {spark}), "
          f"{c.get('tasks_failed', 0)} failed, {shed} shed")
    p95s = []
    for s in (p95_q.get("series") or []):
        pts = s.get("points") or []
        if pts:
            phase = (s.get("labels") or {}).get("phase", "?")
            p95s.append(f"{phase} {pts[-1][5] * 1e3:.1f}ms")
    if p95s:
        print(f"phase p95: {'  '.join(sorted(p95s))}")
    tele = snap.get("telemetry") or {}
    print(f"tsdb: {tele.get('series', 0)} series, "
          f"{tele.get('points', 0)} points retained "
          f"({tele.get('dropped_total', 0)} folded)")
    firing = [a for a in (alerts.get("alerts") or [])
              if a.get("state") == "firing"]
    if firing:
        for a in firing:
            print(f"ALERT [{a.get('severity')}] {a.get('name')} "
                  f"value={a.get('value')} — {a.get('summary', '')}")
    else:
        print("alerts: none firing")
    # Hottest self-time leaf across roles (the continuous profiler's
    # one-line answer to "what is the cluster busy doing").
    best = ("", "", 0)
    for role, frames in ((snap.get("profiling") or {})
                         .get("self_time") or {}).items():
        for frame, hits in frames.items():
            if hits > best[2]:
                best = (role, frame, hits)
    if best[2]:
        print(f"top flame leaf: {best[1]} ({best[0]}, {best[2]} hits)")
    loads = []
    for s in (load_q.get("series") or []):
        pts = s.get("points") or []
        if pts:
            nid = (s.get("labels") or {}).get("node_id", "?")
            loads.append(f"  {nid}  load1 {pts[-1][5]:.2f}")
    if loads:
        print("nodes:")
        for row in sorted(loads):
            print(row)


def cmd_alerts(args) -> int:
    """SLO alert table (`ray-tpu alerts`): active pending/firing
    records, `--history` adds the resolved ring. Firing rows print the
    cross-plane evidence pinned at fire time (trace exemplars, profile
    windows, crash reports)."""
    import time as _time

    from ray_tpu.util import state as us

    _connect(args.address)
    reply = us.list_alerts(history=args.history)
    if args.format == "json":
        print(json.dumps(reply, indent=2, default=str))
        return 0
    rows = reply.get("alerts") or []
    stats = reply.get("stats") or {}
    if not reply.get("enabled", True):
        print("alert engine disabled (RAY_TPU_ALERTS_ENABLED=0)")
        return 0
    print(f"{stats.get('rules', 0)} rule(s): "
          f"{stats.get('firing', 0)} firing, "
          f"{stats.get('pending', 0)} pending, "
          f"{stats.get('fired_total', 0)} fired / "
          f"{stats.get('resolved_total', 0)} resolved lifetime")
    if not rows:
        print("no active alerts" + ("" if args.history
                                    else " (--history for resolved)"))
        return 0
    now = _time.time()
    for a in rows:
        at = a.get("fired_at") or a.get("since")
        ago = f"{now - at:.0f}s ago" if at else "?"
        print(f"[{a.get('state', '?'):8}] {a.get('severity', '?'):4} "
              f"{a.get('name')}  value={a.get('value')}  ({ago})")
        if a.get("summary"):
            print(f"           {a['summary']}")
        ctx = a.get("context") or {}
        if ctx.get("trace_exemplars"):
            print(f"           traces: "
                  f"{', '.join(ctx['trace_exemplars'][:4])}")
        if ctx.get("profile_windows"):
            wins = ctx["profile_windows"]
            print(f"           profile windows: {len(wins)} overlapping "
                  f"(e.g. {wins[-1]['role']}@{wins[-1]['node']} "
                  f"window {wins[-1]['window']})")
        if ctx.get("crash_reports"):
            print(f"           crashes in window: "
                  f"{len(ctx['crash_reports'])}")
    return 0


def cmd_metrics(args) -> int:
    """Telemetry-history queries (`ray-tpu metrics query NAME`): range
    reads from the head's embedded tsdb — raw ~10s buckets for the
    last 30min, 1min rollups for 24h."""
    import time as _time

    from ray_tpu.util import state as us

    _connect(args.address)
    if args.metrics_cmd != "query":
        raise SystemExit(f"unknown metrics command {args.metrics_cmd!r}")
    labels = {}
    for kv in args.label or ():
        k, _, v = kv.partition("=")
        labels[k] = v
    start = args.start if args.start is not None else \
        _time.time() - args.window
    reply = us.query_metrics(args.name, labels or None, start,
                             args.end, args.step)
    if args.format == "json":
        print(json.dumps(reply, indent=2, default=str))
        return 0
    series = reply.get("series") or []
    if not reply.get("enabled", True):
        print("telemetry store disabled (RAY_TPU_TSDB_ENABLED=0)")
        return 0
    if not series:
        print(f"no retained points for {args.name!r} in the window")
        return 1
    for s in series:
        pts = s.get("points") or []
        if not pts:
            continue
        label = ",".join(f"{k}={v}" for k, v in
                         sorted((s.get("labels") or {}).items()))
        vals = [b[5] for b in pts]
        print(f"{s['name']}{{{label}}}  [{s.get('kind')}] "
              f"{len(pts)} bucket(s) @ {s.get('resolution_s', 0):.0f}s")
        print(f"  last={vals[-1]:.6g} min={min(b[1] for b in pts):.6g} "
              f"max={max(b[2] for b in pts):.6g}  {_sparkline(vals)}")
    return 0


def cmd_stop(args) -> int:
    """Stop the cluster: all agents, then the head (reference: `ray
    stop`)."""
    from ray_tpu._private.worker_context import global_runtime

    _connect(args.address)
    reply = global_runtime().conn.call("stop_cluster", {})
    print(f"stopping head + {reply['agents']} node agent(s)")
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu.util import state as us

    _connect(args.address)
    path = us.timeline(args.output)
    print(f"wrote {path}")
    return 0


def cmd_dashboard(args) -> int:
    from ray_tpu.dashboard import start_dashboard

    _connect(args.address)
    port = start_dashboard(port=args.port)
    print(f"dashboard at http://127.0.0.1:{port}/")
    import threading

    threading.Event().wait()
    return 0


def cmd_job(args) -> int:
    """`ray-tpu job ...` (reference: dashboard/modules/job/cli.py —
    ray job submit/status/logs/stop/list)."""
    from ray_tpu.job_submission import JobSubmissionClient

    if args.job_cmd == "submit":
        return cmd_submit(args)  # same namespace shape; one implementation
    client = JobSubmissionClient(address=args.address)
    if args.job_cmd == "status":
        info = client.get_job_info(args.job_id)
        print(json.dumps(info, indent=2, default=str))
        return 0
    if args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id), end="")
        return 0
    if args.job_cmd == "stop":
        stopped = client.stop_job(args.job_id)
        print("stopped" if stopped else "not running")
        return 0
    if args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.get('job_id')}\t{info.get('status')}\t"
                  f"{info.get('entrypoint', '')[:60]}")
        return 0
    raise SystemExit(f"unknown job command {args.job_cmd!r}")


def cmd_serve(args) -> int:
    """`ray-tpu serve ...` (reference: serve/scripts.py — serve
    deploy/status/shutdown)."""
    from ray_tpu import serve

    _connect(args.address)
    if args.serve_cmd == "deploy":
        serve.run_from_config(args.config_file)
        print(f"deployed from {args.config_file}")
        st = serve.status()
        for name, info in st.items():
            print(f"  {name}: {info['running_replicas']}/"
                  f"{info['target_replicas']} replicas")
        return 0
    if args.serve_cmd == "run":
        # `serve run pkg.mod:app` (reference: serve/scripts.py run —
        # deploy an import path; `:` splits module from attribute).
        import importlib

        target = args.import_path
        mod_name, _, attr = target.partition(":")
        if not attr:
            raise SystemExit(
                f"import path must be 'module:attribute', got {target!r}")
        from ray_tpu.serve.deployment import Application, Deployment

        app = getattr(importlib.import_module(mod_name), attr)
        if not isinstance(app, (Application, Deployment)) and callable(app):
            # A builder function (e.g. build_openai_app-style) — only
            # zero-arg builders are runnable from the CLI.
            import inspect as _inspect

            sig = _inspect.signature(app)
            required = [p for p in sig.parameters.values()
                        if p.default is p.empty
                        and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)]
            if required:
                raise SystemExit(
                    f"{target!r} is a builder with required arguments; "
                    "deploy it via a config file instead")
            app = app()
        serve.run(app, route_prefix=args.route_prefix)
        print(f"running {target}")
        if args.blocking:
            import time as _time

            while True:
                _time.sleep(3600)
        return 0
    if args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
        return 0
    if args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")
        return 0
    raise SystemExit(f"unknown serve command {args.serve_cmd!r}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu", description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or join as a node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--snapshot-path", default=None,
                    help="head FT: snapshot file for durable state")
    sp.add_argument("--external-store", default=None,
                    help="head HA: shared store URI (file:///dir) — a "
                         "fresh head on any node restores cluster state")
    sp.add_argument("--address", default=None, help="join an existing head")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=6380)
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--resources", default=None, help="JSON dict")
    sp.add_argument("--object-store-memory", type=int, default=None)
    sp.add_argument("--node-id", default=None)
    sp.add_argument("--force-remote-objects", action="store_true",
                    help=argparse.SUPPRESS)  # test hook
    sp.set_defaults(fn=cmd_start)

    s = sub.add_parser("status")
    s.add_argument("--address", required=True)
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("summary")
    s.add_argument("kind", nargs="?", default="tasks",
                   choices=["tasks", "actors", "objects"])
    s.add_argument("--address", required=True)
    s.set_defaults(fn=cmd_summary)

    s = sub.add_parser("submit", help="run an entrypoint as a cluster job")
    s.add_argument("--address", required=True)
    s.add_argument("--wait", action="store_true")
    s.add_argument("--timeout", type=float, default=600.0)
    s.add_argument("entrypoint", nargs=argparse.REMAINDER)
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("list")
    s.add_argument("kind", choices=["tasks", "actors", "objects", "workers", "nodes"])
    s.add_argument("--address", required=True)
    s.add_argument("--limit", type=int, default=100)
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("memory",
                       help="cluster memory report: callsite-grouped "
                            "census, leak suspects, lineage drill-down")
    s.add_argument("object_id", nargs="?", default=None,
                   help="drill into one object (full row + lineage)")
    s.add_argument("--address", required=True)
    s.add_argument("--limit", type=int, default=200)
    s.add_argument("--json", action="store_true")
    s.add_argument("--format", choices=["table", "json"], default="table")
    s.add_argument("--group-by", dest="group_by", default="callsite",
                   choices=["callsite", "node", "state"])
    s.add_argument("--sort-by", dest="sort_by", default="size",
                   choices=["size", "count"])
    s.add_argument("--units", default="B",
                   choices=["B", "KB", "MB", "GB"])
    s.add_argument("--leaks", action="store_true",
                   help="always print the leak-suspect section")
    s.set_defaults(fn=cmd_memory)

    s = sub.add_parser("trace",
                       help="request-trace waterfall / list / export")
    s.add_argument("trace_id", nargs="?", default=None,
                   help="trace id (from X-Trace-Id / list); omit to list")
    s.add_argument("--address", required=True)
    s.add_argument("--limit", type=int, default=50)
    s.add_argument("--exemplars", action="store_true",
                   help="list only slow/error/shed exemplar traces")
    s.add_argument("--perfetto", default=None, metavar="FILE",
                   help="export the trace as Chrome/Perfetto JSON")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser("logs", help="list or tail cluster worker logs")
    s.add_argument("name", nargs="?", default=None,
                   help="log name from the listing (omit to list)")
    s.add_argument("--address", required=True)
    s.add_argument("--tail", type=int, default=100)
    s.add_argument("--max-bytes", type=int, default=64 * 1024)
    s.add_argument("--node", default=None,
                   help="node id: list/tail that node's logs via its agent")
    s.add_argument("--trace", default=None,
                   help="trace id: grep all logs for the request's lines")
    s.set_defaults(fn=cmd_logs)

    s = sub.add_parser("crashes",
                       help="post-mortem worker crash reports")
    s.add_argument("worker_id", nargs="?", default=None,
                   help="print one full report (stacks, log tail, beacon)")
    s.add_argument("--address", required=True)
    s.add_argument("--limit", type=int, default=100)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_crashes)

    s = sub.add_parser(
        "profile",
        help="merged cluster flamegraph from the always-on profiling "
             "plane (filter --role/--node/--window, diff windows, "
             "export collapsed stacks / speedscope)")
    s.add_argument("--address", required=True)
    s.add_argument("--role", default=None,
                   choices=["head", "shard", "agent", "worker", "driver"])
    s.add_argument("--node", default=None, help="node id filter")
    s.add_argument("--window", type=int, default=None,
                   help="window index filter (floor(ts / window_s))")
    s.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                   help="differential folded output between two "
                        "window indexes (per-sample share)")
    s.add_argument("--speedscope", default=None, metavar="FILE",
                   help="export merged profile as speedscope JSON")
    s.add_argument("-o", "--output", default=None, metavar="FILE",
                   help="write merged collapsed-stack lines "
                        "(flamegraph.pl input)")
    s.add_argument("--top", type=int, default=15)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_profile)

    s = sub.add_parser("health",
                       help="overload + retry-plane health (budgets, "
                            "sheds, pressure, circuit breakers)")
    s.add_argument("--address", required=True)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_health)

    s = sub.add_parser(
        "top",
        help="live cluster view: nodes, tasks/s with sparkline, phase "
             "p95s, firing alerts, hottest flamegraph leaf")
    s.add_argument("--address", required=True)
    s.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    s.add_argument("--once", action="store_true",
                   help="render a single frame and exit")
    s.add_argument("--iterations", type=int, default=0,
                   help="exit after N frames (0 = until ^C)")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser(
        "alerts",
        help="SLO alert table from the burn-rate engine "
             "(--history adds resolved alerts)")
    s.add_argument("--address", required=True)
    s.add_argument("--history", action="store_true",
                   help="include the resolved-alert ring")
    s.add_argument("--format", choices=["table", "json"],
                   default="table")
    s.set_defaults(fn=cmd_alerts)

    s = sub.add_parser(
        "metrics",
        help="query the head's embedded metric history "
             "(raw 10s buckets 30min, 1min rollups 24h)")
    msub = s.add_subparsers(dest="metrics_cmd", required=True)
    m = msub.add_parser("query", help="range-query one series name")
    m.add_argument("name", help="series name, e.g. ray_tpu_phase_p95_seconds")
    m.add_argument("--address", required=True)
    m.add_argument("--label", action="append", metavar="K=V",
                   help="label filter (repeatable)")
    m.add_argument("--start", type=float, default=None,
                   help="unix start time (default: now - window)")
    m.add_argument("--end", type=float, default=None)
    m.add_argument("--step", type=float, default=None,
                   help="coalesce buckets to this resolution")
    m.add_argument("--window", type=float, default=600.0,
                   help="lookback seconds when --start is omitted")
    m.add_argument("--format", choices=["table", "json"],
                   default="table")
    s.set_defaults(fn=cmd_metrics)

    s = sub.add_parser(
        "lint",
        help="run the invariant cross-checkers (tools/rtlint): wire "
             "kinds, env knobs, locks, clocks, metrics, frame budget")
    s.add_argument("--root", default=None,
                   help="repo root to lint (default: this checkout)")
    s.add_argument("--baseline", default=None,
                   help="baseline.toml path ('' disables)")
    s.add_argument("--pass", dest="passes", action="append",
                   metavar="NAME", help="run only this pass (repeatable)")
    s.add_argument("--format", choices=("text", "json"), default="text")
    s.add_argument("--write-baseline", metavar="PATH")
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser("stop", help="stop all agents and the head")
    s.add_argument("--address", required=True)
    s.set_defaults(fn=cmd_stop)

    s = sub.add_parser("timeline")
    s.add_argument("--address", required=True)
    s.add_argument("-o", "--output", default="timeline.json")
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser("dashboard")
    s.add_argument("--address", required=True)
    s.add_argument("--port", type=int, default=0)
    s.set_defaults(fn=cmd_dashboard)

    s = sub.add_parser("job", help="job submission (submit/status/logs/stop/list)")
    jsub = s.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--address", required=True)
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("--address", required=True)
        j.add_argument("job_id")
    j = jsub.add_parser("list")
    j.add_argument("--address", required=True)
    s.set_defaults(fn=cmd_job)

    s = sub.add_parser("serve", help="model serving (deploy/status/shutdown)")
    ssub = s.add_subparsers(dest="serve_cmd", required=True)
    v = ssub.add_parser("deploy")
    v.add_argument("--address", required=True)
    v.add_argument("config_file")
    v = ssub.add_parser("run", help="deploy an import path (module:app)")
    v.add_argument("--address", required=True)
    v.add_argument("--route-prefix", default=None)
    v.add_argument("--blocking", action="store_true")
    v.add_argument("import_path")
    for name in ("status", "shutdown"):
        v = ssub.add_parser(name)
        v.add_argument("--address", required=True)
    s.set_defaults(fn=cmd_serve)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
