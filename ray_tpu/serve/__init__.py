"""ray_tpu.serve: model serving on the actor runtime.

Counterpart of the reference's python/ray/serve (SURVEY.md §3.5):
@serve.deployment classes scale as replica actors under a controller's
reconcile loop; DeploymentHandles route with power-of-two-choices; an
aiohttp proxy provides HTTP ingress; autoscaling follows ongoing-request
load."""

from ray_tpu.serve.api import (
    build,
    delete,
    get_app_handle,
    get_deployment_handle,
    get_grpc_port,
    get_proxy_port,
    run,
    run_from_config,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentConfig,
    deployment,
)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.replica import ReplicaContext, get_replica_context
from ray_tpu.serve.scheduler import (
    ContinuousBatcher,
    LatencyModel,
    get_request_deadline,
)

__all__ = [
    "batch",
    "ContinuousBatcher",
    "LatencyModel",
    "get_request_deadline",
    "get_multiplexed_model_id",
    "multiplexed",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "delete",
    "deployment",
    "get_deployment_handle",
    "get_grpc_port",
    "get_proxy_port",
    "get_app_handle",
    "get_replica_context",
    "ReplicaContext",
    "run",
    "run_from_config",
    "build",
    "shutdown",
    "start",
    "status",
]
