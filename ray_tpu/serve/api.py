"""serve public API: run / start / shutdown / handles / status.

Counterpart of the reference's serve/api.py (serve.run :591 →
client.deploy_application → ServeController) — SURVEY.md §3.5 call stack."""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.serve.controller import ServeController, _HandleMarker
from ray_tpu.serve.deployment import (Application, AutoscalingConfig,
                                      Deployment, DeploymentConfig)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.proxy import HTTPProxy

_controller = None
_proxy = None


def start(*, http_host: str = "127.0.0.1", http_port: int = 0, proxy: bool = True):
    """Ensure the controller (and optionally the HTTP proxy) are running."""
    global _controller, _proxy
    ray_tpu.api.auto_init()
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
        except (RayTpuError, ValueError):
            cls = ray_tpu.remote(num_cpus=0, max_concurrency=16, name="SERVE_CONTROLLER",
                                 namespace="serve")(ServeController)
            _controller = cls.remote()
            ray_tpu.get(_controller.ping.remote())  # wait until live
    if proxy and _proxy is None:
        cls = ray_tpu.remote(num_cpus=0, max_concurrency=32, name="SERVE_PROXY",
                             namespace="serve")(HTTPProxy)
        _proxy = cls.remote(http_host, http_port)
        ray_tpu.get(_proxy.ping.remote())
    return _controller


def _specs_from_app(app: Application, route_prefix: str | None) -> list[dict]:
    nodes = app.flatten()
    specs = []
    for node in nodes:
        dep: Deployment = node.deployment
        args = tuple(
            _HandleMarker(a.deployment.name) if isinstance(a, Application) else a
            for a in node.init_args
        )
        kwargs = {
            k: _HandleMarker(v.deployment.name) if isinstance(v, Application) else v
            for k, v in node.init_kwargs.items()
        }
        prefix = dep.route_prefix
        if node is nodes[-1]:  # ingress (root of the bind tree)
            prefix = route_prefix if route_prefix is not None else (prefix or "/")
        specs.append(
            {
                "name": dep.name,
                "cls": dep.cls,
                "config": dep.config,
                "init_args": args,
                "init_kwargs": kwargs,
                "route_prefix": prefix,
            }
        )
    return specs


def run(app: Application | Deployment, *, name: str | None = None,
        route_prefix: str | None = None, _blocking_ready: bool = True,
        proxy: bool = True) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment.

    An explicit ``name`` makes this a NAMED application (reference:
    multi-app serve.run(name=...)): several apps coexist on one cluster,
    each owning its deployments and route prefix; re-running a name
    REPLACES that app (its stale deployments are removed), names owned
    by other apps are protected, serve.delete(name) removes exactly it,
    and get_app_handle(name) resolves its ingress. Unnamed runs keep the
    additive single-app behavior (deployments accumulate under the
    "default" app)."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = start(proxy=proxy)
    specs = _specs_from_app(app, route_prefix)
    app_tag = name or "default"
    for s in specs:
        s["app"] = app_tag
    dep_names = {s["name"] for s in specs}
    existing = ray_tpu.get(controller.status.remote())
    # Ownership guard for ALL runs: an unnamed run must not silently
    # steal (and re-tag) a named app's deployment either.
    for dn, st in existing.items():
        owner = st.get("app")
        if dn in dep_names and owner not in (None, app_tag):
            raise ValueError(
                f"deployment name {dn!r} already belongs to "
                f"application {owner!r}")
    stale: list[str] = []
    if name is not None:
        stale = [dn for dn, st in existing.items()
                 if st.get("app") == name and dn not in dep_names]
    _deploy_specs(controller, specs, wait=_blocking_ready)
    for dn in stale:
        ray_tpu.get(controller.delete_deployment.remote(dn))
    ray_tpu.get(controller.set_app_ingress.remote(app_tag,
                                                  app.deployment.name))
    return DeploymentHandle(app.deployment.name)


def get_app_handle(name: str) -> DeploymentHandle:
    """Handle to a named application's ingress deployment (reference:
    serve.get_app_handle)."""
    controller = _resolve_controller()
    if controller is None:
        raise RayTpuError("serve is not running")
    ingress = ray_tpu.get(controller.get_app_ingress.remote(name))
    if ingress is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(ingress)


def _wait_ready(controller, name: str, timeout_s: float = 30.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = ray_tpu.get(controller.status.remote()).get(name)
        # Ready = first replica serving (full scale-out continues in the
        # background; reference serve.run readiness semantics).
        if st and st["running_replicas"] >= 1:
            return
        time.sleep(0.1)
    raise TimeoutError(f"deployment {name} not ready after {timeout_s}s")


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def get_proxy_port() -> int:
    if _proxy is None:
        raise RayTpuError("serve proxy not running")
    return ray_tpu.get(_proxy.get_port.remote())


def get_grpc_port() -> int:
    """Port of the gRPC ingress (reference: gRPCProxy); -1 if disabled."""
    if _proxy is None:
        raise RayTpuError("serve proxy not running")
    return ray_tpu.get(_proxy.get_grpc_port.remote())


def _resolve_controller():
    """Attach to a cluster's existing controller without creating one
    (read-only callers; cross-process CLI). Returns None if serve was
    never started."""
    global _controller
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor("SERVE_CONTROLLER",
                                            namespace="serve")
        except Exception:
            return None
    return _controller


def status() -> dict:
    controller = _resolve_controller()
    if controller is None:
        return {}  # serve not running — a status query must not start it
    return ray_tpu.get(controller.status.remote())


def delete(name: str) -> None:
    """Delete a named APPLICATION (all its deployments) if ``name``
    matches one (reference: serve.delete(app_name)); otherwise delete
    the single deployment of that name."""
    if _controller is not None:
        if ray_tpu.get(_controller.get_app_ingress.remote(name)) is not None:
            ray_tpu.get(_controller.delete_application.remote(name))
        else:
            ray_tpu.get(_controller.delete_deployment.remote(name))
        if _proxy is not None:
            routes = ray_tpu.get(_controller.get_routes.remote())
            ray_tpu.get(_proxy.update_routes.remote(routes))


def shutdown() -> None:
    global _controller, _proxy
    # A fresh process (CLI `serve shutdown`) attaches to the cluster's
    # controller by name first — shutdown must work cross-process.
    _resolve_controller()
    if _controller is not None:
        try:
            ray_tpu.get(_controller.shutdown_deployments.remote(), timeout=30)
        except RayTpuError:
            pass
        finally:
            # The controller must die even if draining timed out: its
            # reconcile loop is already stopped, and a live-but-stopped
            # named actor would be reused as a zombie by the next start().
            try:
                ray_tpu.kill(_controller)
            except RayTpuError:
                pass
        _controller = None
    if _proxy is None:
        # Cross-process: the proxy is a named actor too.
        try:
            _proxy = ray_tpu.get_actor("SERVE_PROXY", namespace="serve")
        except Exception:
            _proxy = None
    if _proxy is not None:
        try:
            ray_tpu.kill(_proxy)
        except RayTpuError:
            pass
        _proxy = None


# -- declarative config (reference: serve/schema.py ServeDeploySchema,
#    `serve build` / `serve deploy` config files) -------------------------


def _import_path(path: str):
    """Resolve a dotted path where the module/attribute boundary is
    unknown (nested classes: 'pkg.mod.Outer.Inner'): import the longest
    importable module prefix, then getattr the rest."""
    import importlib

    parts = path.split(".")
    for i in range(len(parts) - 1, 0, -1):
        mod_name = ".".join(parts[:i])
        try:
            obj = importlib.import_module(mod_name)
        except ModuleNotFoundError as e:
            # Only swallow "this prefix is not a module"; a missing
            # dependency INSIDE a located module is the user's real error.
            if e.name == mod_name or (e.name and mod_name.startswith(e.name + ".")):
                continue
            raise
        for attr in parts[i:]:
            obj = getattr(obj, attr)
        return obj
    raise ImportError(f"cannot resolve import path {path!r}")


def _deploy_specs(controller, specs: list[dict], *, wait: bool = True) -> None:
    """Shared deploy + proxy-route push + readiness wait (used by run()
    and run_from_config())."""
    ray_tpu.get(controller.deploy_application.remote(specs))
    if _proxy is not None:
        routes = ray_tpu.get(controller.get_routes.remote())
        ray_tpu.get(_proxy.update_routes.remote(routes))
    if wait and specs:
        _wait_ready(controller, specs[-1]["name"])


def build(app: "Application | Deployment", *, route_prefix: str | None = None,
          name: str = "default") -> dict:
    """Emit a declarative, JSON/YAML-serializable config for an app
    (reference: serve build — serve/scripts.py). Deployment classes must
    be importable (module.ClassName); handles between deployments are
    encoded as {"__handle__": name} markers."""
    if isinstance(app, Deployment):
        app = app.bind()
    specs = _specs_from_app(app, route_prefix)
    deployments = []
    for spec in specs:
        cls = spec["cls"]
        if cls.__module__ in ("__main__", None) or "<locals>" in cls.__qualname__:
            raise ValueError(
                f"deployment {spec['name']!r} class must be importable "
                f"(module.ClassName) to build a config; move it out of "
                f"__main__ / function scope"
            )
        cfg = spec["config"]

        def enc(v):
            if isinstance(v, _HandleMarker):
                return {"__handle__": v.name}
            return v

        deployments.append({
            "name": spec["name"],
            "import_path": f"{cls.__module__}.{cls.__qualname__}",
            "num_replicas": cfg.num_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
            "autoscaling_config": (
                dataclasses.asdict(cfg.autoscaling_config)
                if cfg.autoscaling_config else None
            ),
            "ray_actor_options": dict(cfg.ray_actor_options),
            "route_prefix": spec["route_prefix"],
            "init_args": [enc(a) for a in spec["init_args"]],
            "init_kwargs": {k: enc(v) for k, v in spec["init_kwargs"].items()},
        })
    return {"applications": [{"name": name, "deployments": deployments}]}


def run_from_config(config: dict | str, *, proxy: bool = True) -> None:
    """Deploy from a config produced by ``build`` (or hand-written YAML/
    JSON; reference: `serve deploy config.yaml`). ``config`` may be a
    dict, a path to a .yaml/.json file, or a JSON string."""
    import json as _json
    import os as _os

    if isinstance(config, str):
        if _os.path.exists(config):
            with open(config) as f:
                text = f.read()
            if config.endswith((".yaml", ".yml")):
                import yaml

                config = yaml.safe_load(text)
            else:
                config = _json.loads(text)
        else:
            config = _json.loads(config)
    controller = start(proxy=proxy)
    for app in config["applications"]:
        specs = []
        for d in app["deployments"]:
            cls = _import_path(d["import_path"])
            if isinstance(cls, Deployment):
                # Import paths usually name the @serve.deployment-decorated
                # module attribute; the replica needs the inner class.
                cls = cls.cls

            def dec(v):
                if isinstance(v, dict) and set(v) == {"__handle__"}:
                    return _HandleMarker(v["__handle__"])
                return v

            auto = d.get("autoscaling_config")
            cfg = DeploymentConfig(
                num_replicas=d.get("num_replicas", 1),
                max_ongoing_requests=d.get("max_ongoing_requests", 16),
                autoscaling_config=(AutoscalingConfig(**auto) if auto else None),
                ray_actor_options=d.get("ray_actor_options", {}),
            )
            specs.append({
                "name": d["name"],
                "cls": cls,
                "config": cfg,
                "init_args": tuple(dec(a) for a in d.get("init_args", [])),
                "init_kwargs": {k: dec(v)
                                for k, v in d.get("init_kwargs", {}).items()},
                "route_prefix": d.get("route_prefix"),
            })
        app_name = app.get("name", "default")
        for s in specs:
            s["app"] = app_name
        # Same semantics as run(name=...): a declarative deploy must not
        # steal another app's deployments, and REPLACES its own app —
        # deployments dropped from the config are removed.
        dep_names = {s["name"] for s in specs}
        existing = ray_tpu.get(controller.status.remote())
        for dn, st in existing.items():
            owner = st.get("app")
            if dn in dep_names and owner not in (None, app_name):
                raise ValueError(
                    f"deployment name {dn!r} already belongs to "
                    f"application {owner!r}")
        stale = [dn for dn, st in existing.items()
                 if st.get("app") == app_name and dn not in dep_names]
        _deploy_specs(controller, specs)
        for dn in stale:
            ray_tpu.get(controller.delete_deployment.remote(dn))
        if specs:
            # Ingress = the routed deployment (or the last listed one),
            # registered so get_app_handle(name) works for declarative
            # deploys too.
            ingress = next((s["name"] for s in specs
                            if s.get("route_prefix")), specs[-1]["name"])
            ray_tpu.get(controller.set_app_ingress.remote(app_name, ingress))
