"""@serve.batch — transparent request coalescing, continuously batched.

Counterpart of the reference's python/ray/serve/batching.py: an async
method decorated with ``@serve.batch`` receives LISTS of the items its
callers passed individually; concurrent calls enqueue and a per-instance
``ContinuousBatcher`` (serve/scheduler.py) assembles batches. Unlike the
old one-shot flusher there is NO drain barrier: batch N+1 admits and
launches while batch N still executes, batch size adapts to the observed
exec p95 under ``target_latency_slo_s``, deadline-expired requests are
shed from the queue with a typed ``TaskTimeoutError``, and a bounded
queue sheds with ``PendingCallsLimitError`` (HTTP 503 at the proxy). On
TPU this is the serving throughput lever: one batched forward pass feeds
the MXU a [B, ...] matmul instead of B vector ones.

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=8, target_latency_slo_s=0.1)
        async def __call__(self, inputs: list) -> list:
            return self.model(np.stack(inputs)).tolist()
"""

from __future__ import annotations

import asyncio
import functools
import weakref
from typing import Callable

from ray_tpu.serve.scheduler import ContinuousBatcher, get_request_deadline

_FREE = object()  # key for free-function (unbound) batch state


def batch(_fn: Callable | None = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01,
          target_latency_slo_s: "float | None" = None,
          max_concurrent_batches: "int | None" = None,
          max_queue_len: "int | None" = None):
    """Decorate an async function/method taking a LIST of items and
    returning a list of results of the same length. Callers invoke it
    with a SINGLE item and await their own result (reference:
    serve/batching.py @serve.batch).

    ``target_latency_slo_s`` turns on SLO-aware sizing: batch size
    adapts to the largest size whose observed exec p95 fits the SLO.
    ``max_concurrent_batches`` bounds overlapping batches (None =
    unbounded, the legacy flusher's behavior). ``max_queue_len`` bounds
    the wait queue — past it submissions shed with
    ``PendingCallsLimitError`` instead of queueing unboundedly."""

    def decorator(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError(
                "@serve.batch requires an async def function (it awaits "
                f"the batch on the replica event loop); got {fn!r}"
            )
        # Keyed by id(instance) for IDENTITY semantics (a WeakKeyDict
        # would collapse __eq__-equal instances into one shared state and
        # reject __slots__ classes), with a weakref finalizer shutting
        # the batcher down and removing the entry at collection — the
        # finalizer runs before the id can be recycled, so a new
        # instance at the same address can never inherit a dead
        # instance's pending items/futures. Instances that cannot be
        # weak-referenced are pinned instead (bounded leak beats a
        # wrong-self flush).
        batchers: dict = {}
        pins: dict = {}

        def _batcher_for(inst) -> ContinuousBatcher:
            key = _FREE if inst is _FREE else id(inst)
            b = batchers.get(key)
            if b is None:
                call = fn if inst is _FREE else functools.partial(fn, inst)
                b = batchers[key] = ContinuousBatcher(
                    call,
                    max_batch_size=max_batch_size,
                    batch_wait_timeout_s=batch_wait_timeout_s,
                    target_latency_slo_s=target_latency_slo_s,
                    max_concurrent_batches=max_concurrent_batches,
                    max_queue_len=max_queue_len,
                    name=fn.__name__)
                if inst is not _FREE:
                    def _finalize(key=key):
                        gone = batchers.pop(key, None)
                        if gone is not None:
                            gone.shutdown_threadsafe()
                    try:
                        weakref.finalize(inst, _finalize)
                    except TypeError:
                        pins[key] = inst
            return b

        @functools.wraps(fn)
        async def wrapper(*args):
            # Bound method: args = (self, item); free function: (item,).
            if len(args) == 2:
                inst, item = args
            elif len(args) == 1:
                inst, item = _FREE, args[0]
            else:
                raise TypeError(
                    "@serve.batch methods take exactly one request item"
                )
            batcher = _batcher_for(inst)
            # The caller's deadline (handle timeout_s → TaskSpec
            # deadline → replica contextvar) rides into the queue so
            # assembly can shed expired work; the ambient trace context
            # (adopted from the spec around task execution) rides along
            # so each coalesced item keeps its own span inside the
            # shared batch-exec span.
            from ray_tpu._private import worker_context

            fut = batcher.submit(item, deadline=get_request_deadline(),
                                 trace_ctx=worker_context
                                 .get_trace_context())
            return await fut

        wrapper._ray_tpu_serve_batch = True  # introspection/testing
        wrapper._ray_tpu_batchers = batchers
        return wrapper

    if _fn is not None:  # bare @serve.batch
        return decorator(_fn)
    return decorator


def batchers_of(instance) -> "list[ContinuousBatcher]":
    """Every live ContinuousBatcher owned by ``instance`` (one per
    decorated method that has been called). Used by the replica for
    telemetry (queue depth, batch-size p50) and teardown."""
    out = []
    seen = set()
    for name in dir(type(instance)):
        fn = getattr(type(instance), name, None)
        states = getattr(fn, "_ray_tpu_batchers", None)
        if states:
            b = states.get(id(instance))
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                out.append(b)
    return out


def shutdown_batchers(instance) -> None:
    """Cancel scheduler/batch tasks and queued futures for every batcher
    of ``instance`` — replica teardown calls this so no orphaned asyncio
    task survives the event loop (pytest teardown warnings)."""
    for b in batchers_of(instance):
        b.shutdown()
