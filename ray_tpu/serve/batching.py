"""@serve.batch — transparent request coalescing.

Counterpart of the reference's python/ray/serve/batching.py: an async
method decorated with ``@serve.batch`` receives LISTS of the items its
callers passed individually; concurrent calls enqueue, and a flusher
invokes the wrapped function once per batch of up to ``max_batch_size``
items (or whatever arrived within ``batch_wait_timeout_s`` of the first
item). On TPU this is the serving throughput lever: one batched forward
pass feeds the MXU a [B, ...] matmul instead of B vector ones.

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        async def __call__(self, inputs: list) -> list:
            return self.model(np.stack(inputs)).tolist()
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable


class _BatchState:
    """Per-(instance, method) pending batch."""

    __slots__ = ("items", "futures", "flusher", "pin")

    def __init__(self):
        self.items: list = []
        self.futures: list = []
        self.flusher: asyncio.Task | None = None
        # Only set for non-weakref-able instances: pins the instance so
        # its id() can never be recycled onto this state (see _state_for).
        self.pin = None


def batch(_fn: Callable | None = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate an async function/method taking a LIST of items and
    returning a list of results of the same length. Callers invoke it
    with a SINGLE item and await their own result (reference:
    serve/batching.py @serve.batch)."""

    def decorator(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError(
                "@serve.batch requires an async def function (it awaits "
                f"the batch on the replica event loop); got {fn!r}"
            )
        # Keyed by id(instance) for IDENTITY semantics (a WeakKeyDict
        # would collapse __eq__-equal instances into one shared state and
        # reject __slots__ classes), with a weakref finalizer removing
        # the entry at collection — the finalizer runs before the id can
        # be recycled, so a new instance at the same address can never
        # inherit a dead instance's pending items/futures. Instances
        # that cannot be weak-referenced are pinned instead (bounded
        # leak beats a wrong-self flush).
        import weakref

        states: dict[int, _BatchState] = {}
        free_state = _BatchState()  # free functions share one batch

        def _state_for(inst) -> _BatchState:
            key = id(inst)
            st = states.get(key)
            if st is None:
                st = states[key] = _BatchState()
                try:
                    weakref.finalize(inst, states.pop, key, None)
                except TypeError:
                    st.pin = inst
            return st

        async def flush_after_wait(state: _BatchState, bound_args):
            try:
                await asyncio.sleep(batch_wait_timeout_s)
            except asyncio.CancelledError:
                return  # a full batch already flushed
            _flush(state, bound_args)

        def _flush(state: _BatchState, bound_args) -> None:
            items, futures = state.items, state.futures
            state.items, state.futures = [], []
            if state.flusher is not None:
                state.flusher.cancel()
                state.flusher = None
            if not items:
                return
            asyncio.ensure_future(_run_batch(items, futures, bound_args))

        async def _run_batch(items, futures, bound_args) -> None:
            try:
                results = await fn(*bound_args, items)
                if results is None or len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function {fn.__name__} returned "
                        f"{0 if results is None else len(results)} results "
                        f"for a batch of {len(items)}"
                    )
                for f, r in zip(futures, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:  # noqa: BLE001
                for f in futures:
                    if not f.done():
                        f.set_exception(e)

        @functools.wraps(fn)
        async def wrapper(*args):
            # Bound method: args = (self, item); free function: (item,).
            if len(args) == 2:
                bound_args, item = (args[0],), args[1]
                state = _state_for(args[0])
            elif len(args) == 1:
                bound_args, item = (), args[0]
                state = free_state
            else:
                raise TypeError(
                    "@serve.batch methods take exactly one request item"
                )
            fut = asyncio.get_running_loop().create_future()
            state.items.append(item)
            state.futures.append(fut)
            if len(state.items) >= max_batch_size:
                _flush(state, bound_args)
            elif state.flusher is None or state.flusher.done():
                state.flusher = asyncio.ensure_future(
                    flush_after_wait(state, bound_args))
            return await fut

        wrapper._ray_tpu_serve_batch = True  # introspection/testing
        return wrapper

    if _fn is not None:  # bare @serve.batch
        return decorator(_fn)
    return decorator
