"""ServeController: deployment state reconciliation.

Counterpart of the reference's ServeController actor
(serve/_private/controller.py:84) with the DeploymentState FSM
(deployment_state.py:1249,2330): a background reconcile loop drives each
deployment's replica set toward its target (scale up/down, replace dead
replicas, autoscale from ongoing-request metrics). Handles/proxies read
the versioned routing table (`get_replicas`) — the pull analogue of the
reference's LongPollHost config pushdown (long_poll.py:204).

Serving-plane additions:

* metrics-driven autoscaling — the target follows ongoing requests AND
  replica-reported batch queue depth, with ingress QPS tracked from
  replica totals; upscale is HELD while the PR 5 overload plane reports
  memory-pressured nodes (adding replicas to a cluster already shedding
  by watermark makes the spiral worse);
* scale-down DRAINS — a doomed replica stops admitting, finishes its
  in-flight requests (``Replica.drain``), and only then is killed;
* device-cache-aware placement (PR 8) — when a deployment's init args
  carry ObjectRefs (model weights by reference), new replicas prefer
  the node already holding the payload (soft node affinity), so scale-up
  hits the zero-copy host arena instead of re-pulling weights;
* ``ray_tpu_serve_*`` gauges pushed every reconcile tick (qps, queue
  depth, batch size p50, shed total, replicas) for the Prometheus
  exposition and the Grafana serving row;
* a best-effort ``autoscaler.sdk.request_resources`` hint when the
  replica target grows, so cluster autoscaling can add capacity ahead
  of placement.
"""

from __future__ import annotations

import math
import threading
import time
import uuid
from typing import Any

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.serve.deployment import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.replica import Replica

# Bound on how long a scale-down drain may hold a doomed replica alive.
DRAIN_TIMEOUT_S = 10.0


class _HandleMarker:
    """Placeholder for a child deployment in init args (resolved to a
    DeploymentHandle inside the replica process)."""

    def __init__(self, name: str):
        self.name = name


class _DeploymentState:
    def __init__(self, spec: dict):
        self.spec = spec
        self.config: DeploymentConfig = spec["config"]
        self.replicas: dict[str, Any] = {}  # rid -> ActorHandle
        self.draining: dict[str, tuple] = {}  # rid -> (actor, ref, deadline)
        self.version = 0
        self.last_metrics: dict[str, dict] = {}
        self.target = self.config.num_replicas
        asc = self.config.autoscaling_config
        if asc is not None:
            self.target = max(asc.min_replicas, min(self.config.num_replicas, asc.max_replicas))
        self._last_downscale = time.monotonic()
        # Ingress QPS estimated from replica request totals.
        self.qps = 0.0
        self._prev_total = 0
        self._prev_total_t = time.monotonic()

    def status(self) -> dict:
        return {
            "name": self.spec["name"],
            "target_replicas": self.target,
            "running_replicas": len(self.replicas),
            "draining_replicas": len(self.draining),
            "version": self.version,
            "qps": round(self.qps, 2),
            "qdepth": sum(m.get("qdepth", 0)
                          for m in self.last_metrics.values()),
        }


class ServeController:
    """Runs as a named actor (SERVE_CONTROLLER @ namespace 'serve')."""

    RECONCILE_PERIOD_S = 0.25

    def __init__(self):
        self._deployments: dict[str, _DeploymentState] = {}
        # Named applications (reference: multi-app serve): app name ->
        # ingress deployment name. Deployment specs carry their owning
        # app under spec["app"].
        self._apps: dict[str, str] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._mem_checked = 0.0
        self._mem_pressured_cached = False
        self._gauges = None
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._thread.start()

    # -- API (called by serve.run / handles / proxy) -----------------------

    def deploy_application(self, specs: list[dict]) -> None:
        """Deploy/refresh deployments (children-first order from
        Application.flatten)."""
        with self._lock:
            for spec in specs:
                existing = self._deployments.get(spec["name"])
                if existing is not None:
                    # Config update: keep replicas, adopt new target;
                    # code changes take effect on replica replacement.
                    existing.spec = spec
                    existing.config = spec["config"]
                    existing.target = spec["config"].num_replicas
                else:
                    self._deployments[spec["name"]] = _DeploymentState(spec)
        self._reconcile_once()  # synchronous first pass: fast readiness

    def get_replicas(self, deployment_name: str) -> dict:
        with self._lock:
            st = self._deployments.get(deployment_name)
            if st is None:
                raise RayTpuError(f"no deployment named {deployment_name!r}")
            return {
                "version": st.version,
                "replicas": [(rid, actor) for rid, actor in st.replicas.items()],
                # Per-replica load view for the handle's routing score
                # (queue depth the owner-side direct plane cannot see).
                "telemetry": {
                    rid: {"qdepth": m.get("qdepth", 0),
                          "ongoing": m.get("ongoing", 0)}
                    for rid, m in st.last_metrics.items()
                    if rid in st.replicas
                },
            }

    def get_routes(self) -> dict[str, dict]:
        """prefix -> {"name", "sse_method", "ws_method", "ws_stream"}.

        ``sse_method`` names an async-generator method the HTTP proxy
        should dispatch Accept: text/event-stream requests to (e.g. the
        OpenAI ``stream_events`` protocol handler); None = stream
        __call__. ``ws_method`` names a ``ws_message`` handler that
        makes the route WebSocket-upgradable (reference: serve's
        FastAPI websocket ingress — serve/_private/http_util.py ASGI
        passthrough); ``ws_stream`` is True when it is an async
        generator (each yielded item becomes one outbound frame per
        inbound message)."""
        import inspect

        with self._lock:
            routes = {}
            for st in self._deployments.values():
                prefix = st.spec.get("route_prefix")
                if not prefix:
                    continue
                cls = st.spec.get("cls")
                sse = None
                if cls is not None and inspect.isasyncgenfunction(
                        getattr(cls, "stream_events", None)):
                    sse = "stream_events"
                ws = getattr(cls, "ws_message", None) if cls else None
                pathm = getattr(cls, "route_request", None) if cls else None
                routes[prefix] = {
                    "name": st.spec["name"],
                    "sse_method": sse,
                    "ws_method": "ws_message" if callable(ws) else None,
                    "ws_stream": bool(ws) and inspect.isasyncgenfunction(ws),
                    # Path-aware ingress (reference: real URL routing in
                    # the serve ASGI app): non-streaming requests go to
                    # route_request(subpath, payload) when declared.
                    "path_method": "route_request" if callable(pathm)
                    else None,
                }
            return routes

    def status(self) -> dict:
        with self._lock:
            out = {}
            for name, st in self._deployments.items():
                s = st.status()
                s["app"] = st.spec.get("app")
                out[name] = s
            return out

    def set_app_ingress(self, app: str, ingress: str) -> None:
        with self._lock:
            self._apps[app] = ingress

    def get_app_ingress(self, app: str) -> "str | None":
        with self._lock:
            return self._apps.get(app)

    def list_applications(self) -> dict:
        with self._lock:
            return {
                app: {
                    "ingress": ingress,
                    "deployments": [n for n, st in self._deployments.items()
                                    if st.spec.get("app") == app],
                }
                for app, ingress in self._apps.items()
            }

    def delete_application(self, app: str) -> None:
        with self._lock:
            names = [n for n, st in self._deployments.items()
                     if st.spec.get("app") == app]
            self._apps.pop(app, None)
        for n in names:
            self.delete_deployment(n)

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            st = self._deployments.pop(name, None)
        if st is not None:
            for actor in st.replicas.values():
                self._kill(actor)
            for actor, _ref, _dl in st.draining.values():
                self._kill(actor)

    def shutdown_deployments(self) -> None:
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete_deployment(n)
        self._stop.set()

    def ping(self) -> str:
        return "pong"

    # -- reconciliation ----------------------------------------------------

    def _reconcile_loop(self) -> None:
        while not self._stop.wait(self.RECONCILE_PERIOD_S):
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                import traceback

                traceback.print_exc()

    def _reconcile_once(self) -> None:
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._probe_health(st)
            self._autoscale(st)
            self._scale_to_target(st)
            self._reap_draining(st)
        self._export_metrics(states)

    def _probe_health(self, st: _DeploymentState) -> None:
        dead = []
        refs = []
        with self._lock:
            items = list(st.replicas.items())
        for rid, actor in items:
            try:
                refs.append((rid, actor.get_metrics.remote()))
            except RayTpuError:
                dead.append(rid)
        # One bounded wait for the whole replica set — a saturated replica
        # costs the loop at most the deadline, not deadline × replicas.
        if refs:
            ready, _ = ray_tpu.wait(
                [ref for _, ref in refs], num_returns=len(refs), timeout=5.0
            )
            ready_set = {r.hex() for r in ready}
            for rid, ref in refs:
                if ref.hex() not in ready_set:
                    # Slow ≠ dead (busy replica, or still constructing on
                    # a loaded host). Dead workers fail fast — the head
                    # errors their pending calls on disconnect.
                    continue
                try:
                    st.last_metrics[rid] = ray_tpu.get(ref)
                except RayTpuError:
                    dead.append(rid)
        if dead:
            with self._lock:
                for rid in dead:
                    actor = st.replicas.pop(rid, None)
                    st.last_metrics.pop(rid, None)
                    if actor is not None:
                        self._kill(actor)
                st.version += 1
        # Ingress QPS from replica totals (cumulative, so replica death
        # can dip the sum — clamp at zero and resync).
        total = sum(m.get("total", 0) for m in st.last_metrics.values())
        now = time.monotonic()
        dt = now - st._prev_total_t
        if dt >= 1.0:
            st.qps = max(0.0, (total - st._prev_total) / dt)
            st._prev_total, st._prev_total_t = total, now

    def _mem_pressured(self) -> bool:
        """PR 5 overload signal, cached ~1 s: any node over the soft
        memory watermark. While pressured, upscale is held — more
        replicas on a spilling cluster amplify the pressure."""
        now = time.monotonic()
        if now - self._mem_checked < 1.0:
            return self._mem_pressured_cached
        self._mem_checked = now
        try:
            from ray_tpu._private.worker_context import global_runtime

            stats = global_runtime().conn.call("runtime_stats", {}, timeout=5)
            gauges = stats.get("gauges") or {}
            self._mem_pressured_cached = bool(
                gauges.get("mem_pressured_nodes", 0))
        except Exception:  # noqa: BLE001 — no signal = not pressured
            self._mem_pressured_cached = False
        return self._mem_pressured_cached

    def _autoscale(self, st: _DeploymentState) -> None:
        asc: AutoscalingConfig | None = st.config.autoscaling_config
        if asc is None:
            return
        # Load = executing requests + replica-side batch queue depth:
        # a replica admitting into a deep batch queue is loaded even
        # while its ongoing count looks tame.
        ongoing = sum(m.get("ongoing", 0) for m in st.last_metrics.values())
        qdepth = sum(m.get("qdepth", 0) for m in st.last_metrics.values())
        desired = math.ceil(
            (ongoing + qdepth) / max(asc.target_ongoing_requests, 1e-9))
        desired = max(asc.min_replicas, min(asc.max_replicas, desired))
        now = time.monotonic()
        if desired > st.target:
            if self._mem_pressured():
                return  # hold: scaling into memory pressure makes it worse
            st.target = desired  # upscale immediately
            st._last_downscale = now
            try:
                # Cluster-autoscaler hint: ask for capacity to fit the
                # new target ahead of placement (best-effort).
                from ray_tpu.autoscaler import sdk as autoscaler_sdk

                autoscaler_sdk.request_resources(num_cpus=desired)
            except Exception:  # noqa: BLE001
                pass
        elif desired < st.target:
            if now - st._last_downscale >= asc.downscale_delay_s:
                st.target = max(desired, st.target - 1)  # step down gently
                st._last_downscale = now
        else:
            st._last_downscale = now

    def _scale_to_target(self, st: _DeploymentState) -> None:
        with self._lock:
            current = len(st.replicas)
            if current < st.target:
                for _ in range(st.target - current):
                    rid, actor = self._start_replica(st)
                    st.replicas[rid] = actor
                st.version += 1
            elif current > st.target:
                doomed = list(st.replicas)[st.target - current:]
                for rid in doomed:
                    actor = st.replicas.pop(rid)
                    st.last_metrics.pop(rid, None)
                    # Drain before kill: the version bump re-routes new
                    # traffic away; in-flight requests finish on the
                    # doomed replica, which is reaped once drained (or
                    # at the drain deadline).
                    try:
                        ref = actor.drain.remote(timeout_s=DRAIN_TIMEOUT_S)
                    except RayTpuError:
                        ref = None
                    st.draining[rid] = (
                        actor, ref, time.monotonic() + DRAIN_TIMEOUT_S + 2.0)
                st.version += 1

    def _reap_draining(self, st: _DeploymentState) -> None:
        with self._lock:
            items = list(st.draining.items())
        for rid, (actor, ref, deadline) in items:
            done = time.monotonic() > deadline
            if not done and ref is not None:
                try:
                    ready, _ = ray_tpu.wait([ref], timeout=0)
                    done = bool(ready)
                except RayTpuError:
                    done = True  # replica died mid-drain: just reap
            if done:
                with self._lock:
                    st.draining.pop(rid, None)
                self._kill(actor)

    def _start_replica(self, st: _DeploymentState) -> tuple[str, Any]:
        spec = st.spec
        rid = f"{spec['name']}#{uuid.uuid4().hex[:6]}"
        opts = dict(spec["config"].ray_actor_options)
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = max(2, spec["config"].max_ongoing_requests)
        if "scheduling_strategy" not in opts:
            node = self._weights_node(spec)
            if node:
                from ray_tpu.util.scheduling_strategies import (
                    NodeAffinitySchedulingStrategy,
                )

                opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                    node, soft=True)
        actor_cls = ray_tpu.remote(**opts)(Replica)
        init_args = tuple(self._resolve(a) for a in spec["init_args"])
        init_kwargs = {k: self._resolve(v) for k, v in spec["init_kwargs"].items()}
        actor = actor_cls.remote(
            spec["cls"], init_args, init_kwargs, spec["name"], rid,
            max_ongoing_requests=spec["config"].max_ongoing_requests,
            max_queued_requests=getattr(
                spec["config"], "max_queued_requests", None))
        return rid, actor

    @staticmethod
    def _weights_node(spec: dict) -> "str | None":
        """Device-cache-aware placement (PR 8): when init args carry
        ObjectRefs (model weights passed by reference), prefer the node
        already holding the payload — its host arena / device cache
        serves the weights zero-copy instead of re-pulling them."""
        from ray_tpu._private.ids import ObjectRef

        for a in (list(spec.get("init_args") or ())
                  + list((spec.get("init_kwargs") or {}).values())):
            if not isinstance(a, ObjectRef):
                continue
            try:
                from ray_tpu.util import state as us

                row = us.get_object(a.hex())
            except Exception:  # noqa: BLE001 — placement hint only
                return None
            if not row:
                continue
            node = row.get("node_id") or row.get("location")
            if not node:
                reps = row.get("replicas") or []
                node = reps[0] if reps else None
            if node:
                return node
        return None

    # -- metrics exposition ------------------------------------------------

    def _export_metrics(self, states: list) -> None:
        """Push the serving-plane gauges (Prometheus `ray_tpu_serve_*`,
        Grafana serving row) once per reconcile tick — cheap sets, the
        metric layer amortizes the actual head casts."""
        try:
            g = self._gauges
            if g is None:
                from ray_tpu.util.metrics import Gauge

                g = self._gauges = {
                    "qps": Gauge("ray_tpu_serve_qps",
                                 "Ingress requests/s per deployment",
                                 tag_keys=("deployment",)),
                    "qdepth": Gauge("ray_tpu_serve_queue_depth",
                                    "Replica batch-queue depth",
                                    tag_keys=("deployment",)),
                    "batch_p50": Gauge("ray_tpu_serve_batch_size_p50",
                                       "Median assembled batch size",
                                       tag_keys=("deployment",)),
                    "shed": Gauge("ray_tpu_serve_shed_total",
                                  "Requests shed (deadline/queue-full)",
                                  tag_keys=("deployment",)),
                    "replicas": Gauge("ray_tpu_serve_replicas",
                                      "Running replicas",
                                      tag_keys=("deployment",)),
                    "ongoing": Gauge("ray_tpu_serve_ongoing",
                                     "Executing requests",
                                     tag_keys=("deployment",)),
                }
            for st in states:
                tags = {"deployment": st.spec["name"]}
                metrics = list(st.last_metrics.values())
                g["qps"].set(st.qps, tags)
                g["qdepth"].set(
                    sum(m.get("qdepth", 0) for m in metrics), tags)
                sizes = [m.get("batch_size_p50", 0.0) for m in metrics
                         if m.get("batch_size_p50")]
                g["batch_p50"].set(max(sizes) if sizes else 0.0, tags)
                g["shed"].set(
                    sum(m.get("shed_total", 0) for m in metrics), tags)
                g["replicas"].set(len(st.replicas), tags)
                g["ongoing"].set(
                    sum(m.get("ongoing", 0) for m in metrics), tags)
        except Exception:  # noqa: BLE001 — telemetry must not stall serving
            pass

    @staticmethod
    def _resolve(arg):
        if isinstance(arg, _HandleMarker):
            return DeploymentHandle(arg.name)
        return arg

    @staticmethod
    def _kill(actor) -> None:
        try:
            ray_tpu.kill(actor)
        except RayTpuError:
            pass
