"""ServeController: deployment state reconciliation.

Counterpart of the reference's ServeController actor
(serve/_private/controller.py:84) with the DeploymentState FSM
(deployment_state.py:1249,2330): a background reconcile loop drives each
deployment's replica set toward its target (scale up/down, replace dead
replicas, autoscale from ongoing-request metrics). Handles/proxies read
the versioned routing table (`get_replicas`) — the pull analogue of the
reference's LongPollHost config pushdown (long_poll.py:204)."""

from __future__ import annotations

import math
import threading
import time
import uuid
from typing import Any

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.serve.deployment import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.replica import Replica


class _HandleMarker:
    """Placeholder for a child deployment in init args (resolved to a
    DeploymentHandle inside the replica process)."""

    def __init__(self, name: str):
        self.name = name


class _DeploymentState:
    def __init__(self, spec: dict):
        self.spec = spec
        self.config: DeploymentConfig = spec["config"]
        self.replicas: dict[str, Any] = {}  # rid -> ActorHandle
        self.version = 0
        self.last_metrics: dict[str, dict] = {}
        self.target = self.config.num_replicas
        asc = self.config.autoscaling_config
        if asc is not None:
            self.target = max(asc.min_replicas, min(self.config.num_replicas, asc.max_replicas))
        self._last_downscale = time.monotonic()

    def status(self) -> dict:
        return {
            "name": self.spec["name"],
            "target_replicas": self.target,
            "running_replicas": len(self.replicas),
            "version": self.version,
        }


class ServeController:
    """Runs as a named actor (SERVE_CONTROLLER @ namespace 'serve')."""

    RECONCILE_PERIOD_S = 0.25

    def __init__(self):
        self._deployments: dict[str, _DeploymentState] = {}
        # Named applications (reference: multi-app serve): app name ->
        # ingress deployment name. Deployment specs carry their owning
        # app under spec["app"].
        self._apps: dict[str, str] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._thread.start()

    # -- API (called by serve.run / handles / proxy) -----------------------

    def deploy_application(self, specs: list[dict]) -> None:
        """Deploy/refresh deployments (children-first order from
        Application.flatten)."""
        with self._lock:
            for spec in specs:
                existing = self._deployments.get(spec["name"])
                if existing is not None:
                    # Config update: keep replicas, adopt new target;
                    # code changes take effect on replica replacement.
                    existing.spec = spec
                    existing.config = spec["config"]
                    existing.target = spec["config"].num_replicas
                else:
                    self._deployments[spec["name"]] = _DeploymentState(spec)
        self._reconcile_once()  # synchronous first pass: fast readiness

    def get_replicas(self, deployment_name: str) -> dict:
        with self._lock:
            st = self._deployments.get(deployment_name)
            if st is None:
                raise RayTpuError(f"no deployment named {deployment_name!r}")
            return {
                "version": st.version,
                "replicas": [(rid, actor) for rid, actor in st.replicas.items()],
            }

    def get_routes(self) -> dict[str, dict]:
        """prefix -> {"name", "sse_method", "ws_method", "ws_stream"}.

        ``sse_method`` names an async-generator method the HTTP proxy
        should dispatch Accept: text/event-stream requests to (e.g. the
        OpenAI ``stream_events`` protocol handler); None = stream
        __call__. ``ws_method`` names a ``ws_message`` handler that
        makes the route WebSocket-upgradable (reference: serve's
        FastAPI websocket ingress — serve/_private/http_util.py ASGI
        passthrough); ``ws_stream`` is True when it is an async
        generator (each yielded item becomes one outbound frame per
        inbound message)."""
        import inspect

        with self._lock:
            routes = {}
            for st in self._deployments.values():
                prefix = st.spec.get("route_prefix")
                if not prefix:
                    continue
                cls = st.spec.get("cls")
                sse = None
                if cls is not None and inspect.isasyncgenfunction(
                        getattr(cls, "stream_events", None)):
                    sse = "stream_events"
                ws = getattr(cls, "ws_message", None) if cls else None
                pathm = getattr(cls, "route_request", None) if cls else None
                routes[prefix] = {
                    "name": st.spec["name"],
                    "sse_method": sse,
                    "ws_method": "ws_message" if callable(ws) else None,
                    "ws_stream": bool(ws) and inspect.isasyncgenfunction(ws),
                    # Path-aware ingress (reference: real URL routing in
                    # the serve ASGI app): non-streaming requests go to
                    # route_request(subpath, payload) when declared.
                    "path_method": "route_request" if callable(pathm)
                    else None,
                }
            return routes

    def status(self) -> dict:
        with self._lock:
            out = {}
            for name, st in self._deployments.items():
                s = st.status()
                s["app"] = st.spec.get("app")
                out[name] = s
            return out

    def set_app_ingress(self, app: str, ingress: str) -> None:
        with self._lock:
            self._apps[app] = ingress

    def get_app_ingress(self, app: str) -> "str | None":
        with self._lock:
            return self._apps.get(app)

    def list_applications(self) -> dict:
        with self._lock:
            return {
                app: {
                    "ingress": ingress,
                    "deployments": [n for n, st in self._deployments.items()
                                    if st.spec.get("app") == app],
                }
                for app, ingress in self._apps.items()
            }

    def delete_application(self, app: str) -> None:
        with self._lock:
            names = [n for n, st in self._deployments.items()
                     if st.spec.get("app") == app]
            self._apps.pop(app, None)
        for n in names:
            self.delete_deployment(n)

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            st = self._deployments.pop(name, None)
        if st is not None:
            for actor in st.replicas.values():
                self._kill(actor)

    def shutdown_deployments(self) -> None:
        with self._lock:
            names = list(self._deployments)
        for n in names:
            self.delete_deployment(n)
        self._stop.set()

    def ping(self) -> str:
        return "pong"

    # -- reconciliation ----------------------------------------------------

    def _reconcile_loop(self) -> None:
        while not self._stop.wait(self.RECONCILE_PERIOD_S):
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                import traceback

                traceback.print_exc()

    def _reconcile_once(self) -> None:
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._probe_health(st)
            self._autoscale(st)
            self._scale_to_target(st)

    def _probe_health(self, st: _DeploymentState) -> None:
        dead = []
        refs = []
        with self._lock:
            items = list(st.replicas.items())
        for rid, actor in items:
            try:
                refs.append((rid, actor.get_metrics.remote()))
            except RayTpuError:
                dead.append(rid)
        # One bounded wait for the whole replica set — a saturated replica
        # costs the loop at most the deadline, not deadline × replicas.
        if refs:
            ready, _ = ray_tpu.wait(
                [ref for _, ref in refs], num_returns=len(refs), timeout=5.0
            )
            ready_set = {r.hex() for r in ready}
            for rid, ref in refs:
                if ref.hex() not in ready_set:
                    # Slow ≠ dead (busy replica, or still constructing on
                    # a loaded host). Dead workers fail fast — the head
                    # errors their pending calls on disconnect.
                    continue
                try:
                    st.last_metrics[rid] = ray_tpu.get(ref)
                except RayTpuError:
                    dead.append(rid)
        if dead:
            with self._lock:
                for rid in dead:
                    actor = st.replicas.pop(rid, None)
                    st.last_metrics.pop(rid, None)
                    if actor is not None:
                        self._kill(actor)
                st.version += 1

    def _autoscale(self, st: _DeploymentState) -> None:
        asc: AutoscalingConfig | None = st.config.autoscaling_config
        if asc is None:
            return
        ongoing = sum(m.get("ongoing", 0) for m in st.last_metrics.values())
        desired = math.ceil(ongoing / max(asc.target_ongoing_requests, 1e-9))
        desired = max(asc.min_replicas, min(asc.max_replicas, desired))
        now = time.monotonic()
        if desired > st.target:
            st.target = desired  # upscale immediately
            st._last_downscale = now
        elif desired < st.target:
            if now - st._last_downscale >= asc.downscale_delay_s:
                st.target = max(desired, st.target - 1)  # step down gently
                st._last_downscale = now
        else:
            st._last_downscale = now

    def _scale_to_target(self, st: _DeploymentState) -> None:
        with self._lock:
            current = len(st.replicas)
            if current < st.target:
                for _ in range(st.target - current):
                    rid, actor = self._start_replica(st)
                    st.replicas[rid] = actor
                st.version += 1
            elif current > st.target:
                doomed = list(st.replicas)[st.target - current:]
                for rid in doomed:
                    actor = st.replicas.pop(rid)
                    st.last_metrics.pop(rid, None)
                    self._kill(actor)
                st.version += 1

    def _start_replica(self, st: _DeploymentState) -> tuple[str, Any]:
        spec = st.spec
        rid = f"{spec['name']}#{uuid.uuid4().hex[:6]}"
        opts = dict(spec["config"].ray_actor_options)
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = max(2, spec["config"].max_ongoing_requests)
        actor_cls = ray_tpu.remote(**opts)(Replica)
        init_args = tuple(self._resolve(a) for a in spec["init_args"])
        init_kwargs = {k: self._resolve(v) for k, v in spec["init_kwargs"].items()}
        actor = actor_cls.remote(
            spec["cls"], init_args, init_kwargs, spec["name"], rid,
            max_ongoing_requests=spec["config"].max_ongoing_requests)
        return rid, actor

    @staticmethod
    def _resolve(arg):
        if isinstance(arg, _HandleMarker):
            return DeploymentHandle(arg.name)
        return arg

    @staticmethod
    def _kill(actor) -> None:
        try:
            ray_tpu.kill(actor)
        except RayTpuError:
            pass
