"""Deployment descriptors: @serve.deployment, .bind(), .options().

Counterpart of the reference's serve/deployment.py (Deployment dataclass +
decorator) and the DAG-building `.bind()` API (serve/api.py). A bound
deployment (Application) is a tree: init args may themselves be bound
deployments — the controller materializes children first and injects
DeploymentHandles (model composition, reference: handle.py:625)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Reference: serve/config.py AutoscalingConfig."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    # Bounded replica-side admission: past max_ongoing + max_queued the
    # replica sheds with PendingCallsLimitError (HTTP 503). None =
    # unbounded queueing (legacy behavior).
    max_queued_requests: Optional[int] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: dict = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 2.0


class Deployment:
    """An undeployed class + config (reference: serve/deployment.py)."""

    def __init__(self, cls: type, name: str, config: DeploymentConfig,
                 route_prefix: str | None = None):
        self.cls = cls
        self.name = name
        self.config = config
        self.route_prefix = route_prefix
        self.__name__ = name

    def options(self, *, num_replicas: int | None = None, name: str | None = None,
                max_ongoing_requests: int | None = None,
                max_queued_requests: int | None = None,
                autoscaling_config: AutoscalingConfig | dict | None = None,
                ray_actor_options: dict | None = None,
                route_prefix: str | None = None) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        return Deployment(self.cls, name or self.name, cfg,
                          route_prefix if route_prefix is not None else self.route_prefix)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name}, replicas={self.config.num_replicas})"


class Application:
    """A bound deployment graph node (reference: serve/_private/build_app —
    the result of .bind(), accepted by serve.run)."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs

    def children(self) -> list["Application"]:
        out = []
        for a in list(self.init_args) + list(self.init_kwargs.values()):
            if isinstance(a, Application):
                out.append(a)
        return out

    def flatten(self) -> list["Application"]:
        """Post-order: children before parents (deploy order)."""
        seen: list[Application] = []

        def visit(node: "Application"):
            for c in node.children():
                visit(c)
            if node not in seen:
                seen.append(node)

        visit(self)
        return seen


def deployment(cls: type | None = None, *, name: str | None = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               max_queued_requests: int | None = None,
               autoscaling_config: AutoscalingConfig | dict | None = None,
               ray_actor_options: dict | None = None,
               route_prefix: str | None = None) -> Any:
    """``@serve.deployment`` / ``@serve.deployment(num_replicas=3)``."""

    def wrap(c: type) -> Deployment:
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            autoscaling_config=asc,
            ray_actor_options=ray_actor_options or {},
        )
        return Deployment(c, name or c.__name__, cfg, route_prefix)

    if cls is not None:
        return wrap(cls)
    return wrap
