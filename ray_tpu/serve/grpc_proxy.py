"""gRPC ingress for Serve.

Counterpart of the reference's gRPCProxy (reference: serve/_private/
proxy.py:534 gRPCProxy; user-defined protos served next to HTTP). Here
the service is schema-light: one unary-unary method

    /ray_tpu.serve.Ingress/Predict

with JSON (or cloudpickle) request bytes and the target deployment given
in request metadata (``deployment`` key) or as a JSON envelope
{"deployment": ..., "payload": ...}. Responses mirror the request
encoding. Runs inside the same proxy actor as the HTTP ingress, sharing
its DeploymentHandle routing (power-of-two replica choice).
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Any

from ray_tpu.serve.handle import DeploymentHandle

SERVICE = "ray_tpu.serve.Ingress"
METHOD = "Predict"


def _json_default(o):
    """numpy-aware JSON fallback, mirroring HTTPProxy._encode."""
    import numpy as np

    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    raise TypeError(f"not JSON serializable: {type(o)}")


class GrpcIngress:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._handles: dict[str, DeploymentHandle] = {}
        self._routes: dict[str, str] = {}

        def predict(request: bytes, context) -> bytes:
            meta = dict(context.invocation_metadata())
            encoding = meta.get("encoding", "json")
            deployment = meta.get("deployment")
            payload: Any
            try:
                if encoding == "pickle":
                    import cloudpickle

                    payload = cloudpickle.loads(request)
                else:
                    payload = json.loads(request) if request else {}
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"bad {encoding} request body: {e}")
            # Envelope form ONLY when the dict explicitly carries a
            # 'deployment' key — a user payload that merely contains a
            # 'payload' key must pass through untouched.
            if (deployment is None and isinstance(payload, dict)
                    and "deployment" in payload):
                deployment = payload["deployment"]
                payload = payload.get("payload", {})
            handle = self._resolve(deployment)
            if handle is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no deployment {deployment!r}; known: {sorted(self._routes.values())}",
                )
            # Per-request deadline rides the gRPC deadline when the
            # client set one (context.time_remaining), default 60 s —
            # stamped onto the TaskSpec like the HTTP ingress does.
            remaining = None
            try:
                remaining = context.time_remaining()
            except Exception:  # noqa: BLE001
                pass
            timeout_s = min(remaining, 600.0) if remaining else 60.0
            try:
                result = handle.options(timeout_s=timeout_s).remote(
                    payload).result(timeout_s=timeout_s + 5.0)
            except Exception as e:  # noqa: BLE001
                context.abort(*self._classify(grpc, e))
            try:
                if encoding == "pickle":
                    from ray_tpu._private.serialization import dumps_scoped

                    return dumps_scoped(result)
                return json.dumps(result, default=_json_default).encode()
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL,
                              f"response not {encoding}-serializable: {e}")

        handler = grpc.method_handlers_generic_handler(
            SERVICE,
            {
                METHOD: grpc.unary_unary_rpc_method_handler(
                    predict,
                    request_deserializer=None,  # raw bytes
                    response_serializer=None,
                ),
            },
        )
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((handler,))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    @staticmethod
    def _classify(grpc, e: Exception):
        """Typed overload mapping, mirroring HTTPProxy._error_response:
        admission sheds → RESOURCE_EXHAUSTED, deadline sheds →
        DEADLINE_EXCEEDED, everything else INTERNAL. Replica-raised
        errors arrive as TaskError (sealed repr), hence the string
        match beside the isinstance checks."""
        from ray_tpu.exceptions import (
            PendingCallsLimitError,
            TaskTimeoutError,
        )

        msg = str(e)
        if isinstance(e, PendingCallsLimitError) \
                or "PendingCallsLimitError" in msg:
            return grpc.StatusCode.RESOURCE_EXHAUSTED, msg
        if isinstance(e, (TaskTimeoutError, TimeoutError)) \
                or "TaskTimeoutError" in msg:
            return grpc.StatusCode.DEADLINE_EXCEEDED, msg
        return grpc.StatusCode.INTERNAL, msg

    def _resolve(self, deployment: str | None) -> DeploymentHandle | None:
        if deployment is None:
            # Single-route apps: default to the only deployment.
            targets = set(self._routes.values())
            if len(targets) == 1:
                deployment = next(iter(targets))
            else:
                return None
        if deployment not in set(self._routes.values()):
            return None
        h = self._handles.get(deployment)
        if h is None:
            h = self._handles[deployment] = DeploymentHandle(deployment)
        return h

    def update_routes(self, routes: dict[str, str]) -> None:
        self._routes = dict(routes)
        for name in list(self._handles):
            if name not in set(routes.values()):
                del self._handles[name]

    def get_port(self) -> int:
        return self._port

    def stop(self) -> None:
        self._server.stop(grace=0.5)


def grpc_request(address: str, payload: Any, *, deployment: str | None = None,
                 encoding: str = "json", timeout_s: float = 60.0) -> Any:
    """Client helper (the reference exposes generated stubs; this is the
    stub equivalent for the schema-light service)."""
    import grpc

    channel = grpc.insecure_channel(address)
    try:
        if encoding == "pickle":
            from ray_tpu._private.serialization import dumps_scoped

            body = dumps_scoped(payload)
        else:
            body = json.dumps(payload).encode()
        callable_ = channel.unary_unary(f"/{SERVICE}/{METHOD}")
        metadata = [("encoding", encoding)]
        if deployment:
            metadata.append(("deployment", deployment))
        reply = callable_(body, metadata=metadata, timeout=timeout_s)
        if encoding == "pickle":
            import cloudpickle

            return cloudpickle.loads(reply)
        return json.loads(reply)
    finally:
        channel.close()
