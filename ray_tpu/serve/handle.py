"""DeploymentHandle: client-side router to a deployment's replicas.

Counterpart of the reference's DeploymentHandle (serve/handle.py:625) and
the power-of-two-choices replica scheduler
(serve/_private/replica_scheduler/pow_2_scheduler.py): pick two random
replicas, route to the one with the lower load score. Replica-set changes
propagate by version polling against the controller — the long-poll
(long_poll.py:204) analogue with a pull cadence.

Load-aware routing (serving plane): the score is NOT just this handle's
submitted count. It folds in

* the direct plane's owner-side view (``DirectPlane.route_load``):
  calls pushed but not yet delivery-ACKED weigh heavily — a dead or
  restarting replica stops acking within one RTT, so power-of-two
  deprioritizes it immediately instead of letting it absorb half the
  flood until the next controller refresh; owner-queued calls behind
  the direct window count too;
* replica-reported queue depth from the controller's telemetry table
  (batch queues the owner cannot see).

``options(timeout_s=...)`` stamps a per-request deadline onto the
TaskSpec (PR 5): expired requests are shed at every hop — owner queue,
worker pickup, replica pickup, batch assembly — with a typed
``TaskTimeoutError`` instead of queueing unboundedly."""

from __future__ import annotations

import random
import threading
import time
from typing import Any

import ray_tpu
from ray_tpu.exceptions import ActorError, RayTpuError

# Weight of an unacked pushed call in the routing score: one unacked
# call outweighs several submitted-and-acked ones, so a replica that
# stopped acking (dead, wedged, restarting) loses power-of-two contests
# right away.
_UNACKED_WEIGHT = 8


class DeploymentResponse:
    """Future for one request (reference: handle.py DeploymentResponse).

    If the routed-to replica died before completing, `result()` transparently
    re-issues the request through the handle (the reference router's
    retry-on-replica-death behavior)."""

    def __init__(self, ref, on_done=None, retry=None):
        self._ref = ref
        self._on_done = on_done
        self._retry = retry
        self._done = False

    def result(self, timeout_s: float | None = None) -> Any:
        try:
            value = ray_tpu.get(self._ref, timeout=timeout_s)
        except ActorError:
            if self._finish() and self._retry is not None:
                nxt = self._retry()
                if nxt is not None:
                    return nxt.result(timeout_s=timeout_s)
            raise
        self._finish()
        return value

    def _finish(self) -> bool:
        if not self._done:
            self._done = True
            if self._on_done is not None:
                self._on_done()
            return True
        return False

    @property
    def ref(self):
        """The underlying ObjectRef (composition: pass to other calls)."""
        return self._ref

    def cancel(self) -> None:
        """Best-effort cancel of the in-flight replica call (direct-plane
        cancel first, head fallback). The proxy maps client disconnects
        here so abandoned requests stop burning replica capacity."""
        self._finish()
        try:
            ray_tpu.cancel(self._ref)
        except Exception:  # noqa: BLE001 — cancel is advisory
            pass

    async def _result_async(self, timeout_s: float | None = None) -> Any:
        """Truly async result: awaits the head-pushed object resolution
        (runtime.get_async) — no thread parked for the request's
        lifetime, which is what lets one proxy process hold hundreds of
        in-flight requests (reference: serve/_private/proxy.py:754 fully
        async proxy). Replica-death retry re-routes like result()."""
        import asyncio

        from ray_tpu._private.worker_context import global_runtime

        try:
            fut = asyncio.wrap_future(global_runtime().get_async(self._ref))
            value = await asyncio.wait_for(fut, timeout_s)
        except ActorError:
            if self._finish() and self._retry is not None:
                # retry() may force-refresh against the controller
                # (blocking RPC): keep it off the loop.
                loop = asyncio.get_running_loop()
                nxt = await loop.run_in_executor(None, self._retry)
                if nxt is not None:
                    return await nxt._result_async(timeout_s)
            raise
        self._finish()
        return value

    def __await__(self):
        """Awaitable inside async deployments and the proxy (reference:
        DeploymentHandle responses are awaitable in replica code)."""
        return self._result_async().__await__()


_ASTOP = object()  # end-of-stream sentinel for async iteration


class DeploymentResponseGenerator:
    """Streaming response: iterate to receive items as the replica's
    generator produces them (reference: DeploymentResponseGenerator,
    serve/handle.py — streaming handle results)."""

    def __init__(self, ref_gen, on_done=None):
        self._gen = ref_gen
        self._on_done = on_done
        self._finished = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu

        try:
            return ray_tpu.get(next(self._gen))
        except StopIteration:
            self._finish()
            raise
        except Exception:
            self._finish()
            raise

    def _finish(self):
        if not self._finished:
            self._finished = True
            if self._on_done is not None:
                self._on_done()

    def __aiter__(self):
        return self

    async def __anext__(self):
        """Async iteration for async deployments and the SSE proxy:
        awaits head-pushed item readiness (no thread parked per item).
        Falls back to an executor step for generators lacking the async
        protocol (e.g. a plain iterator injected in tests)."""
        import asyncio

        next_async = getattr(self._gen, "next_ref_async", None)
        if next_async is None:
            def step():
                try:
                    return self.__next__()
                except StopIteration:
                    return _ASTOP

            loop = asyncio.get_event_loop()
            item = await loop.run_in_executor(None, step)
            if item is _ASTOP:
                raise StopAsyncIteration
            return item
        from ray_tpu._private.worker_context import global_runtime

        try:
            ref = await next_async()
        except Exception:
            self._finish()
            raise
        if ref is None:
            self._finish()
            raise StopAsyncIteration
        try:
            return await asyncio.wrap_future(global_runtime().get_async(ref))
        except Exception:
            self._finish()
            raise

    def close(self):
        """Release routing accounting when abandoning the stream early
        (for ... break). Also fired by GC as a backstop."""
        self._finish()

    def __del__(self):
        try:
            self._finish()
        except Exception:
            pass


class DeploymentHandle:
    _REFRESH_S = 1.0

    def __init__(self, deployment_name: str, controller=None, method: str = "__call__"):
        self.deployment_name = deployment_name
        self._method = method
        self._controller = controller
        self._replicas: list = []
        self._version = -1
        self._last_refresh = 0.0
        self._inflight: dict[str, int] = {}
        self._reported: dict[str, int] = {}  # rid -> controller-reported qdepth
        self._lock = threading.Lock()
        self._stream = False
        self._model_id = ""  # multiplexing (serve/multiplex.py)
        self._timeout_s: "float | None" = None
        self._max_retries = 2

    # -- controller discovery (lazy: handles are cheap to pickle) ----------

    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
        return self._controller

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and self._replicas and now - self._last_refresh < self._REFRESH_S:
            return
        info = ray_tpu.get(
            self._get_controller().get_replicas.remote(self.deployment_name)
        )
        with self._lock:
            self._replicas = info["replicas"]
            self._version = info["version"]
            self._last_refresh = now
            self._inflight = {
                rid: self._inflight.get(rid, 0) for rid, _ in self._replicas
            }
            self._reported = {
                rid: int(t.get("qdepth", 0))
                for rid, t in (info.get("telemetry") or {}).items()
            }

    # -- routing -----------------------------------------------------------

    def _load(self, rid: str, actor) -> int:
        """Routing score for one replica: this handle's submitted count
        + controller-reported batch queue depth + the direct plane's
        owner-side view, with UNACKED pushes weighted heavily (acked
        inflight is the real signal — a dead replica's submitted count
        would otherwise drain to zero on error callbacks and make it
        look idle)."""
        load = self._inflight.get(rid, 0) + self._reported.get(rid, 0)
        try:
            from ray_tpu._private.worker_context import global_runtime

            plane = getattr(global_runtime(), "_direct", None)
            if plane is not None:
                rl = plane.route_load(actor._actor_id)
                load += rl["queued"] + _UNACKED_WEIGHT * rl["unacked"]
        except Exception:  # noqa: BLE001 — scoring must never fail a route
            pass
        return load

    def _pick(self):
        """Power-of-two-choices over per-replica load scores; a
        multiplexed model id instead routes by rendezvous hashing so the
        model's replica-local cache keeps hitting (serve/multiplex.py)."""
        with self._lock:
            reps = list(self._replicas)
        if not reps:
            raise RayTpuError(
                f"deployment {self.deployment_name!r} has no running replicas"
            )
        if self._model_id:
            from ray_tpu.serve.multiplex import rendezvous_pick

            return rendezvous_pick(reps, self._model_id)
        if len(reps) == 1:
            return reps[0]
        a, b = random.sample(reps, 2)
        return a if self._load(a[0], a[1]) <= self._load(b[0], b[1]) else b

    def options(self, *, method_name: str | None = None,
                stream: bool | None = None,
                multiplexed_model_id: str | None = None,
                timeout_s: float | None = None,
                max_retries: int | None = None) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name or self._method)
        h._stream = self._stream if stream is None else stream
        h._model_id = (self._model_id if multiplexed_model_id is None
                       else multiplexed_model_id)
        h._timeout_s = self._timeout_s if timeout_s is None else timeout_s
        h._max_retries = (self._max_retries if max_retries is None
                          else max(0, int(max_retries)))
        # Share router state with the parent: the replica cache stays warm
        # (no per-call controller RPC) and power-of-two choices sees ALL
        # in-flight requests, not just this method-view's.
        h._replicas, h._version = self._replicas, self._version
        h._last_refresh = self._last_refresh
        h._inflight = self._inflight
        h._reported = self._reported
        h._lock = self._lock
        return h

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        # Cache the method view: repeated h.method.remote() calls reuse one
        # child handle, so its replica cache warms up instead of being
        # rebuilt (and re-fetched from the controller) per call.
        child = self.options(method_name=name)
        self.__dict__[name] = child
        return child

    def remote(self, *args, _retries_left: "int | None" = None,
               **kwargs) -> DeploymentResponse:
        self._refresh()
        if _retries_left is None:
            _retries_left = self._max_retries
        # Unwrap response objects for composition: pass the underlying ref
        # so the downstream task consumes the upstream output directly.
        args = tuple(a.ref if isinstance(a, DeploymentResponse) else a for a in args)
        kwargs = {k: (v.ref if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        # timeout_s → TaskSpec deadline: the wall-clock deadline rides
        # BOTH the spec (owner/head/worker hops shed expired work) and
        # the replica call payload (replica pickup + batch assembly).
        deadline = (time.time() + self._timeout_s
                    if self._timeout_s else None)

        def retry() -> "DeploymentResponse | None":
            if _retries_left <= 0:
                return None
            self._refresh(force=True)
            return self.remote(*args, _retries_left=_retries_left - 1, **kwargs)
        last_err: Exception | None = None
        for _ in range(3):  # retry across replica death
            try:
                rid, actor = self._pick()
            except RayTpuError as e:
                # Replica set may be mid-rollout: force-refresh and retry.
                last_err = e
                time.sleep(0.2)
                self._refresh(force=True)
                continue
            with self._lock:
                self._inflight[rid] = self._inflight.get(rid, 0) + 1

            def done(rid=rid):
                with self._lock:
                    self._inflight[rid] = max(0, self._inflight.get(rid, 0) - 1)

            try:
                if self._stream:
                    # Streaming: the replica's generator method returns an
                    # ObjectRefGenerator; items surface as produced.
                    m = actor.handle_request_streaming
                    if self._timeout_s:
                        m = m.options(timeout_s=self._timeout_s)
                    gen = m.remote(
                        self._method, args, kwargs, self._model_id, deadline
                    )
                    return DeploymentResponseGenerator(gen, on_done=done)
                m = actor.handle_request
                if self._timeout_s:
                    m = m.options(timeout_s=self._timeout_s)
                ref = m.remote(
                    self._method, args, kwargs, self._model_id, deadline)
                return DeploymentResponse(
                    ref, on_done=done,
                    retry=retry if _retries_left > 0 else None)
            except ActorError as e:
                done()
                last_err = e
                self._refresh(force=True)
        raise last_err or RayTpuError("routing failed")

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, None, self._method))
