"""Model multiplexing — many models behind one deployment.

Counterpart of the reference's python/ray/serve/multiplex.py +
serve.get_multiplexed_model_id(): a deployment declares an async model
loader with ``@serve.multiplexed(max_num_models_per_replica=N)``; each
replica keeps an LRU cache of N loaded models, and requests carry a
``multiplexed_model_id`` (set via
``handle.options(multiplexed_model_id=...)``) that the replica exposes
through ``serve.get_multiplexed_model_id()``.

Routing affinity: the handle routes a model id to a stable replica via
rendezvous (highest-random-weight) hashing over the current replica set,
so repeated requests for one model land where it is already loaded while
different models spread across replicas — no control-plane reporting
loop needed (design difference vs the reference's pushed model-id
state; same cache-hit outcome under a stable replica set).

    @serve.deployment(num_replicas=2)
    class LoRAServer:
        @serve.multiplexed(max_num_models_per_replica=3)
        async def get_model(self, model_id: str):
            return load_lora(model_id)

        async def __call__(self, payload):
            model = await self.get_model(serve.get_multiplexed_model_id())
            return model(payload)
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import weakref
from collections import OrderedDict
from typing import Callable

_request_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (reference:
    serve.get_multiplexed_model_id)."""
    return _request_model_id.get()


def _set_request_model_id(model_id: str):
    return _request_model_id.set(model_id)


class _ModelCache:
    """Per-(instance, loader) LRU of loaded models; one load at a time
    per model id (concurrent requests for the same id await one load)."""

    def __init__(self, max_models: int):
        self.max_models = max_models
        self.models: OrderedDict = OrderedDict()
        self.loading: dict[str, asyncio.Future] = {}

    async def get(self, loader, bound_args, model_id: str):
        if model_id in self.models:
            self.models.move_to_end(model_id)
            return self.models[model_id]
        pending = self.loading.get(model_id)
        if pending is not None:
            return await asyncio.shield(pending)
        fut = asyncio.get_running_loop().create_future()
        self.loading[model_id] = fut
        try:
            model = await loader(*bound_args, model_id)
            while len(self.models) >= self.max_models:
                # LRU eviction: dropping our reference lets CPython
                # finalize the model (its __del__ runs then, matching
                # the reference's eviction hook timing).
                self.models.popitem(last=False)
            self.models[model_id] = model
            fut.set_result(model)
            return model
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)
            raise
        finally:
            self.loading.pop(model_id, None)
            if not fut.done():  # defensive: never leave waiters hanging
                fut.cancel()


class _InstanceRegistry(weakref.WeakKeyDictionary):
    """Weakref-keyed cache registry that pickles as EMPTY: caches are
    per-process state (weakrefs and loaded models don't travel), and a
    deployment class defined in a driver script is shipped to replicas
    by value — the registry must not drag dead-process caches along."""

    def __reduce__(self):
        return (self.__class__, ())


class _FallbackRegistry(dict):
    """id()-keyed fallback registry; same pickle-as-empty contract."""

    def __reduce__(self):
        return (self.__class__, ())


def multiplexed(_fn: Callable | None = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate an async model loader taking a model id (reference:
    serve/multiplex.py @serve.multiplexed)."""

    def decorator(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError(
                "@serve.multiplexed requires an async def loader; got "
                f"{fn!r}"
            )
        # Bound loaders key their cache by a weakref to the instance:
        # an id()-keyed dict is never pruned, so entries leak across
        # replica instance lifetimes, and a recycled id() can hand a
        # fresh instance a dead instance's cache. The id-keyed fallback
        # survives only for unbound loaders (key 0) and instances that
        # cannot be weak-referenced (e.g. __slots__ without __weakref__).
        caches: _InstanceRegistry = _InstanceRegistry()
        fallback_caches: _FallbackRegistry = _FallbackRegistry()

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                bound_args, model_id = (args[0],), args[1]
                registry, key = caches, args[0]
            elif len(args) == 1:
                bound_args, model_id = (), args[0]
                registry, key = fallback_caches, 0
            else:
                raise TypeError(
                    "@serve.multiplexed loaders take exactly one model id"
                )
            if not model_id:
                raise ValueError(
                    "no model id: pass one explicitly or set it on the "
                    "handle via .options(multiplexed_model_id=...)"
                )
            try:
                cache = registry.get(key)
            except TypeError:  # non-weakrefable instance
                registry, key = fallback_caches, id(args[0])
                cache = registry.get(key)
            if cache is None:
                cache = _ModelCache(max_num_models_per_replica)
                registry[key] = cache
            return await cache.get(fn, bound_args, model_id)

        wrapper._ray_tpu_serve_multiplexed = True
        wrapper._model_caches = caches
        wrapper._model_caches_fallback = fallback_caches
        return wrapper

    if _fn is not None:
        return decorator(_fn)
    return decorator


def rendezvous_pick(replicas: list, model_id: str):
    """Highest-random-weight choice of replica for a model id — stable
    under replica-set changes (only keys owned by a removed replica
    move)."""
    import hashlib

    def weight(rid: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(f"{rid}|{model_id}".encode(),
                            digest_size=8).digest(), "big")

    return max(replicas, key=lambda r: weight(r[0]))
