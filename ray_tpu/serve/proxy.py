"""HTTP proxy: the ingress actor.

Counterpart of the reference's HTTPProxy/ProxyActor (serve/_private/proxy.py
:754,:1131 — uvicorn/ASGI). Here: an aiohttp server on its own event-loop
thread inside a proxy actor. Routes come from the controller's route table
(route_prefix → deployment); requests are routed through a DeploymentHandle
(power-of-two choices) and awaited without blocking the loop.

Serving-plane duties at the ingress hop:

* deadline stamping — ``X-Request-Timeout-S`` (default 30 s) becomes the
  request deadline the handle stamps onto the TaskSpec, so expired work
  sheds at every hop (owner queue, head, worker pickup, batch queue);
* typed overload mapping — ``PendingCallsLimitError`` (admission shed)
  → HTTP 503 with Retry-After, ``TaskTimeoutError`` (deadline shed) →
  HTTP 408; everything else stays 500;
* client-disconnect propagation — when the HTTP client goes away the
  awaiting coroutine is cancelled and the proxy forwards the cancel to
  the in-flight replica call (``ray_tpu.cancel``), so abandoned work
  stops burning replica capacity (reference: serve/_private/proxy.py
  disconnect handling)."""

from __future__ import annotations

import asyncio
import json
import os
import socket
import threading
import time
from typing import Any

from ray_tpu._private import traceplane, worker_context
from ray_tpu.serve.handle import DeploymentHandle


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._handles: dict[str, DeploymentHandle] = {}
        self._routes: dict[str, str] = {}
        self._port = port
        self._ready = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        # Bind the socket synchronously so get_port is correct immediately.
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True, name="http-proxy")
        self._thread.start()
        self._ready.wait(timeout=10)
        # gRPC ingress beside HTTP (reference: gRPCProxy in the same
        # proxy actor, serve/_private/proxy.py:534); optional — absent
        # grpcio just disables the listener.
        self._grpc = None
        try:
            from ray_tpu.serve.grpc_proxy import GrpcIngress

            self._grpc = GrpcIngress(host)
        except ImportError:
            pass  # grpcio not installed: HTTP-only
        except Exception as e:  # noqa: BLE001 — real failures must be visible
            import sys

            print(f"[serve] gRPC ingress failed to start: {e!r}", file=sys.stderr)

    # -- control -----------------------------------------------------------

    def get_port(self) -> int:
        return self._port

    def get_grpc_port(self) -> int:
        return self._grpc.get_port() if self._grpc is not None else -1

    def update_routes(self, routes: dict) -> None:
        """route_prefix -> {"name", "sse_method"} (pushed by
        serve.run/delete; legacy plain-string values are normalized).
        Handles are populated BEFORE the route table swap (requests
        racing this update must never see a route without a handle),
        and stale handles are dropped."""
        routes = {prefix: (v if isinstance(v, dict)
                           else {"name": v, "sse_method": None})
                  for prefix, v in routes.items()}
        handles = {
            v["name"]: self._handles.get(v["name"])
            or DeploymentHandle(v["name"])
            for v in routes.values()
        }
        self._handles.update(handles)
        self._routes = dict(routes)
        for name in list(self._handles):
            if name not in handles:
                del self._handles[name]
        if self._grpc is not None:
            self._grpc.update_routes(
                {prefix: v["name"] for prefix, v in routes.items()})

    def ping(self) -> str:
        return "pong"

    # -- server ------------------------------------------------------------

    def _serve(self) -> None:
        from aiohttp import web

        async def handle(request: "web.Request") -> "web.Response":
            path = request.path.rstrip("/") or "/"
            meta = self._match_route(path)
            if meta is None:
                return web.json_response(
                    {"error": f"no route for {path}"}, status=404
                )
            name = meta["name"]
            if request.method == "POST":
                raw = await request.read()
                try:
                    payload = json.loads(raw) if raw else {}
                except json.JSONDecodeError:
                    payload = raw.decode()
            else:
                payload = dict(request.query)
            handle_ = self._handles.get(name)
            if handle_ is None:
                # Route table swapped concurrently (serve.delete race).
                return web.json_response(
                    {"error": f"no route for {path}"}, status=404
                )
            if (request.headers.get("Upgrade", "").lower() == "websocket"
                    and meta.get("ws_method")):
                return await self._handle_ws(web, request, handle_, meta)
            # Request tracing: mint (or adopt X-Request-Id as) the trace
            # at the ingress hop; the context rides every nested
            # .remote() via the ambient contextvar, and the trace id is
            # echoed back as X-Trace-Id for client-side correlation.
            trace_ctx = traceplane.mint_trace(
                request.headers.get("X-Request-Id"))
            t0 = time.time()
            wants_sse = ("text/event-stream" in request.headers.get("Accept", "")
                         or (isinstance(payload, dict)
                             and payload.get("stream") is True
                             and meta.get("sse_method")))
            if wants_sse:
                # SSE streaming: each yielded item becomes one `data:`
                # event as produced. Deployments declaring a dedicated
                # async-generator protocol handler (sse_method, e.g.
                # the OpenAI `stream_events`) get SSE routed there —
                # their __call__ stays a plain JSON method; otherwise
                # __call__ itself must be a generator.
                return await self._stream_sse(
                    web, request, handle_, payload,
                    method=meta.get("sse_method"), trace_ctx=trace_ctx,
                    t0=t0)
            # Per-request deadline: the handle stamps it onto the
            # TaskSpec so expired requests shed at every hop instead of
            # completing into the void.
            try:
                timeout_s = float(
                    request.headers.get("X-Request-Timeout-S", 30.0))
            except (TypeError, ValueError):
                timeout_s = 30.0
            timeout_s = max(0.001, min(timeout_s, 600.0))
            resp_obj = None
            try:
                # Submit via a SHORT executor hop (routing can hit a
                # blocking controller refresh ~1/s), then await the
                # result fully async: an in-flight request holds no
                # thread (reference: serve/_private/proxy.py:754 async
                # proxy). Concurrency is bounded by memory, not pool
                # size.
                loop = asyncio.get_running_loop()
                if meta.get("path_method"):
                    # Path-aware deployment: it receives the subpath
                    # below its route prefix plus the payload — real
                    # URL routing (reference: serve's ASGI app routes).
                    prefix = meta.get("_prefix", "/")
                    sub = path[len(prefix):] if prefix != "/" else path
                    sub = sub or "/"
                    resp_obj = await loop.run_in_executor(
                        None, lambda: self._with_trace(
                            trace_ctx, lambda: handle_.options(
                                method_name=meta["path_method"],
                                timeout_s=timeout_s).remote(sub, payload)))
                else:
                    resp_obj = await loop.run_in_executor(
                        None, lambda: self._with_trace(
                            trace_ctx, lambda: handle_.options(
                                timeout_s=timeout_s).remote(payload)))
                result = await resp_obj._result_async(
                    timeout_s=timeout_s + 5.0)
            except asyncio.CancelledError:
                # Client disconnected while we awaited the replica:
                # propagate the cancel so the in-flight call stops
                # burning replica capacity, then let aiohttp tear the
                # transport down.
                if resp_obj is not None:
                    loop = asyncio.get_running_loop()
                    loop.run_in_executor(None, resp_obj.cancel)
                raise
            except Exception as e:  # noqa: BLE001 — surface to the client
                # Shed/error responses close the trace too — 503/408
                # exemplars are exactly what tail-based retention keeps.
                return self._finish_trace(
                    trace_ctx, request, self._error_response(web, e),
                    t0, error=e)
            return self._finish_trace(
                trace_ctx, request, self._encode(web, result), t0)

        async def run():
            app = web.Application()
            app.router.add_route("*", "/{tail:.*}", handle)
            # handler_cancellation: aiohttp >= 3.9 stopped cancelling
            # handlers on client disconnect by default — the serving
            # plane WANTS the cancel (it propagates to the in-flight
            # replica call so abandoned work is dropped).
            try:
                runner = web.AppRunner(app, handler_cancellation=True)
            except TypeError:  # older aiohttp: cancellation is the default
                runner = web.AppRunner(app)
            await runner.setup()
            site = web.SockSite(runner, self._sock)
            await site.start()
            self._ready.set()
            while True:  # park forever; actor kill tears the process down
                await asyncio.sleep(3600)

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(run())

    async def _stream_sse(self, web, request, handle_, payload,
                          method: "str | None" = None, trace_ctx=None,
                          t0: float = 0.0):
        """Fully async SSE: submit via a short executor hop, then
        async-iterate the response generator — each item awaits a
        head-pushed readiness notification, so a stream in flight holds
        NO thread (the old design parked one pump thread per stream,
        capping concurrent streams at the pool size). Backpressure is
        inherent: the next item is requested only after the previous
        write completes."""
        loop = asyncio.get_running_loop()
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        }
        if trace_ctx is not None:
            headers["X-Trace-Id"] = trace_ctx[0]
        resp = web.StreamResponse(headers=headers)
        await resp.prepare(request)
        gen = None
        try:
            opts = {"stream": True}
            if method:
                opts["method_name"] = method
            gen = await loop.run_in_executor(
                None, lambda: self._with_trace(
                    trace_ctx,
                    lambda: handle_.options(**opts).remote(payload)))
            async for item in gen:
                if item == "[DONE]":
                    # OpenAI stream terminator: literal, not JSON.
                    await resp.write(b"data: [DONE]\n\n")
                    continue
                await resp.write(
                    f"data: {json.dumps(item, default=str)}\n\n".encode())
            await resp.write_eof()
        except Exception as e:  # noqa: BLE001
            # Replica error mid-stream (surface to the client) or the
            # client disconnected (write raised — nothing to surface).
            try:
                await resp.write(
                    f"event: error\ndata: {json.dumps(str(e))}\n\n".encode())
                await resp.write_eof()
            except Exception:
                pass
        finally:
            # Early termination must release routing accounting.
            if gen is not None and hasattr(gen, "close"):
                gen.close()
            self._record_root_span(trace_ctx, request, 200, t0)
        return resp

    async def _handle_ws(self, web, request, handle_, meta):
        """WebSocket ingress (reference: serve's FastAPI websocket
        routes through the ASGI proxy; here the deployment declares a
        ``ws_message`` handler). Per inbound frame: JSON-decode when
        possible, dispatch to the replica, and send the reply — every
        yielded item of an async-generator handler becomes one outbound
        frame, so token-streaming chat works over one socket. The
        connection closes when the client closes; a replica error
        surfaces as an error frame, not a dropped socket."""
        from aiohttp import WSMsgType

        loop = asyncio.get_running_loop()
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        method = meta["ws_method"]
        streaming = bool(meta.get("ws_stream"))
        async for msg in ws:
            if msg.type in (WSMsgType.CLOSE, WSMsgType.CLOSING,
                            WSMsgType.ERROR):
                break
            if msg.type == WSMsgType.BINARY:
                # The contract is one reply per inbound frame — an
                # unsupported frame type gets an error reply, never
                # silence (the client would block on its receive).
                try:
                    await ws.send_str(json.dumps(
                        {"error": "binary frames not supported; "
                                  "send JSON text frames"}))
                except Exception:
                    break
                continue
            if msg.type != WSMsgType.TEXT:
                continue  # ping/pong handled by aiohttp
            try:
                payload = json.loads(msg.data)
            except json.JSONDecodeError:
                payload = msg.data
            gen = None
            try:
                if streaming:
                    gen = await loop.run_in_executor(
                        None, lambda: handle_.options(
                            stream=True, method_name=method).remote(payload))
                    async for item in gen:
                        await ws.send_str(json.dumps(item, default=str))
                else:
                    resp_obj = await loop.run_in_executor(
                        None, lambda: handle_.options(
                            method_name=method).remote(payload))
                    result = await resp_obj._result_async(timeout_s=30.0)
                    await ws.send_str(json.dumps(result, default=str))
            except Exception as e:  # noqa: BLE001 — surface per-frame
                try:
                    await ws.send_str(json.dumps({"error": str(e)}))
                except Exception:
                    break  # client gone mid-reply
            finally:
                if gen is not None and hasattr(gen, "close"):
                    gen.close()
        return ws

    def _match_route(self, path: str) -> "dict | None":
        # Longest-prefix match (reference: proxy route matching). The
        # matched prefix rides along so path-aware deployments receive
        # the subpath below their mount point.
        best, best_len, best_prefix = None, -1, "/"
        for prefix, meta in self._routes.items():
            p = prefix.rstrip("/") or "/"
            if (path == p or path.startswith(p + "/") or p == "/") and len(p) > best_len:
                best, best_len, best_prefix = meta, len(p), p
        if best is None:
            return None
        return {**best, "_prefix": best_prefix}

    @staticmethod
    def _with_trace(trace_ctx, fn):
        """Run the submit closure with the request's trace context
        ambient. Contextvars don't cross run_in_executor, and executor
        threads are REUSED — push/pop (not set) so the context can't
        leak into the thread's next unrelated request."""
        if trace_ctx is None:
            return fn()
        tok = worker_context.push_trace_context(trace_ctx)
        try:
            return fn()
        finally:
            worker_context.pop_trace_context(tok)

    def _finish_trace(self, trace_ctx, request, resp, t0,
                      error: "Exception | None" = None):
        """Echo X-Trace-Id and close the request's root span. Runs on
        the success AND error/shed paths — a 503/408 response is
        exactly the tail exemplar the head's trace table retains."""
        if trace_ctx is None:
            return resp
        resp.headers["X-Trace-Id"] = trace_ctx[0]
        self._record_root_span(trace_ctx, request,
                               getattr(resp, "status", 200), t0,
                               error=error)
        return resp

    @staticmethod
    def _record_root_span(trace_ctx, request, status, t0, error=None):
        if trace_ctx is None or not int(trace_ctx[2] or 0):
            return
        attrs = {"method": request.method, "path": request.path,
                 "status": status}
        if error is not None:
            attrs["error"] = repr(error)
        traceplane.buffer_span({
            "event": "span",
            "name": "http.request",
            "kind": "proxy",
            "trace_id": trace_ctx[0],
            "span_id": trace_ctx[1],
            "parent_span_id": "",
            "pid": os.getpid(),
            "start": t0,
            "end": time.time(),
            "failed": status >= 500,
            "status": status,
            "attributes": attrs,
        })

    @staticmethod
    def _error_response(web, e: Exception):
        """Typed overload mapping. Replica-raised errors cross the wire
        as TaskError (the worker seals repr(exc)), so classification
        string-matches the type name in the message alongside the
        isinstance checks for locally-raised instances."""
        from ray_tpu.exceptions import (
            PendingCallsLimitError,
            TaskTimeoutError,
        )

        msg = str(e)
        if isinstance(e, PendingCallsLimitError) \
                or "PendingCallsLimitError" in msg:
            return web.json_response(
                {"error": msg, "type": "PendingCallsLimitError",
                 "retry_after_s": 0.5},
                status=503, headers={"Retry-After": "1"})
        if isinstance(e, (TaskTimeoutError, TimeoutError, asyncio.TimeoutError)) \
                or "TaskTimeoutError" in msg:
            return web.json_response(
                {"error": msg, "type": "TaskTimeoutError"}, status=408)
        return web.json_response(
            {"error": msg, "type": type(e).__name__}, status=500)

    @staticmethod
    def _encode(web, result: Any):
        import numpy as np

        def default(o):
            if isinstance(o, np.ndarray):
                return o.tolist()
            if isinstance(o, np.generic):
                return o.item()
            raise TypeError(f"not JSON serializable: {type(o)}")

        if isinstance(result, (bytes, bytearray)):
            return web.Response(body=bytes(result))
        if isinstance(result, str):
            return web.Response(text=result)
        return web.Response(
            text=json.dumps(result, default=default),
            content_type="application/json",
        )
