"""Replica: the actor hosting one copy of a deployment's user class.

Counterpart of the reference's serve/_private/replica.py — wraps the user
callable, counts ongoing requests (the autoscaling signal), exposes a
health check. Runs with max_concurrency > 1 so requests overlap up to
max_ongoing_requests (threaded-actor semantics here; the reference uses
an asyncio replica event loop)."""

from __future__ import annotations

import threading
from typing import Any


class Replica:
    def __init__(self, cls_or_fn, init_args: tuple, init_kwargs: dict,
                 deployment_name: str, replica_id: str):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        if isinstance(cls_or_fn, type):
            self.instance = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.instance = cls_or_fn  # plain function deployment

    def _resolve_call(self, method: str, args: tuple, kwargs: dict):
        """Shared request plumbing: await composed upstream ObjectRefs
        (handle.remote unwraps .ref) and resolve the target callable."""
        import ray_tpu
        from ray_tpu._private.ids import ObjectRef

        args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a for a in args)
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        target = (self.instance if method in ("__call__", "")
                  else getattr(self.instance, method))
        return target, args, kwargs

    def handle_request(self, method: str, args: tuple, kwargs: dict) -> Any:
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target, args, kwargs = self._resolve_call(method, args, kwargs)
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method: str, args: tuple, kwargs: dict):
        """Generator variant: yields the user generator's items one by one.
        Being itself a generator actor method, callers receive an
        ObjectRefGenerator whose items appear as produced (reference:
        streaming deployment responses through the proxy,
        serve/_private/proxy response streaming)."""
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target, args, kwargs = self._resolve_call(method, args, kwargs)
            yield from target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def get_metrics(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "ongoing": self._ongoing,
                "total": self._total,
            }

    def check_health(self) -> bool:
        user_check = getattr(self.instance, "check_health", None)
        if callable(user_check):
            user_check()
        return True

    def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self.instance, "reconfigure", None)
        if callable(hook):
            hook(user_config)
