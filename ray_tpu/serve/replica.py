"""Replica: the actor hosting one copy of a deployment's user class.

Counterpart of the reference's serve/_private/replica.py — wraps the user
callable, counts ongoing requests (the autoscaling signal), exposes a
health check. The replica is an ASYNC actor (its handler methods are
coroutines), so requests overlap on one event loop up to the actor's
concurrency bound — the reference's asyncio replica event loop. Async
user methods await natively; sync user methods run in a thread pool so
they cannot stall the loop (reference: sync methods offloaded to the
replica's executor)."""

from __future__ import annotations

import asyncio
import inspect
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any

_STOP = object()


class ReplicaContext:
    """Metadata about the replica hosting the current code (reference:
    serve/api.py get_replica_context / ReplicaContext)."""

    def __init__(self, deployment: str, replica_id: str, servable_object):
        self.deployment = deployment
        self.replica_id = replica_id
        self.servable_object = servable_object


_replica_context: "ReplicaContext | None" = None


def get_replica_context() -> ReplicaContext:
    if _replica_context is None:
        raise RuntimeError(
            "get_replica_context() may only be called inside a Serve "
            "replica (deployment __init__ or request handling)")
    return _replica_context


class Replica:
    def __init__(self, cls_or_fn, init_args: tuple, init_kwargs: dict,
                 deployment_name: str, replica_id: str,
                 max_ongoing_requests: int = 16):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        # Visible to user code from __init__ onward (the context is set
        # BEFORE the servable constructs, matching reference timing; the
        # servable_object field is filled in right after construction).
        global _replica_context
        _replica_context = ReplicaContext(deployment_name, replica_id, None)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        # Sync user code runs here, off the replica event loop — sized by
        # max_ongoing_requests so the knob governs sync parallelism the
        # way it did for threaded replicas.
        self._user_pool = ThreadPoolExecutor(
            max_workers=max(2, int(max_ongoing_requests)),
            thread_name_prefix="replica-user")
        if isinstance(cls_or_fn, type):
            self.instance = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.instance = cls_or_fn  # plain function deployment
        _replica_context.servable_object = self.instance

    def _resolve_call(self, method: str, args: tuple, kwargs: dict):
        """Shared request plumbing: await composed upstream ObjectRefs
        (handle.remote unwraps .ref) and resolve the target callable."""
        import ray_tpu
        from ray_tpu._private.ids import ObjectRef

        args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a for a in args)
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        target = (self.instance if method in ("__call__", "")
                  else getattr(self.instance, method))
        return target, args, kwargs

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             multiplexed_model_id: str = "") -> Any:
        import contextvars

        from ray_tpu.serve.multiplex import _set_request_model_id

        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_request_model_id(multiplexed_model_id)
        try:
            loop = asyncio.get_running_loop()
            target, args, kwargs = await loop.run_in_executor(
                self._user_pool, self._resolve_call, method, args, kwargs)
            if inspect.iscoroutinefunction(getattr(target, "__call__", target)) \
                    or inspect.iscoroutinefunction(target):
                return await target(*args, **kwargs)
            # ctx.run: sync user code in the pool still sees
            # serve.get_multiplexed_model_id() (run_in_executor does not
            # propagate contextvars by itself).
            ctx = contextvars.copy_context()
            result = await loop.run_in_executor(
                self._user_pool, lambda: ctx.run(target, *args, **kwargs))
            if inspect.iscoroutine(result):
                return await result
            return result
        finally:
            with self._lock:
                self._ongoing -= 1

    async def handle_request_streaming(self, method: str, args: tuple,
                                       kwargs: dict,
                                       multiplexed_model_id: str = ""):
        """Streaming variant: an async generator either way — async user
        generators are consumed natively, sync ones are stepped in the
        user pool so a slow producer never blocks the replica loop
        (reference: streaming deployment responses, serve/_private/proxy
        response streaming)."""
        import contextvars

        from ray_tpu.serve.multiplex import _set_request_model_id

        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_request_model_id(multiplexed_model_id)
        try:
            loop = asyncio.get_running_loop()
            target, args, kwargs = await loop.run_in_executor(
                self._user_pool, self._resolve_call, method, args, kwargs)
            # Invoke off-loop: a sync method doing real work before
            # returning its iterable (e.g. computing a full list) must
            # not stall every other request on this replica. Generator
            # functions return instantly either way.
            ctx = contextvars.copy_context()
            out = await loop.run_in_executor(
                self._user_pool, lambda: ctx.run(target, *args, **kwargs))
            if inspect.iscoroutine(out):
                out = await out
            if hasattr(out, "__anext__"):
                async for item in out:
                    yield item
                return
            it = iter(out)

            def step():
                try:
                    return next(it)
                except StopIteration:
                    return _STOP

            while True:
                # ctx.run so generator-body steps see the request's
                # multiplexed model id too (steps are sequential, so
                # re-entering the copied context each time is safe).
                item = await loop.run_in_executor(
                    self._user_pool, ctx.run, step)
                if item is _STOP:
                    return
                yield item
        finally:
            with self._lock:
                self._ongoing -= 1

    async def get_metrics(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "ongoing": self._ongoing,
                "total": self._total,
            }

    async def check_health(self) -> bool:
        user_check = getattr(self.instance, "check_health", None)
        if callable(user_check):
            result = user_check()
            if inspect.iscoroutine(result):
                await result
        return True

    async def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self.instance, "reconfigure", None)
        if callable(hook):
            result = hook(user_config)
            if inspect.iscoroutine(result):
                await result
