"""Replica: the actor hosting one copy of a deployment's user class.

Counterpart of the reference's serve/_private/replica.py — wraps the user
callable, counts ongoing requests (the autoscaling signal), exposes a
health check. The replica is an ASYNC actor (its handler methods are
coroutines), so requests overlap on one event loop up to the actor's
concurrency bound — the reference's asyncio replica event loop. Async
user methods await natively; sync user methods run in a thread pool so
they cannot stall the loop (reference: sync methods offloaded to the
replica's executor).

Serving-plane duties on top of that (PR 5 overload discipline applied at
the replica hop):

* deadline check at pickup — a request whose handle-stamped deadline
  already passed sheds with a typed ``TaskTimeoutError`` instead of
  burning replica capacity on a result nobody can use;
* bounded admission — past ``max_ongoing + max_queued_requests`` the
  replica sheds with ``PendingCallsLimitError`` (HTTP 503);
* ``drain()`` — scale-down path: stop admitting, let in-flight requests
  finish, then shut the batch schedulers down;
* ``get_metrics`` — queue depth, shed counts, and continuous-batching
  stats (plus the servable's own ``serve_batch_stats()`` when it
  declares one, e.g. the LLM engine's token-level batch view) feed
  handle routing and controller autoscaling.
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from ray_tpu.exceptions import PendingCallsLimitError, TaskTimeoutError

_STOP = object()


class ReplicaContext:
    """Metadata about the replica hosting the current code (reference:
    serve/api.py get_replica_context / ReplicaContext)."""

    def __init__(self, deployment: str, replica_id: str, servable_object):
        self.deployment = deployment
        self.replica_id = replica_id
        self.servable_object = servable_object


_replica_context: "ReplicaContext | None" = None


def get_replica_context() -> ReplicaContext:
    if _replica_context is None:
        raise RuntimeError(
            "get_replica_context() may only be called inside a Serve "
            "replica (deployment __init__ or request handling)")
    return _replica_context


class Replica:
    def __init__(self, cls_or_fn, init_args: tuple, init_kwargs: dict,
                 deployment_name: str, replica_id: str,
                 max_ongoing_requests: int = 16,
                 max_queued_requests: "int | None" = None):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        # Visible to user code from __init__ onward (the context is set
        # BEFORE the servable constructs, matching reference timing; the
        # servable_object field is filled in right after construction).
        global _replica_context
        _replica_context = ReplicaContext(deployment_name, replica_id, None)
        self._ongoing = 0
        self._total = 0
        self._shed = 0
        self._draining = False
        self._max_ongoing = max(1, int(max_ongoing_requests))
        self._max_queued = (None if max_queued_requests is None
                            else max(0, int(max_queued_requests)))
        self._lock = threading.Lock()
        # Sync user code runs here, off the replica event loop — sized by
        # max_ongoing_requests so the knob governs sync parallelism the
        # way it did for threaded replicas.
        self._user_pool = ThreadPoolExecutor(
            max_workers=max(2, int(max_ongoing_requests)),
            thread_name_prefix="replica-user")
        if isinstance(cls_or_fn, type):
            self.instance = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.instance = cls_or_fn  # plain function deployment
        _replica_context.servable_object = self.instance

    def _admit(self, deadline: "float | None") -> None:
        """Shed-before-work gate, mirrored from the direct plane's
        pop-time deadline check: expired or over-budget requests never
        touch user code. Raises inside the actor method, so callers see
        the typed reason in the TaskError cause."""
        if self._draining:
            from ray_tpu.exceptions import ActorUnavailableError

            raise ActorUnavailableError(
                f"replica {self.replica_id} is draining for scale-down")
        if deadline is not None and time.time() > deadline:
            with self._lock:
                self._shed += 1
            raise TaskTimeoutError(
                "TaskTimeoutError: request exceeded its deadline before "
                f"replica {self.replica_id} picked it up (shed)",
                where="replica_pickup")
        if self._max_queued is not None:
            with self._lock:
                over = self._ongoing >= self._max_ongoing + self._max_queued
                if over:
                    self._shed += 1
            if over:
                raise PendingCallsLimitError(
                    f"PendingCallsLimitError: replica {self.replica_id} "
                    f"is saturated ({self._ongoing} ongoing, limit "
                    f"{self._max_ongoing}+{self._max_queued} queued)")

    def _resolve_call(self, method: str, args: tuple, kwargs: dict):
        """Shared request plumbing: await composed upstream ObjectRefs
        (handle.remote unwraps .ref) and resolve the target callable."""
        import ray_tpu
        from ray_tpu._private.ids import ObjectRef

        args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a for a in args)
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        target = (self.instance if method in ("__call__", "")
                  else getattr(self.instance, method))
        return target, args, kwargs

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             multiplexed_model_id: str = "",
                             deadline: "float | None" = None) -> Any:
        import contextvars

        from ray_tpu.serve.multiplex import _set_request_model_id
        from ray_tpu.serve.scheduler import set_request_deadline

        self._admit(deadline)
        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_request_model_id(multiplexed_model_id)
        # Batched methods read this to shed queued work whose caller's
        # deadline expires while waiting for batch assembly.
        set_request_deadline(deadline)
        try:
            loop = asyncio.get_running_loop()
            target, args, kwargs = await loop.run_in_executor(
                self._user_pool, self._resolve_call, method, args, kwargs)
            if inspect.iscoroutinefunction(getattr(target, "__call__", target)) \
                    or inspect.iscoroutinefunction(target):
                return await target(*args, **kwargs)
            # ctx.run: sync user code in the pool still sees
            # serve.get_multiplexed_model_id() (run_in_executor does not
            # propagate contextvars by itself).
            ctx = contextvars.copy_context()
            result = await loop.run_in_executor(
                self._user_pool, lambda: ctx.run(target, *args, **kwargs))
            if inspect.iscoroutine(result):
                return await result
            return result
        finally:
            with self._lock:
                self._ongoing -= 1

    async def handle_request_streaming(self, method: str, args: tuple,
                                       kwargs: dict,
                                       multiplexed_model_id: str = "",
                                       deadline: "float | None" = None):
        """Streaming variant: an async generator either way — async user
        generators are consumed natively, sync ones are stepped in the
        user pool so a slow producer never blocks the replica loop
        (reference: streaming deployment responses, serve/_private/proxy
        response streaming)."""
        import contextvars

        from ray_tpu.serve.multiplex import _set_request_model_id
        from ray_tpu.serve.scheduler import set_request_deadline

        self._admit(deadline)
        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_request_model_id(multiplexed_model_id)
        set_request_deadline(deadline)
        try:
            loop = asyncio.get_running_loop()
            target, args, kwargs = await loop.run_in_executor(
                self._user_pool, self._resolve_call, method, args, kwargs)
            # Invoke off-loop: a sync method doing real work before
            # returning its iterable (e.g. computing a full list) must
            # not stall every other request on this replica. Generator
            # functions return instantly either way.
            ctx = contextvars.copy_context()
            out = await loop.run_in_executor(
                self._user_pool, lambda: ctx.run(target, *args, **kwargs))
            if inspect.iscoroutine(out):
                out = await out
            if hasattr(out, "__anext__"):
                async for item in out:
                    yield item
                return
            it = iter(out)

            def step():
                try:
                    return next(it)
                except StopIteration:
                    return _STOP

            while True:
                # ctx.run so generator-body steps see the request's
                # multiplexed model id too (steps are sequential, so
                # re-entering the copied context each time is safe).
                item = await loop.run_in_executor(
                    self._user_pool, ctx.run, step)
                if item is _STOP:
                    return
                yield item
        finally:
            with self._lock:
                self._ongoing -= 1

    async def get_metrics(self) -> dict:
        from ray_tpu.serve import batching

        snaps = [b.snapshot() for b in batching.batchers_of(self.instance)]
        with self._lock:
            out = {
                "replica_id": self.replica_id,
                "ongoing": self._ongoing,
                "total": self._total,
                "draining": self._draining,
            }
        out["qdepth"] = sum(s["queued"] for s in snaps)
        out["shed_total"] = self._shed + sum(
            s["shed_deadline"] + s["shed_queue_full"] for s in snaps)
        if snaps:
            out["batch_size_p50"] = max(s["batch_size_p50"] for s in snaps)
            out["batchers"] = snaps
        # Token-level continuous batching: servables driving their own
        # engine loop (llm/serving.LLMServer) report it here.
        hook = getattr(self.instance, "serve_batch_stats", None)
        if callable(hook):
            try:
                stats = hook()
                if inspect.iscoroutine(stats):
                    stats = await stats
                out["engine"] = stats
            except Exception:  # noqa: BLE001 — telemetry must not fail
                pass
        return out

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """Scale-down path: stop admitting (new requests shed and the
        handle re-routes them), wait for in-flight requests to finish,
        then cancel the batch schedulers. True = drained clean within
        the timeout; the controller kills the actor either way."""
        from ray_tpu.serve import batching

        self._draining = True
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    break
            await asyncio.sleep(0.05)
        batching.shutdown_batchers(self.instance)
        with self._lock:
            return self._ongoing == 0

    async def check_health(self) -> bool:
        user_check = getattr(self.instance, "check_health", None)
        if callable(user_check):
            result = user_check()
            if inspect.iscoroutine(result):
                await result
        return True

    async def reconfigure(self, user_config: Any) -> None:
        hook = getattr(self.instance, "reconfigure", None)
        if callable(hook):
            result = hook(user_config)
            if inspect.iscoroutine(result):
                await result
