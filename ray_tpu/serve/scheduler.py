"""Continuous batch scheduler: the serving plane's core.

Counterpart of the reference's request-batching loop in
serve/batching.py's _BatchQueue plus the dynamic batch sizing the
TPU-serving literature calls continuous batching: instead of a one-shot
"collect-then-drain" flusher, a per-instance scheduler ADMITS requests
into the next batch as slots free — batch N+1 assembles and launches
while batch N is still executing (no drain barrier), so the accelerator
never idles between batches.

Two pieces:

* ``LatencyModel`` — per-batch-size exec-latency histograms (the PR 3
  flight-recorder ``PhaseHistogram``), bucketed by power of two. The
  p95 estimate per bucket drives SLO-aware sizing: ``pick_batch_size``
  returns the largest size whose observed p95 stays under
  ``target_latency_slo_s`` (cold start is optimistic: unobserved sizes
  are explored so the model learns the envelope).

* ``ContinuousBatcher`` — the per-(instance, method) scheduler behind
  ``@serve.batch``. Submissions carry the request deadline (stamped by
  the DeploymentHandle via ``timeout_s`` → TaskSpec deadline, surfaced
  here through a contextvar); expired items are SHED from the queue
  with a typed ``TaskTimeoutError`` before user code ever sees them —
  the same discipline the PR 5 overload plane applies at the owner,
  head, and worker hops. A bounded queue sheds with
  ``PendingCallsLimitError`` (HTTP 503 at the proxy).

The scheduler task is SELF-TERMINATING: it exists only while work is
queued or in flight, so replica teardown under pytest never strands a
parked asyncio task (the orphaned-flusher warning the old design had).
"""

from __future__ import annotations

import asyncio
import contextvars
import time
from collections import deque
from typing import Any, Callable, Optional

from ray_tpu._private.events import PhaseHistogram
from ray_tpu.exceptions import PendingCallsLimitError, TaskTimeoutError

# Request deadline (wall clock) for the request currently being handled
# on this replica: set by Replica.handle_request, read by the batching
# wrapper so queued items inherit their caller's deadline.
_REQUEST_DEADLINE: contextvars.ContextVar[Optional[float]] = \
    contextvars.ContextVar("ray_tpu_serve_request_deadline", default=None)


def set_request_deadline(deadline: "float | None") -> None:
    _REQUEST_DEADLINE.set(deadline)


def get_request_deadline() -> "float | None":
    return _REQUEST_DEADLINE.get()


class LatencyModel:
    """Observed exec latency per batch size, power-of-two bucketed.

    Reuses the flight recorder's PhaseHistogram so the p95 estimate is
    the same conservative upper-boundary read the tracing plane
    exposes — a bucket only "fits" the SLO when its whole observed
    range does."""

    MIN_OBSERVATIONS = 3  # below this a bucket is cold (optimistic)

    def __init__(self):
        self._hists: dict[int, PhaseHistogram] = {}

    @staticmethod
    def bucket(batch_size: int) -> int:
        """Smallest power of two >= batch_size (1 for size 1)."""
        n = max(1, int(batch_size))
        return 1 << (n - 1).bit_length()

    def observe(self, batch_size: int, exec_s: float) -> None:
        b = self.bucket(batch_size)
        h = self._hists.get(b)
        if h is None:
            h = self._hists[b] = PhaseHistogram()
        h.observe(exec_s)

    def p95(self, bucket: int) -> "float | None":
        """Upper-boundary p95 estimate for one bucket; None while the
        bucket is cold (too few observations to trust)."""
        h = self._hists.get(bucket)
        if h is None or h.count < self.MIN_OBSERVATIONS:
            return None
        target = 0.95 * h.count
        cum = 0
        for i, c in enumerate(h.buckets):
            cum += c
            if cum >= target:
                if i < len(h.boundaries):
                    return h.boundaries[i]
                return h.boundaries[-1] * 2  # overflow bucket
        return h.boundaries[-1] * 2

    def pick_batch_size(self, max_batch_size: int,
                        slo_s: "float | None") -> int:
        """Largest batch size whose observed p95 fits under the SLO.

        Walks size candidates upward and stops at the first OBSERVED
        violation (exec latency is monotone in batch size, so nothing
        larger can fit either). Unobserved sizes below the first
        violation are trusted — that is the exploration path: cold
        start picks ``max_batch_size`` and the model tightens as real
        batches are measured."""
        if not slo_s:
            return max_batch_size
        candidates = []
        b = 1
        while b < max_batch_size:
            candidates.append(b)
            b <<= 1
        candidates.append(max_batch_size)
        chosen = 1
        for size in candidates:
            p = self.p95(self.bucket(size))
            if p is not None and p > slo_s:
                break
            chosen = size
        return chosen

    def snapshot(self) -> dict:
        return {
            str(b): {"count": h.count,
                     "mean_s": (h.sum / h.count) if h.count else 0.0,
                     "p95_s": self.p95(b)}
            for b, h in sorted(self._hists.items())
        }


class ContinuousBatcher:
    """SLO-aware continuous batching over one async batch function.

    ``fn`` is an async callable taking a list of items and returning a
    list of results of the same length (the ``@serve.batch`` contract).
    ``submit`` enqueues one item and returns an asyncio future; the
    scheduler assembles batches dynamically:

      * batch size = ``LatencyModel.pick_batch_size`` under
        ``target_latency_slo_s`` (or ``max_batch_size`` with no SLO);
      * an assembly window of ``batch_wait_timeout_s`` lets a partial
        batch fill before launching;
      * batches launch as independent tasks — up to
        ``max_concurrent_batches`` overlap (None = unbounded), so new
        requests are admitted while earlier batches still execute;
      * deadline-expired and caller-cancelled items are shed at
        assembly, never dispatched.

    Must be driven from a single event loop (the replica loop)."""

    def __init__(self, fn: Callable, *, max_batch_size: int = 10,
                 batch_wait_timeout_s: float = 0.01,
                 target_latency_slo_s: "float | None" = None,
                 max_concurrent_batches: "int | None" = None,
                 max_queue_len: "int | None" = None,
                 name: str = "batch"):
        self._fn = fn
        self._name = name
        self._max_batch_size = max(1, int(max_batch_size))
        self._batch_wait_timeout_s = float(batch_wait_timeout_s)
        self._target_latency_slo_s = target_latency_slo_s
        self._max_concurrent_batches = max_concurrent_batches
        self._max_queue_len = max_queue_len
        self.model = LatencyModel()
        self._queue: deque = deque()  # (item, future, deadline, trace_ctx)
        self._wakeup: "asyncio.Event | None" = None
        self._scheduler: "asyncio.Task | None" = None
        self._batches: set = set()
        self._loop: "asyncio.AbstractEventLoop | None" = None
        self._closed = False
        self._recent_sizes: deque = deque(maxlen=128)
        self.stats = {
            "submitted": 0, "batches": 0, "items": 0,
            "shed_deadline": 0, "shed_queue_full": 0,
            "shed_cancelled": 0, "batch_errors": 0,
        }

    # -- submission --------------------------------------------------------

    def submit(self, item: Any, deadline: "float | None" = None,
               trace_ctx: "tuple | None" = None) -> "asyncio.Future":
        loop = asyncio.get_running_loop()
        if self._closed:
            raise RuntimeError(f"batcher {self._name} is shut down")
        self._loop = loop
        if (self._max_queue_len is not None
                and len(self._queue) >= self._max_queue_len):
            self.stats["shed_queue_full"] += 1
            raise PendingCallsLimitError(
                f"PendingCallsLimitError: @serve.batch queue for "
                f"{self._name} is full ({self._max_queue_len} waiting)")
        fut = loop.create_future()
        self._queue.append((item, fut, deadline, trace_ctx))
        self.stats["submitted"] += 1
        if self._wakeup is None:
            self._wakeup = asyncio.Event()
        self._wakeup.set()
        if self._scheduler is None or self._scheduler.done():
            self._scheduler = loop.create_task(self._run_scheduler())
        return fut

    # -- scheduler loop ----------------------------------------------------

    async def _run_scheduler(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while self._queue and not self._closed:
                # Concurrency gate — NOT a drain barrier: with room for
                # another batch, assembly proceeds while earlier
                # batches are still executing.
                while (self._max_concurrent_batches is not None
                       and len(self._batches)
                       >= self._max_concurrent_batches):
                    await asyncio.wait(set(self._batches),
                                       return_when=asyncio.FIRST_COMPLETED)
                self._shed_unservable()
                if not self._queue:
                    break
                target = self.model.pick_batch_size(
                    self._max_batch_size, self._target_latency_slo_s)
                # Assembly window: let the batch fill, but never hold a
                # partial batch past the wait timeout.
                window_end = loop.time() + self._batch_wait_timeout_s
                while len(self._queue) < target and not self._closed:
                    remaining = window_end - loop.time()
                    if remaining <= 0:
                        break
                    self._wakeup.clear()
                    try:
                        await asyncio.wait_for(self._wakeup.wait(),
                                               remaining)
                    except asyncio.TimeoutError:
                        break
                self._shed_unservable()
                n = min(target, len(self._queue))
                batch = [self._queue.popleft() for _ in range(n)]
                if not batch:
                    continue
                t = loop.create_task(self._run_batch(batch))
                self._batches.add(t)
                t.add_done_callback(self._batches.discard)
        except asyncio.CancelledError:
            pass

    def _shed_unservable(self) -> None:
        """Drop deadline-expired items (typed TaskTimeoutError — the
        overload plane's shed-at-every-hop discipline applied to the
        batch queue) and items whose caller already cancelled."""
        if not self._queue:
            return
        now = time.time()
        kept: deque = deque()
        for item, fut, dl, tc in self._queue:
            if fut.done():  # caller gone (cancelled/disconnected)
                self.stats["shed_cancelled"] += 1
                continue
            if dl is not None and now > dl:
                self.stats["shed_deadline"] += 1
                fut.set_exception(TaskTimeoutError(
                    "TaskTimeoutError: request exceeded its deadline "
                    "while queued for batching (shed before execution)",
                    where="serve_batcher"))
                # Shed span: failed + shed attribute makes the trace a
                # tail exemplar at the head (never folded first).
                self._emit_span(tc, f"{self._name}.shed", now, now,
                                failed=True,
                                attributes={"shed": "serve_batcher"})
                continue
            kept.append((item, fut, dl, tc))
        self._queue = kept

    async def _run_batch(self, batch: list) -> None:
        items = [b[0] for b in batch]
        futures = [b[1] for b in batch]
        self.stats["batches"] += 1
        self.stats["items"] += len(items)
        self._recent_sizes.append(len(items))
        t0 = time.perf_counter()
        wall0 = time.time()
        failed = False
        try:
            results = await self._fn(items)
            self.model.observe(len(items), time.perf_counter() - t0)
            if results is None or len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function {self._name} returned "
                    f"{0 if results is None else len(results)} results "
                    f"for a batch of {len(items)}")
            for f, r in zip(futures, results):
                if not f.done():
                    f.set_result(r)
        except asyncio.CancelledError:
            for f in futures:
                if not f.done():
                    f.cancel()
            raise
        except Exception as e:  # noqa: BLE001 — propagate to every caller
            failed = True
            self.stats["batch_errors"] += 1
            for f in futures:
                if not f.done():
                    f.set_exception(e)
        self._trace_batch(batch, wall0, time.time(), failed)

    def _trace_batch(self, batch: list, start: float, end: float,
                     failed: bool) -> None:
        """Per-trace view of a coalesced batch: each distinct sampled
        trace in the batch gets a "batch_exec" span under its own
        caller span (a shared batch_id attribute ties the copies
        together), and every item keeps its own "batch_item" child —
        so one request's trace shows exactly its share of the shared
        execution, including who it was coalesced with."""
        traced = [tc for _i, _f, _d, tc in batch if tc and int(tc[2] or 0)]
        if not traced:
            return
        from ray_tpu._private import traceplane

        batch_id = traceplane.new_span_id()
        exec_span_of: dict[str, str] = {}
        for tc in traced:
            if tc[0] in exec_span_of:
                continue
            sid = traceplane.new_span_id()
            exec_span_of[tc[0]] = sid
            self._emit_span(
                tc, f"{self._name}.batch_exec", start, end, failed=failed,
                span_id=sid,
                attributes={"batch_id": batch_id,
                            "batch_size": len(batch)})
        for idx, (_item, _fut, _dl, tc) in enumerate(batch):
            if not (tc and int(tc[2] or 0)):
                continue
            self._emit_span(
                tc, f"{self._name}.batch_item", start, end, failed=failed,
                parent_span_id=exec_span_of[tc[0]],
                attributes={"batch_id": batch_id, "index": idx})

    def _emit_span(self, tc: "tuple | None", name: str, start: float,
                   end: float, *, failed: bool = False,
                   span_id: "str | None" = None,
                   parent_span_id: "str | None" = None,
                   attributes: "dict | None" = None) -> None:
        """Buffer one serve-plane span into the request's trace (rides
        the next amortized rpc_report — zero per-span frames)."""
        if not (tc and int(tc[2] or 0)):
            return
        import os

        from ray_tpu._private import traceplane

        traceplane.buffer_span({
            "event": "span",
            "name": name,
            "kind": "serve",
            "trace_id": tc[0],
            "span_id": span_id or traceplane.new_span_id(),
            "parent_span_id": parent_span_id or tc[1],
            "pid": os.getpid(),
            "start": start,
            "end": end,
            "failed": failed,
            "attributes": attributes or {},
        })

    # -- introspection / teardown ------------------------------------------

    def batch_size_p50(self) -> float:
        if not self._recent_sizes:
            return 0.0
        s = sorted(self._recent_sizes)
        return float(s[len(s) // 2])

    def snapshot(self) -> dict:
        return {
            "name": self._name,
            "queued": len(self._queue),
            "inflight_batches": len(self._batches),
            "batch_size_p50": self.batch_size_p50(),
            "picked_batch_size": self.model.pick_batch_size(
                self._max_batch_size, self._target_latency_slo_s),
            **self.stats,
            "latency_model": self.model.snapshot(),
        }

    def shutdown(self) -> None:
        """Cancel the scheduler and in-flight batch tasks, cancel every
        queued future. Must run on the owning event loop; idempotent."""
        self._closed = True
        t, self._scheduler = self._scheduler, None
        if t is not None and not t.done():
            t.cancel()
        for b in list(self._batches):
            if not b.done():
                b.cancel()
        while self._queue:
            _item, fut, _dl, _tc = self._queue.popleft()
            if not fut.done():
                fut.cancel()
        if self._wakeup is not None:
            self._wakeup.set()

    def shutdown_threadsafe(self) -> None:
        """Teardown entry for finalizers running off-loop (instance GC):
        hops onto the owning loop when it is still alive."""
        loop = self._loop
        if loop is None or loop.is_closed():
            self._closed = True
            return
        try:
            loop.call_soon_threadsafe(self.shutdown)
        except RuntimeError:
            self._closed = True  # loop shut down between checks
