"""ray_tpu.train — distributed training on TPU.

Public surface mirrors the reference's ``ray.train`` (+``ray.train.torch``
replaced by the JAX backend):

    from ray_tpu.train import (JaxTrainer, ScalingConfig, RunConfig,
                               Checkpoint, report, get_checkpoint, get_context)
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxBackend, JaxConfig
from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    load_pytree,
    save_pytree,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    make_temp_checkpoint_dir,
    report,
)
from ray_tpu.train.trainer import JaxTrainer
from ray_tpu.train import jax_utils

__all__ = [
    "Backend",
    "BackendConfig",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "FailureConfig",
    "JaxBackend",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainContext",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "jax_utils",
    "load_pytree",
    "make_temp_checkpoint_dir",
    "report",
    "save_pytree",
]
