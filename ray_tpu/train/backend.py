"""Training backends: per-worker distributed setup.

Counterpart of the reference's Backend ABC + TorchConfig
(reference: train/backend.py:32 Backend; train/torch/config.py:36 TorchConfig,
:66 _setup_torch_process_group, :115 dist.init_process_group(nccl|gloo)).

The JaxConfig backend replaces the NCCL/gloo process group with:
  - a host-level collective group (ray_tpu.util.collective) for control-plane
    sync (weight broadcast, metric reduction, barriers), and
  - on real multi-host TPU pods, ``jax.distributed.initialize`` so in-jit
    collectives span hosts over ICI/DCN — the data plane
    (SURVEY.md §2.4 row "Data parallel").
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called around the training lifecycle (reference train/backend.py:32)."""

    def on_start(self, worker_group, backend_config) -> None:
        pass

    def on_worker_setup(self, rank: int, world_size: int, group_name: str) -> None:
        pass

    def on_shutdown(self, worker_group, backend_config) -> None:
        pass


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """JAX/TPU backend config.

    distributed="auto": initialize jax.distributed only when a multi-host
    environment is detected (TPU_WORKER_HOSTNAMES / coordinator env); "off"
    never; "on" always (requires coordinator_address).
    """

    distributed: str = "auto"
    coordinator_address: str | None = None
    collective_group: bool = True

    def backend_cls(self):
        return JaxBackend


class JaxBackend(Backend):
    def on_worker_setup(self, rank: int, world_size: int, group_name: str, config: JaxConfig | None = None) -> None:
        config = config or JaxConfig()
        # torchrun-style env vars for user code parity (reference:
        # train/torch/xla/config.py:41-56 sets the same family).
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world_size)
        os.environ["LOCAL_RANK"] = str(rank)
        if config.collective_group and world_size > 1:
            from ray_tpu.util import collective

            collective.init_collective_group(world_size, rank, group_name=group_name)
        if config.distributed == "on" or (
            config.distributed == "auto" and self._is_multihost_pod()
        ):
            import jax

            coordinator = config.coordinator_address
            if coordinator is None:
                hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
                if hosts and hosts[0]:
                    coordinator = f"{hosts[0]}:8476"
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=int(os.environ.get("TPU_POD_PROCESS_COUNT", world_size)),
                    process_id=rank,
                )
            except Exception as e:  # noqa: BLE001
                if config.distributed == "on":
                    raise
                import sys

                print(f"[train] jax.distributed auto-init skipped: {e}", file=sys.stderr)

    @staticmethod
    def _is_multihost_pod() -> bool:
        hosts = [h for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
        return len(hosts) > 1
