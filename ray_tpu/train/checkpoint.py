"""Checkpoint abstraction + top-k retention manager.

Counterpart of the reference's Checkpoint (train/_checkpoint.py:56 — a
directory + to/from_directory) and _CheckpointManager
(train/_internal/checkpoint_manager.py:43 — top-k by score). Storage is a
filesystem path (fsspec/cloud URIs are a later round; the StorageContext
analogue is RunConfig.resolved_storage_path).

For JAX state, prefer `save_pytree`/`load_pytree` (orbax under the hood,
async-capable) over hand-pickling — checkpoint/restore speed bounds elastic
recovery on TPU (SURVEY.md §7 "hard parts" (c)).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any


class Checkpoint:
    """A directory of training state."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    def to_directory(self, dest: str | None = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if os.path.abspath(dest) != os.path.abspath(self.path):
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def as_directory(self):
        """Context manager yielding a readable directory path."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            yield self.path

        return _ctx()

    def __repr__(self):
        return f"Checkpoint({self.path})"

    # --- jax pytree helpers ---

    def save_pytree(self, state: Any, name: str = "state") -> None:
        save_pytree(state, os.path.join(self.path, name))

    def load_pytree(self, target: Any = None, name: str = "state") -> Any:
        return load_pytree(os.path.join(self.path, name), target)


def save_pytree(state: Any, path: str) -> None:
    """Orbax-backed pytree save (works for flax/optax/jax state)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state)


def load_pytree(path: str, target: Any = None) -> Any:
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        if target is not None:
            return ckptr.restore(os.path.abspath(path), item=target)
        return ckptr.restore(os.path.abspath(path))


class CheckpointManager:
    """Top-k retention over a storage directory (reference:
    _internal/checkpoint_manager.py:43)."""

    def __init__(
        self,
        storage_path: str,
        num_to_keep: int | None = None,
        score_attribute: str | None = None,
        score_order: str = "max",
    ):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._records: list[dict] = []  # {path, score, index, metrics}
        self._index = 0
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint_dir: str, metrics: dict | None = None) -> Checkpoint:
        """Move a freshly written checkpoint into managed storage."""
        metrics = metrics or {}
        dest = os.path.join(self.storage_path, f"checkpoint_{self._index:06d}")
        if os.path.abspath(checkpoint_dir) != dest:
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.move(checkpoint_dir, dest)
        score = metrics.get(self.score_attribute) if self.score_attribute else None
        self._records.append(
            {"path": dest, "score": score, "index": self._index, "metrics": metrics}
        )
        self._index += 1
        self._save_manifest()
        self._enforce_retention()
        return Checkpoint(dest)

    def _enforce_retention(self) -> None:
        if self.num_to_keep is None or len(self._records) <= self.num_to_keep:
            return
        # Keep best k by score; unscored checkpoints rank BELOW every scored
        # one (they only survive when fewer than k scored exist), latest
        # breaks ties.
        def sort_key(r):
            if r["score"] is None:
                return (0, 0.0, r["index"])
            value = r["score"] if self.score_order == "max" else -r["score"]
            return (1, value, r["index"])

        ranked = sorted(self._records, key=sort_key, reverse=True)
        keep = set(id(r) for r in ranked[: self.num_to_keep])
        for r in list(self._records):
            if id(r) not in keep:
                shutil.rmtree(r["path"], ignore_errors=True)
                self._records.remove(r)
        self._save_manifest()

    def _save_manifest(self) -> None:
        manifest = [
            {k: v for k, v in r.items() if k != "metrics"} | {"metrics": r["metrics"]}
            for r in self._records
        ]
        with open(os.path.join(self.storage_path, "manifest.json"), "w") as f:
            json.dump(manifest, f, default=str)

    @property
    def latest(self) -> Checkpoint | None:
        if not self._records:
            return None
        return Checkpoint(max(self._records, key=lambda r: r["index"])["path"])

    @property
    def best(self) -> Checkpoint | None:
        if not self._records:
            return None
        scored = [r for r in self._records if r["score"] is not None]
        if not scored:
            return self.latest
        best = (max if self.score_order == "max" else min)(scored, key=lambda r: r["score"])
        return Checkpoint(best["path"])
