"""Train configuration dataclasses.

Counterpart of the reference's air/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig) and train/base_trainer.py Result handling
(air/result.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any


@dataclasses.dataclass
class ScalingConfig:
    """How to scale training (reference: air/config.py ScalingConfig).

    TPU-native semantics:
      - ``num_workers``: worker processes. On a multi-host pod: one per host
        (each host drives its local chips; cross-host sync over ICI/DCN).
      - ``use_tpu`` + ``tpus_per_worker``: chips reserved and made visible
        per worker (TPU_VISIBLE_CHIPS pinning).
      - ``topology="mesh"``: single-controller SPMD — ONE worker owns every
        local chip and the train loop runs under pjit/shard_map on a Mesh.
        This is the idiomatic hot path (SURVEY.md §7); multi-worker mode
        exists for host-level parallelism (env runners, data loaders) and
        multi-host process-per-host layouts.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: float | None = None
    cpus_per_worker: float | None = None
    resources_per_worker: dict[str, float] | None = None
    topology: str = "workers"  # "workers" | "mesh"
    placement_strategy: str = "PACK"
    # Elastic range (reference: train/v2 scaling_policy — a failure retry
    # may restart with fewer workers when the cluster shrank; None =
    # fixed-size gang of num_workers). XLA's compiled world is rigid
    # WITHIN an attempt, so elasticity happens at restart boundaries:
    # restart = recompile with the new world size.
    min_workers: int | None = None

    @property
    def elastic(self) -> bool:
        return self.min_workers is not None and self.min_workers < self.num_workers

    def worker_resources(self) -> dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", self.cpus_per_worker if self.cpus_per_worker is not None else 1.0)
        if self.use_tpu:
            res.setdefault("TPU", self.tpus_per_worker if self.tpus_per_worker is not None else 1.0)
        return res


@dataclasses.dataclass
class FailureConfig:
    """Reference: air/config.py FailureConfig. max_failures<0 = infinite."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """Reference: air/config.py CheckpointConfig (top-k retention)."""

    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"  # "max" | "min"
    # Tune: auto-save trial state every N iterations (0 = only on
    # pause/exploit). Reference: air/config.py CheckpointConfig.checkpoint_frequency.
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    """Reference: air/config.py RunConfig."""

    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig | None = None
    checkpoint_config: CheckpointConfig | None = None
    # Stop criterion (used by Tune): dict of metric -> threshold, or a
    # callable result -> bool (reference: air/config.py RunConfig.stop).
    stop: Any = None

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)


@dataclasses.dataclass
class Result:
    """Outcome of a run (reference: air/result.py Result)."""

    metrics: dict[str, Any]
    checkpoint: "Any | None"  # Checkpoint
    path: str
    metrics_history: list[dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Exception | None = None
    config: dict[str, Any] | None = None  # trial config (reference: Result.config)

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []
