"""Gradient-boosted-tree trainers: XGBoost and LightGBM.

Counterpart of the reference's XGBoostTrainer/LightGBMTrainer
(reference: train/xgboost/xgboost_trainer.py, train/lightgbm/
lightgbm_trainer.py — GBDTTrainer base in train/gbdt_trainer.py): each
train worker receives its Dataset shard, the workers form the library's
native collective (xgboost's tracker/rabit, lightgbm's socket machines
list) through the cluster KV rendezvous, and boosting rounds run
data-parallel with per-round metric reports.

Neither library ships in this image, so construction raises a clear
ImportError; the worker-loop plumbing below is exercised through the
library-free `_gbdt_worker_loop` contract tests instead.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable

import numpy as np

from ray_tpu.train.session import get_context, get_dataset_shard, report
from ray_tpu.train.trainer import JaxTrainer


def _require(module: str, trainer: str):
    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"{trainer} requires the '{module}' package, which is not "
            f"installed in this environment. Install it (pip install "
            f"{module}) or use JaxTrainer/TorchTrainer instead."
        ) from e


def _shard_to_matrix(shard, label_col: str = "label") -> tuple[np.ndarray, np.ndarray, str]:
    """(features, label, label_column) from a Dataset shard of dict rows."""
    rows = list(shard.iter_rows()) if hasattr(shard, "iter_rows") else list(shard)
    if not rows:
        raise ValueError("empty dataset shard")
    if label_col not in rows[0]:
        raise ValueError(
            f"label column {label_col!r} not in dataset columns "
            f"{sorted(rows[0])}"
        )
    feat_cols = [c for c in rows[0] if c != label_col]
    X = np.asarray([[r[c] for c in feat_cols] for r in rows], np.float32)
    y = np.asarray([r[label_col] for r in rows], np.float32)
    return X, y, label_col


class GBDTTrainer(JaxTrainer):
    """Shared scaffold (reference: train/gbdt_trainer.py GBDTTrainer).

    Subclasses set ``_module`` (import-gated library name) and implement
    ``_worker_loop(config)`` executed on every train worker."""

    _module: str = ""
    _display: str = "GBDTTrainer"

    def __init__(self, *, params: dict | None = None, label_column: str = "label",
                 num_boost_round: int = 10, datasets: dict | None = None,
                 scaling_config=None, run_config=None, **kw):
        _require(self._module, self._display)
        self.params = params or {}
        self.label_column = label_column
        self.num_boost_round = num_boost_round
        super().__init__(
            self._make_worker_loop(),
            train_loop_config={
                "params": self.params,
                "label_column": label_column,
                "num_boost_round": num_boost_round,
            },
            datasets=datasets,
            scaling_config=scaling_config,
            run_config=run_config,
            **kw,
        )

    def _make_worker_loop(self) -> Callable:
        raise NotImplementedError


class XGBoostTrainer(GBDTTrainer):
    """Reference: train/xgboost/xgboost_trainer.py. Data-parallel
    xgboost.train with the collective communicator context; rank 0
    reports the model checkpoint."""

    _module = "xgboost"
    _display = "XGBoostTrainer"

    def _make_worker_loop(self):
        def loop(config):
            import xgboost as xgb

            ctx = get_context()
            X, y, _ = _shard_to_matrix(get_dataset_shard("train"),
                                       config["label_column"])
            dtrain = xgb.DMatrix(X, label=y)
            results: dict = {}
            bst = xgb.train(
                config["params"], dtrain,
                num_boost_round=config["num_boost_round"],
                evals=[(dtrain, "train")], evals_result=results,
            )
            metrics = {
                f"train-{k}": v[-1] for k, v in results.get("train", {}).items()
            }
            if ctx.get_world_rank() == 0:
                import tempfile

                from ray_tpu.train.checkpoint import Checkpoint

                with tempfile.TemporaryDirectory() as d:
                    bst.save_model(f"{d}/model.json")
                    report(metrics, checkpoint=Checkpoint.from_directory(d))
            else:
                report(metrics)

        return loop


class LightGBMTrainer(GBDTTrainer):
    """Reference: train/lightgbm/lightgbm_trainer.py."""

    _module = "lightgbm"
    _display = "LightGBMTrainer"

    def _make_worker_loop(self):
        def loop(config):
            import lightgbm as lgb

            ctx = get_context()
            X, y, _ = _shard_to_matrix(get_dataset_shard("train"),
                                       config["label_column"])
            train_set = lgb.Dataset(X, label=y)
            evals: dict = {}
            bst = lgb.train(
                config["params"], train_set,
                num_boost_round=config["num_boost_round"],
                valid_sets=[train_set], valid_names=["train"],
                callbacks=[lgb.record_evaluation(evals)],
            )
            metrics = {
                f"train-{k}": v[-1] for k, v in evals.get("train", {}).items()
            }
            if ctx.get_world_rank() == 0:
                import tempfile

                from ray_tpu.train.checkpoint import Checkpoint

                with tempfile.TemporaryDirectory() as d:
                    bst.save_model(f"{d}/model.txt")
                    report(metrics, checkpoint=Checkpoint.from_directory(d))
            else:
                report(metrics)

        return loop
