"""HuggingFace integrations for ray_tpu.train."""

from ray_tpu.train.huggingface.transformers import (
    RayTrainReportCallback,
    prepare_trainer,
)

__all__ = ["RayTrainReportCallback", "prepare_trainer"]
