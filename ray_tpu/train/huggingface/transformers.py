"""transformers.Trainer integration.

Counterpart of the reference's ray.train.huggingface.transformers
(reference: train/huggingface/transformers/_transformers_utils.py —
prepare_trainer and RayTrainReportCallback). Run a stock
``transformers.Trainer`` inside a TorchTrainer loop: the torch backend
(ray_tpu.train.torch) has already set RANK/WORLD_SIZE and initialized the
gloo process group, which transformers' TrainingArguments picks up, so
``prepare_trainer`` only needs to splice in the report callback and
silence per-rank progress bars on non-zero ranks.

    def loop(config):
        trainer = transformers.Trainer(model, args, train_dataset=ds)
        trainer.add_callback(RayTrainReportCallback())
        trainer = prepare_trainer(trainer)
        trainer.train()

    TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=2)).fit()
"""

from __future__ import annotations

import os

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import get_context, report

try:  # subclass the real TrainerCallback when transformers is present
    from transformers import TrainerCallback as _CallbackBase
except Exception:  # pragma: no cover - transformers always in this image
    _CallbackBase = object


class RayTrainReportCallback(_CallbackBase):
    """transformers.TrainerCallback reporting logs/checkpoints to the
    train session (reference: _transformers_utils.py
    RayTrainReportCallback — on_log buffers metrics; on_save reports the
    just-written HF checkpoint directory as a train Checkpoint).

    Implemented duck-typed (transformers invokes callbacks by attribute)
    so importing this module never requires transformers itself.
    """

    CHECKPOINT_NAME = "checkpoint"

    def __init__(self):
        self._metrics: dict = {}

    # transformers.TrainerCallback surface -----------------------------
    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs:
            self._metrics.update(
                {k: v for k, v in logs.items() if isinstance(v, (int, float))}
            )
            self._metrics["step"] = state.global_step

    def on_save(self, args, state, control, **kwargs):
        ckpt_dir = os.path.join(
            args.output_dir, f"checkpoint-{state.global_step}"
        )
        metrics = dict(self._metrics) or {"step": state.global_step}
        if os.path.isdir(ckpt_dir):
            report(metrics, checkpoint=Checkpoint.from_directory(ckpt_dir))
        else:
            report(metrics)
        self._metrics = {}

    def on_train_end(self, args, state, control, **kwargs):
        if self._metrics:
            report(dict(self._metrics))
            self._metrics = {}

    # Unused TrainerCallback hooks: transformers tolerates their absence
    # only on TrainerCallback subclasses, so provide no-op fallbacks.
    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a, **k: None
        raise AttributeError(name)


def prepare_trainer(trainer):
    """Final fit-up of a transformers.Trainer for the train worker
    (reference: _transformers_utils.py prepare_trainer)."""
    ctx = get_context()
    if ctx.get_world_rank() != 0:
        # Quiet non-chief ranks (the reference disables progress bars on
        # workers; rank-0 keeps user-visible logging).
        trainer.args.disable_tqdm = True
    has_report_cb = any(
        isinstance(cb, RayTrainReportCallback)
        for cb in getattr(trainer, "callback_handler").callbacks
    )
    if not has_report_cb:
        trainer.add_callback(RayTrainReportCallback())
    return trainer
