"""JAX helpers for the per-worker training loop.

The counterparts of the reference's prepare_model/prepare_data_loader
(reference: train/torch/train_loop_utils.py:163,493 — DDP/FSDP wrapping),
reshaped for JAX: instead of wrapping a module, these prepare *pytrees* and
*gradient sync* for the chosen parallelism mode.

Modes:
  - In-process mesh (topology="mesh"): don't use these — shard with
    NamedSharding/pjit and let XLA insert collectives (ray_tpu.parallel).
  - Process-per-host DP: `allreduce_gradients` averages grad pytrees across
    workers via the host collective group; on a real pod the same loop can
    use jax.distributed + in-jit psum instead.
"""

from __future__ import annotations

import numpy as np


def sync_model_params(params, group_name: str = None):
    """Broadcast rank 0's params to all workers (reference analogue: DDP's
    initial parameter broadcast)."""
    import jax

    from ray_tpu.train.session import get_session
    from ray_tpu.util import collective

    session = get_session()
    if session.world_size == 1:
        return params
    group = collective.get_group(group_name or session.group_name)
    leaves, treedef = jax.tree.flatten(params)
    # ONE broadcast for the whole pytree (leaves ship as a single object)
    # — n_leaves round-trips collapse to one.
    synced = group.broadcast_object([np.asarray(l) for l in leaves], src=0)
    return jax.tree.unflatten(treedef, [jax.numpy.asarray(s) for s in synced])


def allreduce_gradients(grads, group_name: str = None, op: str = "mean"):
    """Average gradient pytrees across DP workers.

    All leaves are packed into ONE flat buffer per call (bucketing — same
    motivation as DDP gradient buckets) so the collective count per step is
    1, not n_layers.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.train.session import get_session
    from ray_tpu.util import collective

    session = get_session()
    if session.world_size == 1:
        return grads
    group = collective.get_group(group_name or session.group_name)
    leaves, treedef = jax.tree.flatten(grads)
    # One flat f32 buffer for the wire; each leaf's own dtype is restored on
    # unpack so bf16 training loops keep bf16 grads (reduction in f32 is the
    # standard numerically-safe choice).
    shapes = [l.shape for l in leaves]
    dtypes = [np.asarray(l).dtype for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = np.concatenate(
        [np.asarray(l).astype(np.float32, copy=False).ravel() for l in leaves]
    )
    reduced = group.allreduce(flat, op=op)
    out, pos = [], 0
    for shape, size, dtype in zip(shapes, sizes, dtypes):
        out.append(jnp.asarray(reduced[pos : pos + size].reshape(shape).astype(dtype)))
        pos += size
    return jax.tree.unflatten(treedef, out)


def barrier(group_name: str = None):
    from ray_tpu.train.session import get_session
    from ray_tpu.util import collective

    session = get_session()
    if session.world_size == 1:
        return
    collective.get_group(group_name or session.group_name).barrier()
