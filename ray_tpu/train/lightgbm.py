"""Namespace parity with ray.train.lightgbm (reference:
train/lightgbm/lightgbm_trainer.py)."""

from ray_tpu.train.gbdt import LightGBMTrainer

__all__ = ["LightGBMTrainer"]
