"""PyTorch Lightning integration (import-gated).

Counterpart of the reference's ray.train.lightning
(reference: train/lightning/_lightning_utils.py — RayDDPStrategy,
RayLightningEnvironment, RayTrainReportCallback, prepare_trainer).
Lightning is not installed in this image; every public symbol raises a
clear ImportError at use. The environment/strategy contract mirrors the
reference: ranks and the rendezvous come from the ray_tpu train session
(ray_tpu.train.torch gloo process group), and Lightning is told NOT to
launch its own processes.
"""

from __future__ import annotations

import os


def _lightning():
    try:
        import lightning.pytorch as pl  # lightning>=2
        return pl
    except ImportError:
        try:
            import pytorch_lightning as pl  # legacy package name
            return pl
        except ImportError as e:
            raise ImportError(
                "ray_tpu.train.lightning requires 'lightning' (or "
                "'pytorch_lightning'), which is not installed in this "
                "environment. Install it or write the training loop with "
                "TorchTrainer directly."
            ) from e


def RayDDPStrategy(**kwargs):
    """DDP strategy bound to the session's pre-initialized gloo group
    (reference: _lightning_utils.py RayDDPStrategy)."""
    pl = _lightning()
    from ray_tpu.train.session import get_context

    ctx = get_context()

    class _Strategy(pl.strategies.DDPStrategy):
        @property
        def root_device(self):
            import torch

            return torch.device("cpu")

        @property
        def distributed_sampler_kwargs(self):
            return {"num_replicas": ctx.get_world_size(),
                    "rank": ctx.get_world_rank()}

    return _Strategy(**kwargs)


def RayLightningEnvironment():
    """ClusterEnvironment that reads ranks from the train session
    (reference: _lightning_utils.py RayLightningEnvironment)."""
    pl = _lightning()
    from lightning.fabric.plugins.environments import LightningEnvironment

    from ray_tpu.train.session import get_context

    ctx = get_context()

    class _Env(LightningEnvironment):
        def world_size(self) -> int:
            return ctx.get_world_size()

        def global_rank(self) -> int:
            return ctx.get_world_rank()

        def local_rank(self) -> int:
            return ctx.get_local_rank()

        def node_rank(self) -> int:
            return ctx.get_node_rank()

        @property
        def creates_processes_externally(self) -> bool:
            return True  # ray_tpu spawned the workers already

    return _Env()


class RayTrainReportCallback:
    """Lightning Callback reporting per-epoch metrics + checkpoint
    (reference: _lightning_utils.py RayTrainReportCallback). Duck-typed:
    Lightning calls hooks by name, so no base class import is needed
    until training actually runs."""

    def on_train_epoch_end(self, trainer, pl_module):
        import tempfile

        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.train.session import get_context, report

        metrics = {k: float(v) for k, v in trainer.callback_metrics.items()}
        metrics["epoch"] = trainer.current_epoch
        metrics["step"] = trainer.global_step
        if get_context().get_world_rank() == 0:
            with tempfile.TemporaryDirectory() as d:
                ckpt_path = os.path.join(d, "checkpoint.ckpt")
                trainer.save_checkpoint(ckpt_path, weights_only=False)
                report(metrics, checkpoint=Checkpoint.from_directory(d))
        else:
            report(metrics)

    def __getattr__(self, name):
        if name.startswith("on_") or name in ("setup", "teardown"):
            return lambda *a, **k: None
        raise AttributeError(name)


def prepare_trainer(trainer):
    """Validate a Lightning Trainer for ray_tpu train workers
    (reference: _lightning_utils.py prepare_trainer)."""
    _lightning()
    return trainer


__all__ = [
    "RayDDPStrategy",
    "RayLightningEnvironment",
    "RayTrainReportCallback",
    "prepare_trainer",
]
