"""Per-worker training session: report(), get_checkpoint(), context.

Counterpart of the reference's _TrainSession
(reference: train/_internal/session.py:112 — report :405, public
ray.train.report :672, get_checkpoint :786) and TrainContext
(train/context.py:39 — ranks, world size).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: "TrainSession | None" = None


class TrainSession:
    def __init__(
        self,
        rank: int,
        world_size: int,
        local_rank: int,
        collector,  # ActorHandle of the run's state actor
        experiment_name: str,
        latest_checkpoint: Checkpoint | None = None,
        dataset_shards: dict[str, Any] | None = None,
        start_iteration: int = 0,
        group_name: str | None = None,
    ):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.collector = collector
        self.experiment_name = experiment_name
        # The attempt-unique collective/process-group name (worker_group
        # passes it through; falls back to the legacy derivation).
        self.group_name = group_name or f"train-{experiment_name}"
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        # Non-zero after failure recovery so training_iteration stays
        # monotonic across restarts.
        self.iteration = start_iteration

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None) -> None:
        import ray_tpu

        ckpt_path = None
        if checkpoint is not None:
            # Only rank 0's checkpoint is persisted (reference semantics:
            # train/_internal/session.py — non-rank-0 checkpoints dropped
            # for DP; sharded-ckpt support comes with FSDP paths).
            if self.rank == 0:
                ckpt_path = checkpoint.path
            self.latest_checkpoint = checkpoint
        # Synchronous actor call: gives per-worker ordering + backpressure.
        ray_tpu.get(
            self.collector.report.remote(self.rank, self.iteration, metrics, ckpt_path)
        )
        self.iteration += 1

    def get_checkpoint(self) -> Checkpoint | None:
        return self.latest_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        if name not in self.dataset_shards:
            raise KeyError(f"no dataset {name!r} passed to the trainer")
        return self.dataset_shards[name]


class TrainContext:
    """Reference: train/context.py:39."""

    def get_world_size(self) -> int:
        return get_session().world_size

    def get_world_rank(self) -> int:
        return get_session().rank

    def get_local_rank(self) -> int:
        return get_session().local_rank

    def get_local_world_size(self) -> int:
        return get_session().world_size  # single-node: local == world

    def get_node_rank(self) -> int:
        return 0

    def get_experiment_name(self) -> str:
        return get_session().experiment_name


def set_session(session: TrainSession | None) -> None:
    global _session
    _session = session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No training session active — this API must be called inside "
            "train_loop_per_worker"
        )
    return _session


def in_session() -> bool:
    return _session is not None


# --- public API mirrors (ray.train.*) ---


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_context() -> TrainContext:
    return TrainContext()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def make_temp_checkpoint_dir() -> str:
    """Scratch dir for assembling a checkpoint before report()."""
    return tempfile.mkdtemp(prefix="rtpu_ckpt_stage_")
