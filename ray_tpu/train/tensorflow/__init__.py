"""TensorFlow training backend: MultiWorkerMirroredStrategy via TF_CONFIG.

Counterpart of the reference's ray.train.tensorflow
(reference: train/tensorflow/config.py — _setup_tensorflow_environment
builds TF_CONFIG from the worker group's addresses;
tensorflow_trainer.py TensorflowTrainer; train_loop_utils.py
prepare_dataset_shard). Every worker publishes host:port through the
cluster KV; once all ranks are visible each assembles the identical
TF_CONFIG cluster spec and the user loop creates
``tf.distribute.MultiWorkerMirroredStrategy()``.

    def loop(config):
        strategy = tf.distribute.MultiWorkerMirroredStrategy()
        with strategy.scope():
            model = build_and_compile()
        model.fit(...)

    TensorflowTrainer(loop, scaling_config=ScalingConfig(num_workers=2)).fit()
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import time

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.trainer import JaxTrainer


@dataclasses.dataclass
class TensorflowConfig(BackendConfig):
    """Reference: train/tensorflow/config.py TensorflowConfig."""

    init_timeout_s: float = 120.0

    def backend_cls(self):
        return _TensorflowBackend


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _host_ip() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class _TensorflowBackend(Backend):
    """All-rank address exchange through the cluster KV (the reference
    gathers every worker's address via the worker group and pushes
    TF_CONFIG to each, config.py _setup_tensorflow_environment)."""

    def on_worker_setup(self, rank: int, world_size: int, group_name: str,
                        config: TensorflowConfig | None = None) -> None:
        config = config or TensorflowConfig()
        if world_size <= 1:
            os.environ.pop("TF_CONFIG", None)
            return
        from ray_tpu._private.worker_context import global_runtime

        rt = global_runtime()
        addr = f"{_host_ip()}:{_free_port()}"
        rt.kv_put(f"tf_addr:{group_name}:{rank}", addr.encode(), ns="__train__")
        workers: list[str | None] = [None] * world_size
        deadline = time.time() + config.init_timeout_s
        while time.time() < deadline:
            missing = False
            for r in range(world_size):
                if workers[r] is None:
                    raw = rt.kv_get(f"tf_addr:{group_name}:{r}", ns="__train__")
                    if raw:
                        workers[r] = raw.decode()
                    else:
                        missing = True
            if not missing:
                break
            time.sleep(0.05)
        else:
            absent = [r for r, w in enumerate(workers) if w is None]
            raise TimeoutError(
                f"rank {rank}: TF_CONFIG rendezvous incomplete after "
                f"{config.init_timeout_s}s; missing ranks {absent}"
            )
        os.environ["TF_CONFIG"] = json.dumps({
            "cluster": {"worker": workers},
            "task": {"type": "worker", "index": rank},
        })

    def on_shutdown(self, worker_group, backend_config) -> None:
        try:
            from ray_tpu._private.worker_context import try_runtime

            rt = try_runtime()
            if rt is not None:
                for r in range(worker_group.scaling.num_workers):
                    rt.kv_del(f"tf_addr:{worker_group.group_name}:{r}",
                              ns="__train__")
        except Exception:
            pass


class TensorflowTrainer(JaxTrainer):
    """Reference: train/tensorflow/tensorflow_trainer.py — a
    DataParallelTrainer whose backend assembles TF_CONFIG."""

    def __init__(self, train_loop_per_worker, *, backend_config=None, **kw):
        super().__init__(
            train_loop_per_worker,
            backend_config=backend_config or TensorflowConfig(),
            **kw,
        )


def prepare_dataset_shard(dataset):
    """Disable tf.data auto-sharding: the shard handed to this worker is
    already its slice (reference: train/tensorflow/train_loop_utils.py
    prepare_dataset_shard)."""
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = (
        tf.data.experimental.AutoShardPolicy.OFF
    )
    return dataset.with_options(options)


__all__ = [
    "TensorflowConfig",
    "TensorflowTrainer",
    "prepare_dataset_shard",
]
