"""Torch training backend: DDP over gloo on the cluster's worker group.

Counterpart of the reference's ray.train.torch
(reference: train/torch/config.py:36 TorchConfig, :66
_setup_torch_process_group, :115 dist.init_process_group(nccl|gloo);
torch/torch_trainer.py:11 TorchTrainer; train_loop_utils.py:163
prepare_model wrapping DDP, :493 prepare_data_loader). On TPU machines
torch runs CPU-side (data prep, reference models, CI parity tests), so
the process group backend is gloo; the JAX backend (backend.py) owns the
accelerator path.

    def loop():
        model = prepare_model(Net())
        loader = prepare_data_loader(DataLoader(ds, batch_size=32))
        ...

    TorchTrainer(loop, scaling_config=ScalingConfig(num_workers=4)).fit()
"""

from __future__ import annotations

import dataclasses
import os
import socket
import time
from typing import Any

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.trainer import JaxTrainer


@dataclasses.dataclass
class TorchConfig(BackendConfig):
    """Reference: train/torch/config.py:36. backend: gloo (CPU hosts)."""

    backend: str = "gloo"
    init_timeout_s: float = 120.0

    def backend_cls(self):
        return _TorchBackend


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _host_ip() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class _TorchBackend(Backend):
    """Rank 0 publishes a TCP rendezvous address through the cluster KV
    (the reference broadcasts the rank-0 worker address through the
    worker group, config.py:66-113); every rank then joins the gloo
    process group."""

    def on_worker_setup(self, rank: int, world_size: int, group_name: str,
                        config: TorchConfig | None = None) -> None:
        config = config or TorchConfig()
        if world_size <= 1:
            # A lone worker must look non-distributed: libraries that key
            # off RANK (transformers' TrainingArguments does) would try an
            # env:// rendezvous that was never set up.
            for var in ("RANK", "WORLD_SIZE", "LOCAL_RANK"):
                os.environ.pop(var, None)
            return
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world_size)
        os.environ["LOCAL_RANK"] = str(rank)
        import torch.distributed as dist

        from ray_tpu._private.worker_context import global_runtime

        rt = global_runtime()
        key = f"torch_pg_addr:{group_name}"
        if rank == 0:
            addr = f"{_host_ip()}:{_free_port()}"
            rt.kv_put(key, addr.encode(), ns="__train__")
        else:
            deadline = time.time() + config.init_timeout_s
            addr = None
            while time.time() < deadline:
                raw = rt.kv_get(key, ns="__train__")
                if raw:
                    addr = raw.decode()
                    break
                time.sleep(0.05)
            if addr is None:
                raise TimeoutError(
                    f"rank {rank}: no torch process-group address published "
                    f"by rank 0 within {config.init_timeout_s}s"
                )
        dist.init_process_group(
            backend=config.backend,
            init_method=f"tcp://{addr}",
            rank=rank,
            world_size=world_size,
        )

    def on_shutdown(self, worker_group, backend_config) -> None:
        try:
            import torch.distributed as dist

            if dist.is_initialized():
                dist.destroy_process_group()
        except Exception:
            pass
        # Best-effort rendezvous-key cleanup (the attempt-unique group
        # name already prevents stale reads; this just avoids KV litter).
        try:
            from ray_tpu._private.worker_context import try_runtime

            rt = try_runtime()
            if rt is not None:
                rt.kv_del(f"torch_pg_addr:{worker_group.group_name}",
                          ns="__train__")
        except Exception:
            pass


class TorchTrainer(JaxTrainer):
    """Reference: train/torch/torch_trainer.py:11 — a DataParallelTrainer
    whose backend sets up the torch process group."""

    def __init__(self, train_loop_per_worker, *, backend_config=None, **kw):
        super().__init__(
            train_loop_per_worker,
            backend_config=backend_config or TorchConfig(),
            **kw,
        )


def get_device():
    """Reference: ray.train.torch.get_device — CPU here (TPU compute goes
    through the JAX path)."""
    import torch

    return torch.device("cpu")


def prepare_model(model, *, ddp_kwargs: dict | None = None):
    """Wrap in DistributedDataParallel when world_size > 1 (reference:
    train_loop_utils.py:163)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model, **(ddp_kwargs or {}))
    return model


def prepare_data_loader(loader, *, add_dist_sampler: bool = True):
    """Shard a DataLoader across workers via DistributedSampler
    (reference: train_loop_utils.py:493). The original loader's ordering
    contract is preserved: shuffle only if the incoming sampler shuffles
    (a sequential validation loader stays sequential per shard)."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1 and add_dist_sampler):
        return loader
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    sampler = getattr(loader, "sampler", None)
    if isinstance(sampler, DistributedSampler):
        return loader
    if getattr(loader, "batch_sampler", None) is not None and not hasattr(
        loader.batch_sampler, "sampler"
    ):
        # Custom batch_sampler: cannot be rebuilt faithfully — leave the
        # loader alone (each worker sees the full data; same reference
        # behavior for non-default batch samplers).
        return loader
    shuffle = isinstance(sampler, RandomSampler)
    return DataLoader(
        loader.dataset,
        batch_size=loader.batch_size,
        sampler=DistributedSampler(loader.dataset, shuffle=shuffle),
        num_workers=getattr(loader, "num_workers", 0),
        collate_fn=getattr(loader, "collate_fn", None),
        drop_last=getattr(loader, "drop_last", False),
        pin_memory=getattr(loader, "pin_memory", False),
        worker_init_fn=getattr(loader, "worker_init_fn", None),
        generator=getattr(loader, "generator", None),
        persistent_workers=getattr(loader, "persistent_workers", False),
    )


__all__ = [
    "TorchConfig",
    "TorchTrainer",
    "get_device",
    "prepare_model",
    "prepare_data_loader",
]
