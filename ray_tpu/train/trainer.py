"""JaxTrainer: the TPU-native DataParallelTrainer.

Counterpart of the reference's DataParallelTrainer/TorchTrainer path
(reference: train/data_parallel_trainer.py:26 — training_loop :427;
torch/torch_trainer.py:11; fit entry base_trainer.py:651), redesigned as a
standalone Train-v2-style controller (reference:
train/v2/_internal/execution/controller/controller.py:91) so training does
not route through Tune (SURVEY.md §7 build-order note).

The per-worker loop runs JAX: on one worker per host, in-jit collectives
(psum under shard_map / pjit shardings) carry gradients over ICI; the
host-level collective group carries control-plane sync. With
``topology="mesh"`` a single worker drives every local chip as a Mesh —
the idiomatic single-controller SPMD mode.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Callable

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.worker_group import RunStateActor, WorkerGroup


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        backend_config=None,
        datasets: dict[str, Any] | None = None,
    ):
        from ray_tpu.train.backend import JaxConfig

        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config if backend_config is not None else JaxConfig()
        self.datasets = datasets or {}

    # ------------------------------------------------------------------

    def _dataset_shards(self, n: int) -> list[dict[str, Any]] | None:
        """Split datasets across workers (reference analogue: DataConfig +
        streaming_split, train/_internal/data_config.py:12)."""
        if not self.datasets:
            return None
        shards: list[dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                for i, shard in enumerate(ds.streaming_split(n)):
                    shards[i][name] = shard
            elif hasattr(ds, "split"):
                for i, shard in enumerate(ds.split(n)):
                    shards[i][name] = shard
            else:
                for i in range(n):
                    shards[i][name] = ds
        return shards

    @staticmethod
    def _max_placeable_workers(scaling: ScalingConfig) -> int:
        """How many worker gangs the cluster can place right now, judged
        against TOTAL per-node capacity of alive nodes (reference:
        train/v2 scaling policy reacting to resource availability)."""
        per_worker = scaling.worker_resources()
        if not any(v > 0 for v in per_worker.values()):
            return scaling.num_workers  # zero-demand workers always fit
        fit = 0
        try:
            for node in ray_tpu.nodes():
                if not node.get("alive", True):
                    continue
                total = dict(node.get("resources", {}))
                while all(total.get(k, 0.0) >= v for k, v in per_worker.items()):
                    for k, v in per_worker.items():
                        total[k] = total.get(k, 0.0) - v
                    fit += 1
        except Exception:
            return scaling.num_workers
        return fit

    def fit(self) -> Result:
        ray_tpu.api.auto_init()
        scaling = self.scaling_config
        if scaling.topology == "mesh" and scaling.num_workers != 1:
            raise ValueError("topology='mesh' uses a single controller worker")
        name = self.run_config.name or f"JaxTrainer_{uuid.uuid4().hex[:6]}"
        storage = self.run_config.resolved_storage_path()
        failure_config = self.run_config.failure_config or FailureConfig()
        ckpt_config = self.run_config.checkpoint_config or CheckpointConfig()

        state = RunStateActor.remote(storage, ckpt_config)
        state.set_run_info.remote(name, scaling.num_workers)
        failures_left = failure_config.max_failures
        latest_ckpt: str | None = None
        start_iteration = 0
        error: Exception | None = None

        while True:
            # Attempt-unique group name: collective groups and the torch
            # process-group rendezvous key (train/torch) are keyed by it —
            # a retry must never read the previous (dead) attempt's
            # rendezvous state.
            group = WorkerGroup(
                scaling, self.backend_config,
                group_name=f"train-{name}-{uuid.uuid4().hex[:8]}",
            )
            try:
                refs = group.run(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    state,
                    name,
                    latest_ckpt,
                    self._dataset_shards(scaling.num_workers),
                    start_iteration,
                )
                ray_tpu.get(refs)
                error = None
                break
            except RayTpuError as e:  # covers actor death, crashes, task errors
                error = e
                latest_ckpt = ray_tpu.get(state.latest_checkpoint_path.remote())
                start_iteration = len(ray_tpu.get(state.get_history.remote()))
                if failures_left == 0:
                    break
                if failures_left > 0:
                    failures_left -= 1
                if scaling.elastic:
                    # Elastic restart (reference: train/v2 scaling_policy +
                    # failure_handling): re-fit the gang to what the
                    # cluster can actually place now, down to min_workers.
                    # The next attempt recompiles at the new world size.
                    fit = self._max_placeable_workers(scaling)
                    new_n = max(scaling.min_workers, min(scaling.num_workers, fit))
                    if new_n != scaling.num_workers:
                        scaling = dataclasses.replace(scaling, num_workers=new_n)
                time.sleep(0.5)  # let worker-death cleanup settle
            finally:
                group.shutdown()

        state.finish_run.remote("ERRORED" if error is not None else
                                "FINISHED",
                                repr(error) if error is not None else None)
        history = ray_tpu.get(state.get_history.remote())
        best = ray_tpu.get(state.best_checkpoint_path.remote())
        result = Result(
            metrics=history[-1] if history else {},
            checkpoint=Checkpoint(best) if best else None,
            path=storage,
            metrics_history=history,
            error=error,
        )
        if error is not None:
            raise error
        return result
