"""Worker group: the gang of training worker actors.

Counterpart of the reference's WorkerGroup + BackendExecutor
(reference: train/_internal/worker_group.py:102; backend_executor.py:73 —
start :146, start_training :460). Workers are gang-scheduled through a
placement group built from ScalingConfig (reference: BackendExecutor builds
its PG from ScalingConfig the same way).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import CheckpointConfig, ScalingConfig
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group


@ray_tpu.remote(num_cpus=0)
class RunStateActor:
    """Collects worker reports; owns checkpoint registration.

    Reference analogue: the result-queue + checkpoint handling the trial
    actor does in train v1 (session.py:405 queue path) folded into one
    state actor (train v2 controller state).
    """

    def __init__(self, storage_path: str, ckpt_cfg: CheckpointConfig | None):
        ckpt_cfg = ckpt_cfg or CheckpointConfig()
        self.manager = CheckpointManager(
            storage_path,
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        self.history: list[dict] = []
        self.storage_path = storage_path
        self._run_info: dict | None = None

    def set_run_info(self, name: str, num_workers: int) -> bool:
        """Register this run in the cluster KV so the dashboard's Train
        page can list live/finished runs (reference:
        dashboard/modules/train — run registry fed by the controller)."""
        import time as _time

        self._run_info = {
            "name": name, "status": "RUNNING",
            "num_workers": num_workers, "storage": self.storage_path,
            "started_at": _time.time(), "iterations": 0,
            "last_metrics": {},
        }
        self._publish()
        return True

    def finish_run(self, status: str, error: "str | None" = None) -> bool:
        if self._run_info is not None:
            self._run_info["status"] = status
            if error:
                self._run_info["error"] = error
            self._publish()
        return True

    def _publish(self) -> None:
        import json as _json

        if self._run_info is None:
            return
        info = dict(self._run_info,
                    iterations=len(self.history),
                    last_metrics=self.history[-1] if self.history else {},
                    best_checkpoint=self.best_checkpoint_path())
        try:
            from ray_tpu._private.worker_context import global_runtime

            global_runtime().kv_put(
                info["name"], _json.dumps(info, default=str).encode(),
                ns="__train__")
        except Exception:
            pass  # registry is best-effort observability

    def report(self, rank: int, iteration: int, metrics: dict, ckpt_staging_path: str | None):
        if ckpt_staging_path is not None:
            self.manager.register(ckpt_staging_path, metrics)
        if rank == 0:
            self.history.append(dict(metrics, training_iteration=iteration))
            self._publish()
        return True

    def get_history(self) -> list[dict]:
        return self.history

    def latest_checkpoint_path(self) -> str | None:
        c = self.manager.latest
        return c.path if c else None

    def best_checkpoint_path(self) -> str | None:
        c = self.manager.best
        return c.path if c else None


@ray_tpu.remote
class TrainWorker:
    """One training worker process (reference: the actors WorkerGroup
    spawns; execution path backend_executor.py:460 start_training)."""

    def __init__(self, rank: int, world_size: int, group_name: str, backend_config=None):
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        if backend_config is not None:
            backend = backend_config.backend_cls()()
            # Dispatch on arity, not exception type: a TypeError raised
            # INSIDE setup must propagate, not trigger a silent re-run.
            params = inspect.signature(backend.on_worker_setup).parameters
            if len(params) >= 4:
                backend.on_worker_setup(rank, world_size, group_name, backend_config)
            else:
                backend.on_worker_setup(rank, world_size, group_name)

    def run(
        self,
        fn: Callable,
        config: dict | None,
        collector,
        experiment_name: str,
        latest_ckpt_path: str | None,
        dataset_shards: dict[str, Any] | None,
        start_iteration: int = 0,
    ):
        from ray_tpu.train import session as session_mod

        session = session_mod.TrainSession(
            rank=self.rank,
            world_size=self.world_size,
            local_rank=self.rank,
            collector=collector,
            experiment_name=experiment_name,
            group_name=self.group_name,
            latest_checkpoint=Checkpoint(latest_ckpt_path) if latest_ckpt_path else None,
            dataset_shards=dataset_shards,
            start_iteration=start_iteration,
        )
        session_mod.set_session(session)
        try:
            sig = inspect.signature(fn)
            if len(sig.parameters) == 0:
                fn()
            else:
                fn(config or {})
        finally:
            session_mod.set_session(None)
        return self.rank


class WorkerGroup:
    def __init__(
        self,
        scaling_config: ScalingConfig,
        backend_config,
        group_name: str,
    ):
        self.scaling_config = scaling_config
        self.group_name = group_name
        n = scaling_config.num_workers
        res = scaling_config.worker_resources()
        self.pg: PlacementGroup | None = None
        if n > 1:
            # Fail fast if the gang can never fit (reference analogue:
            # BackendExecutor's resource validation before PG wait).
            total = ray_tpu.cluster_resources()
            for k, v in res.items():
                if total.get(k, 0.0) < v * n:
                    raise ray_tpu.exceptions.PlacementGroupUnschedulableError(
                        f"ScalingConfig needs {v * n} {k} "
                        f"({n} workers x {v}), cluster has {total.get(k, 0.0)}"
                    )
            self.pg = placement_group([dict(res)] * n, strategy=scaling_config.placement_strategy)
            if not self.pg.wait(120):
                remove_placement_group(self.pg)
                raise ray_tpu.exceptions.PlacementGroupUnschedulableError(
                    f"placement group for {n} training workers not ready after 120s"
                )
        self.workers = []
        for rank in range(n):
            opts: dict = {
                "resources": {k: v for k, v in res.items() if k != "CPU"},
                "num_cpus": res.get("CPU", 1),
            }
            if self.pg is not None:
                opts["scheduling_strategy"] = ray_tpu.PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=rank
                )
            self.workers.append(
                TrainWorker.options(**opts).remote(rank, n, group_name, backend_config)
            )

    def run(self, fn, config, collector, experiment_name, latest_ckpt, shards_per_worker, start_iteration=0):
        return [
            w.run.remote(
                fn,
                config,
                collector,
                experiment_name,
                latest_ckpt,
                shards_per_worker[i] if shards_per_worker else None,
                start_iteration,
            )
            for i, w in enumerate(self.workers)
        ]

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
