"""Namespace parity with ray.train.xgboost (reference:
train/xgboost/xgboost_trainer.py)."""

from ray_tpu.train.gbdt import XGBoostTrainer

__all__ = ["XGBoostTrainer"]
