"""ray_tpu.tune: hyperparameter search over the actor runtime.

Counterpart of the reference's python/ray/tune (SURVEY.md §2.3): Tuner.fit
drives a TuneController event loop over trial actors; searchers generate
configs, schedulers make early-stopping / PBT decisions."""

from ray_tpu.train.config import CheckpointConfig, FailureConfig, Result, RunConfig
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
    TuneBOHB,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    HyperOptSearch,
    OptunaSearch,
    Searcher,
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (
    Trainable,
    get_checkpoint,
    get_trial_dir,
    get_trial_id,
    report,
    with_parameters,
    with_resources,
)
from ray_tpu.tune.tuner import ResultGrid, Trial, TuneConfig, TuneController, Tuner, run

__all__ = [
    "ASHAScheduler",
    "HyperBandScheduler",
    "HyperOptSearch",
    "TuneBOHB",
    "CheckpointConfig",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "FIFOScheduler",
    "FailureConfig",
    "MedianStoppingRule",
    "OptunaSearch",
    "PopulationBasedTraining",
    "Result",
    "ResultGrid",
    "RunConfig",
    "Searcher",
    "Trainable",
    "Trial",
    "TrialScheduler",
    "TuneConfig",
    "TuneController",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_trial_dir",
    "get_trial_id",
    "grid_search",
    "lograndint",
    "loguniform",
    "quniform",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
]
