"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Counterpart of the reference's tune/schedulers/: TrialScheduler ABC
(trial_scheduler.py), AsyncHyperBandScheduler/ASHA (async_hyperband.py),
MedianStoppingRule (median_stopping_rule.py), PopulationBasedTraining
(pbt.py). Decisions flow back to the TuneController which owns actor
lifecycle (stop/pause/exploit)."""

from __future__ import annotations

import dataclasses
import math
import random
from collections import defaultdict
from typing import TYPE_CHECKING, Dict, Optional

from ray_tpu.tune.search import Domain

if TYPE_CHECKING:  # pragma: no cover
    from ray_tpu.tune.tuner import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    PAUSE = "PAUSE"

    metric: Optional[str] = None
    mode: str = "max"

    def set_search_properties(self, metric: str | None, mode: str | None) -> None:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    def _score(self, result: dict) -> float | None:
        if self.metric is None or self.metric not in result:
            return None
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_add(self, trial: "Trial") -> None:
        pass

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial: "Trial", result: dict | None) -> None:
        pass

    def on_trial_error(self, trial: "Trial") -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run trials to completion in submission order."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py).

    Rungs at grace_period * reduction_factor^k. When a trial reaches a rung
    it is compared against the rung's history; trials below the top
    1/reduction_factor quantile stop early. Asynchronous: no waiting for a
    full rung before promoting."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
        brackets: int = 1,
    ):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self.rf = reduction_factor
        # bracket b starts at grace * rf^b (HyperBand-style staggering).
        self._bracket_rungs: list[list[float]] = []
        for b in range(brackets):
            rungs, t = [], grace_period * (reduction_factor**b)
            while t < max_t:
                rungs.append(t)
                t *= reduction_factor
            self._bracket_rungs.append(rungs)
        self._rung_scores: Dict[tuple, list[float]] = defaultdict(list)
        self._trial_bracket: Dict[str, int] = {}
        self._rr = 0

    def on_trial_add(self, trial: "Trial") -> None:
        self._trial_bracket[trial.trial_id] = self._rr % len(self._bracket_rungs)
        self._rr += 1

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        bracket = self._trial_bracket.get(trial.trial_id, 0)
        decision = self.CONTINUE
        for rung in reversed(self._bracket_rungs[bracket]):
            if t < rung:
                continue
            key = (bracket, rung, trial.trial_id)
            if key in self._rung_scores:
                break  # already recorded at this rung
            scores = self._rung_scores[(bracket, rung)]
            scores.append(score)
            self._rung_scores[key] = [score]
            if len(scores) > 1:
                cutoff_idx = max(0, int(len(scores) / self.rf) - 1)
                cutoff = sorted(scores, reverse=True)[cutoff_idx]
                if score < cutoff:
                    decision = self.STOP
            break
        return decision


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average score falls below the median of
    other trials' averages at the same point in time
    (reference: tune/schedulers/median_stopping_rule.py)."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str = "max",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, list[float]] = defaultdict(list)

    def on_trial_result(self, trial: "Trial", result: dict) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return self.CONTINUE
        self._history[trial.trial_id].append(score)
        if t < self.grace_period:
            return self.CONTINUE
        means = [
            sum(h) / len(h)
            for tid, h in self._history.items()
            if tid != trial.trial_id and h
        ]
        if len(means) < self.min_samples:
            return self.CONTINUE
        median = sorted(means)[len(means) // 2]
        best = max(self._history[trial.trial_id])
        return self.STOP if best < median else self.CONTINUE


@dataclasses.dataclass
class ExploitDecision:
    """PBT: restart `trial` from `source`'s checkpoint with a mutated config."""

    source: "Trial"
    new_config: dict


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every perturbation_interval,
    bottom-quantile trials clone a top-quantile trial's checkpoint and
    perturb its hyperparameters (×1.2 / ×0.8, or resample)."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str = "max",
        perturbation_interval: int = 10,
        hyperparam_mutations: dict | None = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        synch: bool = False,
        seed: int | None = None,
    ):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.synch = synch
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = defaultdict(float)
        self._latest: Dict[str, tuple[float, float]] = {}  # tid -> (t, score)
        self._at_boundary: set[str] = set()  # synch mode: trials held paused

    def _mutate(self, config: dict) -> dict:
        new = dict(config)
        for k, spec in self.mutations.items():
            if self._rng.random() < self.resample_p or k not in new:
                if isinstance(spec, Domain):
                    new[k] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    new[k] = self._rng.choice(spec)
                elif callable(spec):
                    new[k] = spec()
            else:
                cur = new[k]
                if isinstance(spec, list):
                    idx = spec.index(cur) if cur in spec else 0
                    idx += self._rng.choice([-1, 1])
                    new[k] = spec[max(0, min(len(spec) - 1, idx))]
                elif isinstance(cur, (int, float)):
                    factor = self._rng.choice([0.8, 1.2])
                    new[k] = type(cur)(cur * factor) if isinstance(cur, float) else max(1, int(cur * factor))
        return new

    def on_trial_result(self, trial: "Trial", result: dict):
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return self.CONTINUE
        self._latest[trial.trial_id] = (t, score)
        if t - self._last_perturb[trial.trial_id] < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        if self.synch:
            # Hold the trial at the boundary; exploits happen in
            # resume_decisions once every live trial arrives
            # (reference: pbt.py synch=True).
            self._at_boundary.add(trial.trial_id)
            return self.PAUSE
        peers = sorted(self._latest.items(), key=lambda kv: kv[1][1])
        n = len(peers)
        k = max(1, int(math.ceil(n * self.quantile)))
        if n < 2 * k:
            return self.CONTINUE
        bottom = {tid for tid, _ in peers[:k]}
        top = [tid for tid, _ in peers[-k:]]
        if trial.trial_id not in bottom:
            return self.CONTINUE
        source_id = self._rng.choice(top)
        source = next((x for x in trial.experiment_trials if x.trial_id == source_id), None)
        if source is None or source is trial:
            return self.CONTINUE
        return ExploitDecision(source=source, new_config=self._mutate(source.config))

    def on_trial_complete(self, trial: "Trial", result: dict | None) -> None:
        # Dead trials must neither anchor the bottom quantile nor be
        # picked as exploit sources.
        self._latest.pop(trial.trial_id, None)
        self._at_boundary.discard(trial.trial_id)

    def on_trial_error(self, trial: "Trial") -> None:
        self._latest.pop(trial.trial_id, None)
        self._at_boundary.discard(trial.trial_id)

    # --- synch-mode controller hooks ---

    def may_resume(self, trial: "Trial") -> bool:
        return trial.trial_id not in self._at_boundary

    def resume_decisions(self, trials) -> dict:
        """Once all live trials are paused at the perturbation boundary,
        release them — bottom-quantile trials with (mutated config, source
        checkpoint). Returns {trial: (config, checkpoint_path | None)}."""
        if not self._at_boundary:
            return {}
        live = [t for t in trials if t.status in ("RUNNING", "PAUSED", "PENDING")]
        held = [t for t in live if t.trial_id in self._at_boundary]
        if any(t.status != "PAUSED" for t in live) or len(held) < len(live):
            return {}  # someone is still training toward the boundary
        ranked = sorted(held, key=lambda t: self._latest[t.trial_id][1])
        n = len(ranked)
        k = max(1, int(math.ceil(n * self.quantile)))
        decisions: dict = {}
        if n >= 2 * k:
            top = ranked[-k:]
            for t in ranked[:k]:
                source = self._rng.choice(top)
                if source.checkpoint_path:
                    decisions[t] = (self._mutate(source.config), source.checkpoint_path)
        for t in held:
            decisions.setdefault(t, (t.config, None))
        self._at_boundary.clear()
        return decisions


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference: tune/schedulers/hyperband.py).

    Trials are assigned to brackets s = s_max..0; bracket s admits
    n_s = ceil((s_max+1)/(s+1) * eta^s) trials with initial rung budget
    r_s = max_t * eta^-s. At each rung every live bracket member PAUSES
    until the whole bracket arrives, then the top 1/eta continue to the
    next rung (budget *= eta) and the rest stop — the synchronous cut
    ASHA deliberately forgoes. Losers are reaped through the
    ``pending_stops`` controller hook."""

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: str | None = None,
        mode: str = "max",
        max_t: int = 81,
        reduction_factor: float = 3,
    ):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self.eta = reduction_factor
        self._s_max = int(math.log(max_t) / math.log(reduction_factor))
        self._next_s = self._s_max
        self._brackets: list[dict] = []
        self._trial_bracket: Dict[str, dict] = {}
        self._to_stop: set[str] = set()

    def _new_bracket(self) -> dict:
        s = self._next_s
        self._next_s = self._s_max if self._next_s == 0 else self._next_s - 1
        n = int(math.ceil((self._s_max + 1) / (s + 1) * self.eta**s))
        r = self.max_t * self.eta ** (-s)
        bracket = {"s": s, "n": n, "r": max(1.0, r), "trials": [],
                   "scores": {}, "reached": set()}
        self._brackets.append(bracket)
        return bracket

    def on_trial_add(self, trial: "Trial") -> None:
        bracket = next(
            (b for b in self._brackets if len(b["trials"]) < b["n"]), None
        ) or self._new_bracket()
        bracket["trials"].append(trial)
        self._trial_bracket[trial.trial_id] = bracket

    def _live_members(self, bracket: dict) -> list:
        return [t for t in bracket["trials"]
                if t.status not in ("TERMINATED", "ERROR")
                and t.trial_id not in self._to_stop]

    def _cut(self, bracket: dict) -> None:
        """All live members reached the rung: keep the top 1/eta."""
        ranked = sorted(bracket["scores"].items(), key=lambda kv: kv[1])
        n_live = len(ranked)
        keep = max(1, int(n_live / self.eta))
        if bracket["r"] * self.eta > self.max_t:
            keep = n_live  # final rung: everyone left runs to max_t
        losers = [tid for tid, _ in ranked[:-keep]] if keep < n_live else []
        self._to_stop.update(losers)
        bracket["r"] = bracket["r"] * self.eta
        bracket["scores"] = {}
        bracket["reached"] = set()

    def on_trial_result(self, trial: "Trial", result: dict):
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return self.STOP
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return self.CONTINUE
        if t < bracket["r"] or trial.trial_id in bracket["reached"]:
            return self.CONTINUE
        score = self._score(result)
        if score is None:
            return self.CONTINUE
        bracket["reached"].add(trial.trial_id)
        bracket["scores"][trial.trial_id] = score
        live = self._live_members(bracket)
        if all(x.trial_id in bracket["reached"] for x in live):
            self._cut(bracket)
            if trial.trial_id in self._to_stop:
                self._to_stop.discard(trial.trial_id)
                return self.STOP
            return self.CONTINUE
        return self.PAUSE

    def on_trial_complete(self, trial: "Trial", result: dict | None) -> None:
        self._finalize(trial)

    def on_trial_error(self, trial: "Trial") -> None:
        self._finalize(trial)

    def _finalize(self, trial: "Trial") -> None:
        bracket = self._trial_bracket.pop(trial.trial_id, None)
        self._to_stop.discard(trial.trial_id)
        if bracket is None:
            return
        bracket["reached"].discard(trial.trial_id)
        bracket["scores"].pop(trial.trial_id, None)
        # A member dying can complete the rung for the rest.
        live = self._live_members(bracket)
        if live and bracket["reached"] and all(
            x.trial_id in bracket["reached"] for x in live
        ):
            self._cut(bracket)

    # --- controller hooks ---

    def may_resume(self, trial: "Trial") -> bool:
        if trial.trial_id in self._to_stop:
            return False
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return True
        # Resume only once the rung cut released this trial.
        return trial.trial_id not in bracket["reached"]

    def pending_stops(self, trials) -> list:
        out = [t for t in trials
               if t.trial_id in self._to_stop and t.status == "PAUSED"]
        return out


class TuneBOHB(TrialScheduler):
    """BOHB (reference: tune/schedulers/hb_bohb.py + search/bohb) needs the
    hpbandster package, which is not installed in this image; construction
    raises with guidance. Use HyperBandScheduler + OptunaSearch for a
    comparable model-based bandit setup."""

    def __init__(self, *a, **kw):
        raise ImportError(
            "TuneBOHB requires 'hpbandster', which is not installed in this "
            "environment. Use HyperBandScheduler (+ OptunaSearch) instead."
        )
