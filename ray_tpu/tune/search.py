"""Search spaces and search algorithms.

Counterpart of the reference's tune/search/: sample domains
(tune/search/sample.py — Float/Integer/Categorical, grid_search),
Searcher ABC (tune/search/searcher.py), and the default
BasicVariantGenerator (tune/search/basic_variant.py) that expands
`grid_search` entries into a cartesian product and samples the rest.
External searcher backends (optuna/hyperopt/...) plug in via the same
Searcher ABC; OptunaSearch is provided when optuna is importable.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Optional


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False, q: float | None = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng: random.Random) -> int:
        if self.log:
            v = int(math.exp(rng.uniform(math.log(self.lower), math.log(self.upper))))
            return max(self.lower, min(self.upper - 1, v))
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng: random.Random):
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng: random.Random) -> float:
        return rng.gauss(self.mean, self.sd)


class SampleFrom(Domain):
    """Arbitrary callable over the (partially resolved) config."""

    def __init__(self, fn: Callable[[dict], Any]):
        self.fn = fn

    def sample(self, rng: random.Random):  # resolved specially (needs config)
        raise TypeError("SampleFrom is resolved against the trial config")


# --- public constructors (ray.tune.* naming) ---


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories) -> Categorical:
    return Categorical(categories)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def sample_from(fn: Callable[[dict], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values) -> dict:
    return {"grid_search": list(values)}


# ---------------------------------------------------------------------------


def _is_grid(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _resolve(config: dict, rng: random.Random) -> dict:
    """Sample every Domain; SampleFrom last (sees sampled siblings)."""
    out: Dict[str, Any] = {}
    deferred: list[tuple[str, SampleFrom]] = []
    for k, v in config.items():
        if isinstance(v, SampleFrom):
            deferred.append((k, v))
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict) and not _is_grid(v):
            out[k] = _resolve(v, rng)
        else:
            out[k] = v
    for k, sf in deferred:
        out[k] = sf.fn(out)
    return out


class Searcher:
    """ABC for search algorithms (reference: tune/search/searcher.py).

    `suggest` returns the next config (or None when exhausted);
    `on_trial_complete` feeds the final observation back.
    """

    metric: Optional[str] = None
    mode: Optional[str] = None

    def set_search_properties(self, metric: str | None, mode: str | None, config: dict) -> None:
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None, error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid × random expansion (reference: tune/search/basic_variant.py).

    Every `grid_search` key contributes a cartesian-product axis; each of
    `num_samples` repetitions re-samples the stochastic domains across the
    full grid (reference semantics: num_samples multiplies the grid).
    """

    def __init__(self, param_space: dict | None = None, num_samples: int = 1, seed: int | None = None):
        self._space = param_space or {}
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._variants = self._generate()
        self._i = 0

    def _grid_axes(self, config: dict, prefix=()) -> list[tuple[tuple, list]]:
        axes = []
        for k, v in config.items():
            if _is_grid(v):
                axes.append((prefix + (k,), v["grid_search"]))
            elif isinstance(v, dict):
                axes.extend(self._grid_axes(v, prefix + (k,)))
        return axes

    @staticmethod
    def _set_path(config: dict, path: tuple, value) -> None:
        for k in path[:-1]:
            config = config[k]
        config[path[-1]] = value

    def _generate(self) -> list[dict]:
        import copy

        axes = self._grid_axes(self._space)
        combos = list(itertools.product(*[vals for _, vals in axes])) if axes else [()]
        variants = []
        for _ in range(self._num_samples):
            for combo in combos:
                cfg = copy.deepcopy(self._space)
                for (path, _), value in zip(axes, combo):
                    self._set_path(cfg, path, value)
                variants.append(_resolve(cfg, self._rng))
        return variants

    def __len__(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._i >= len(self._variants):
            return None
        cfg = self._variants[self._i]
        self._i += 1
        return cfg


class RepeatedRandomSearch(Searcher):
    """Pure random search over a space with no grid axes, unbounded until
    num_samples trials have been suggested."""

    def __init__(self, param_space: dict, num_samples: int, seed: int | None = None):
        self._space = param_space
        self._remaining = num_samples
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._remaining <= 0:
            return None
        self._remaining -= 1
        return _resolve(self._space, self._rng)


try:  # optional backend, mirrors reference tune/search/optuna/optuna_search.py
    import optuna as _optuna  # noqa: F401

    class OptunaSearch(Searcher):
        def __init__(self, space: dict, metric: str, mode: str, seed: int | None = None):
            sampler = _optuna.samplers.TPESampler(seed=seed)
            direction = "maximize" if mode == "max" else "minimize"
            self._study = _optuna.create_study(sampler=sampler, direction=direction)
            self._space = space
            self._trials: dict[str, Any] = {}
            self.metric, self.mode = metric, mode

        def suggest(self, trial_id: str) -> Optional[dict]:
            t = self._study.ask()
            cfg = {}
            for k, v in self._space.items():
                if isinstance(v, Float):
                    cfg[k] = t.suggest_float(k, v.lower, v.upper, log=v.log)
                elif isinstance(v, Integer):
                    cfg[k] = t.suggest_int(k, v.lower, v.upper - 1, log=v.log)
                elif isinstance(v, Categorical):
                    cfg[k] = t.suggest_categorical(k, v.categories)
                else:
                    cfg[k] = v
            self._trials[trial_id] = t
            return cfg

        def on_trial_complete(self, trial_id: str, result=None, error: bool = False):
            t = self._trials.pop(trial_id, None)
            if t is None:
                return
            if error or result is None or self.metric not in result:
                self._study.tell(t, state=_optuna.trial.TrialState.FAIL)
            else:
                self._study.tell(t, result[self.metric])

except ImportError:  # pragma: no cover
    OptunaSearch = None  # type: ignore[assignment]


try:  # optional backend, mirrors reference tune/search/hyperopt
    import hyperopt as _hyperopt  # noqa: F401

    class HyperOptSearch(Searcher):
        """TPE via hyperopt (reference: tune/search/hyperopt/
        hyperopt_search.py). Space entries map to hp.uniform/loguniform/
        quniform/randint/choice from the shared Domain types."""

        def __init__(self, space: dict, metric: str, mode: str = "max",
                     seed: int | None = None):
            from hyperopt import hp

            self.metric, self.mode = metric, mode
            self._space = dict(space)
            hspace = {}
            for k, v in self._space.items():
                if isinstance(v, Float):
                    if v.q:
                        hspace[k] = (hp.qloguniform(k, math.log(v.lower),
                                                    math.log(v.upper), v.q)
                                     if v.log
                                     else hp.quniform(k, v.lower, v.upper, v.q))
                    else:
                        hspace[k] = (hp.loguniform(k, math.log(v.lower),
                                                   math.log(v.upper))
                                     if v.log
                                     else hp.uniform(k, v.lower, v.upper))
                elif isinstance(v, Integer):
                    hspace[k] = hp.randint(k, v.lower, v.upper)
                elif isinstance(v, Categorical):
                    hspace[k] = hp.choice(k, v.categories)
                elif isinstance(v, Normal):
                    hspace[k] = hp.normal(k, v.mean, v.sd)
                # Other domains (SampleFrom, plugins) are outside TPE's
                # model: resolved per-suggest by direct sampling below.
            self._hspace = hspace
            self._py_rng = random.Random(seed)
            self._domain = _hyperopt.Domain(lambda c: 0.0, hspace)
            self._hp_trials = _hyperopt.Trials()
            self._rng = __import__("numpy").random.default_rng(seed)
            self._tid = 0
            self._by_trial: dict[str, int] = {}

        def suggest(self, trial_id: str):
            from hyperopt import base

            self._tid += 1
            seed = int(self._rng.integers(2**31))
            new = _hyperopt.tpe.suggest(
                [self._tid], self._domain, self._hp_trials, seed)
            self._hp_trials.insert_trial_docs(new)
            self._hp_trials.refresh()
            doc = self._hp_trials._dynamic_trials[-1]
            vals = {k: v[0] for k, v in doc["misc"]["vals"].items() if v}
            cfg = dict(self._space)
            for k, v in self._space.items():
                if isinstance(v, Categorical) and k in vals:
                    cfg[k] = v.categories[int(vals[k])]
                elif k in vals:
                    cfg[k] = int(vals[k]) if isinstance(v, Integer) else float(vals[k])
                elif isinstance(v, SampleFrom):
                    cfg[k] = v.fn(cfg)
                elif isinstance(v, Domain):
                    # Domain outside the TPE model: plain random sample.
                    cfg[k] = v.sample(self._py_rng)
            self._by_trial[trial_id] = self._tid
            doc["state"] = base.JOB_STATE_RUNNING
            return cfg

        def on_trial_complete(self, trial_id: str, result=None, error: bool = False):
            from hyperopt import base

            tid = self._by_trial.pop(trial_id, None)
            if tid is None:
                return
            doc = next((d for d in self._hp_trials._dynamic_trials
                        if d["tid"] == tid), None)
            if doc is None:
                return
            if error or result is None or self.metric not in result:
                doc["state"] = base.JOB_STATE_ERROR
            else:
                score = float(result[self.metric])
                loss = -score if self.mode == "max" else score
                doc["result"] = {"loss": loss, "status": base.STATUS_OK}
                doc["state"] = base.JOB_STATE_DONE
            self._hp_trials.refresh()

except ImportError:  # pragma: no cover
    HyperOptSearch = None  # type: ignore[assignment]
