"""Trainable API + the trial actor that hosts it.

Counterpart of the reference's tune/trainable/trainable.py:57 (class API:
setup/step/save_checkpoint/load_checkpoint) and function trainables
(tune/trainable/function_trainable.py — the user function runs on its own
thread and `tune.report` hands results to the controller with
backpressure). One `TrialActor` process hosts one trial; the controller
drives it via `step()` calls, so pausing/stopping a trial never blocks the
experiment loop.
"""

from __future__ import annotations

import functools
import inspect
import os
import queue
import shutil
import threading
import time
from typing import Any, Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class trainable (reference: tune/trainable/trainable.py:57)."""

    def __init__(self, config: dict | None = None, trial_dir: str | None = None):
        self.config = config or {}
        self.trial_dir = trial_dir or os.getcwd()
        self.iteration = 0
        self.setup(self.config)

    # --- subclass surface ---

    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[dict]:
        raise NotImplementedError(f"{type(self).__name__} does not implement save_checkpoint")

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        raise NotImplementedError(f"{type(self).__name__} does not implement load_checkpoint")

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Return True if the trainable can hot-swap configs (PBT exploit
        without an actor restart). Default: not supported."""
        return False

    # --- driver surface ---

    def train(self) -> dict:
        result = self.step() or {}
        self.iteration += 1
        result.setdefault(TRAINING_ITERATION, self.iteration)
        return result

    def save(self, checkpoint_dir: str | None = None) -> str:
        """Checkpoint to ``checkpoint_dir`` (default: a fresh
        ``checkpoint_{iteration:06d}`` under the trial dir) and return
        the path (reference: Trainable.save, trainable.py:467)."""
        dest = checkpoint_dir or os.path.join(
            self.trial_dir, f"checkpoint_{self.iteration:06d}")
        os.makedirs(dest, exist_ok=True)
        self.save_checkpoint(dest)
        return dest

    def restore(self, checkpoint_path: str) -> None:
        """Load state saved by :meth:`save` (reference:
        Trainable.restore, trainable.py:507)."""
        self.load_checkpoint(checkpoint_path)


def with_resources(trainable, resources: dict):
    """Attach per-trial resource requests to a trainable (reference:
    tune/trainable/util.py:147 with_resources). ``resources`` uses the
    reference's shorthand ({"cpu": 2, "gpu"/"tpu": 1, custom: n}) or
    our remote-options form ({"num_cpus": 2, "resources": {...}});
    overrides TuneConfig.trial_resources for this trainable."""
    opts: dict = {}
    custom: dict = {}
    for k, v in resources.items():
        if k in ("cpu", "CPU", "num_cpus"):
            opts["num_cpus"] = v
        elif k in ("tpu", "TPU", "gpu", "GPU", "num_tpus"):
            opts["num_tpus"] = v
        elif k == "resources" and isinstance(v, dict):
            custom.update(v)
        else:
            custom[k] = v
    if custom:
        opts["resources"] = custom

    if isinstance(trainable, type):
        wrapped = type(trainable.__name__, (trainable,), {})
    else:
        @functools.wraps(trainable)
        def wrapped(*a, **kw):
            return trainable(*a, **kw)
    wrapped._tune_resources = opts
    return wrapped


def with_parameters(trainable, **kwargs):
    """Bind large objects to a trainable via the object store so every
    trial resolves them from shm instead of re-pickling them into each
    actor (reference: tune/trainable/util.py:21 with_parameters). The
    trainable receives them as keyword arguments after ``config``."""
    import ray_tpu

    ray_tpu.api.auto_init()
    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    if isinstance(trainable, type):
        raise TypeError(
            "with_parameters supports function trainables; class "
            "trainables can take ObjectRefs in their config directly")

    @functools.wraps(trainable)
    def wrapped(config):
        import ray_tpu as _rt

        resolved = {k: _rt.get(r) for k, r in refs.items()}
        return trainable(config, **resolved)

    # Keep any resource annotation from an inner with_resources wrap.
    if hasattr(trainable, "_tune_resources"):
        wrapped._tune_resources = trainable._tune_resources
    return wrapped


class _StopTrial(Exception):
    """Raised inside a function trainable's thread to unwind it."""


class _TuneSession:
    """Per-process session backing `tune.report` inside function trainables."""

    def __init__(self, trial_id: str, trial_dir: str, checkpoint: Checkpoint | None):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.checkpoint = checkpoint
        self.results: "queue.Queue[tuple]" = queue.Queue()
        self.resume = threading.Event()
        self.stopped = False

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None) -> None:
        if self.stopped:
            raise _StopTrial()
        if checkpoint is not None:
            self.checkpoint = checkpoint
        self.results.put(("result", dict(metrics), checkpoint))
        # Backpressure: wait for the controller to consume this result
        # before computing the next one (reference function-trainable
        # semantics), so PAUSE/STOP decisions apply promptly.
        self.resume.wait()
        self.resume.clear()
        if self.stopped:
            raise _StopTrial()


_session: _TuneSession | None = None


def _set_session(s: _TuneSession | None) -> None:
    global _session
    _session = s


def get_session() -> _TuneSession:
    if _session is None:
        raise RuntimeError("tune.report()/get_checkpoint() called outside a Tune trial")
    return _session


def in_session() -> bool:
    return _session is not None


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """Public `tune.report` (also reachable as ray_tpu.tune.report)."""
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    return get_session().checkpoint


def get_trial_id() -> str:
    return get_session().trial_id


def get_trial_dir() -> str:
    return get_session().trial_dir


def is_function_trainable(t: Any) -> bool:
    return callable(t) and not (inspect.isclass(t) and issubclass(t, Trainable))


class TrialActor:
    """Hosts one trial: a Trainable instance or a function-on-a-thread.

    Spawned via ray_tpu actors by the TuneController
    (reference analogue: tune_controller.py:964 _schedule_trial_actor).
    """

    def __init__(
        self,
        trainable: Any,
        config: dict,
        trial_id: str,
        trial_dir: str,
        checkpoint_path: str | None = None,
    ):
        os.makedirs(trial_dir, exist_ok=True)
        self._config = config
        self._trial_id = trial_id
        self._trial_dir = trial_dir
        self._ckpt_index = 0
        self._latest_ckpt = checkpoint_path
        self._start = time.monotonic()
        self._trainable: Trainable | None = None
        self._fn: Callable | None = None
        self._thread: threading.Thread | None = None
        self._fn_error: list[BaseException] = []
        self._last_metrics: dict = {}
        self._iter = 0
        if is_function_trainable(trainable):
            self._fn = trainable
            self._sess = _TuneSession(
                trial_id, trial_dir, Checkpoint(checkpoint_path) if checkpoint_path else None
            )
        else:
            self._trainable = trainable(config, trial_dir)
            if checkpoint_path:
                self._trainable.load_checkpoint(checkpoint_path)
                # Iteration count continues from the checkpoint's manifest.
                meta = os.path.join(checkpoint_path, ".tune_iteration")
                if os.path.exists(meta):
                    with open(meta) as f:
                        self._trainable.iteration = int(f.read())

    # ------------------------------------------------------------------

    def _fn_main(self) -> None:
        _set_session(self._sess)
        try:
            self._fn(self._config)
        except _StopTrial:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced via step()
            self._fn_error.append(e)
        finally:
            self._sess.results.put(("done",))
            _set_session(None)

    def step(self) -> dict:
        """Run/collect one reporting interval. Returns the result dict with
        `done=True` appended when the trial is finished."""
        if self._trainable is not None:
            result = self._trainable.train()
            result[DONE] = bool(result.get(DONE, False))
            result["time_total_s"] = time.monotonic() - self._start
            return result
        if self._thread is None:
            self._thread = threading.Thread(target=self._fn_main, daemon=True, name="tune-fn")
            self._thread.start()
        item = self._sess.results.get()
        if item[0] == "done":
            self._thread.join()
            if self._fn_error:
                raise self._fn_error[0]
            # Function returned: final result repeats the last reported
            # metrics with done=True (reference function-trainable behavior).
            final = dict(self._last_metrics)
            final[DONE] = True
            final[TRAINING_ITERATION] = max(self._iter, 1)
            final["time_total_s"] = time.monotonic() - self._start
            return final
        _, metrics, checkpoint = item
        if checkpoint is not None:
            self._latest_ckpt = self._persist(checkpoint)
        self._sess.resume.set()
        self._iter += 1
        metrics.setdefault(TRAINING_ITERATION, self._iter)
        metrics[DONE] = bool(metrics.get(DONE, False))
        metrics["time_total_s"] = time.monotonic() - self._start
        self._last_metrics = {k: v for k, v in metrics.items() if k != "time_total_s"}
        return metrics

    def _persist(self, checkpoint: Checkpoint) -> str:
        dest = os.path.join(self._trial_dir, f"checkpoint_{self._ckpt_index:06d}")
        self._ckpt_index += 1
        if os.path.abspath(checkpoint.path) != dest:
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint.path, dest)
        return dest

    def save(self) -> str | None:
        """Checkpoint the trial; returns the checkpoint path."""
        if self._trainable is not None:
            dest = os.path.join(self._trial_dir, f"checkpoint_{self._ckpt_index:06d}")
            self._ckpt_index += 1
            os.makedirs(dest, exist_ok=True)
            self._trainable.save_checkpoint(dest)
            with open(os.path.join(dest, ".tune_iteration"), "w") as f:
                f.write(str(self._trainable.iteration))
            self._latest_ckpt = dest
            return dest
        return self._latest_ckpt  # function trials: latest reported checkpoint

    def latest_checkpoint(self) -> str | None:
        return self._latest_ckpt

    def reset(self, new_config: dict) -> bool:
        """PBT exploit fast path: swap config in place if supported."""
        if self._trainable is not None and self._trainable.reset_config(new_config):
            self._trainable.config = new_config
            self._config = new_config
            return True
        return False

    def restore(self, checkpoint_path: str) -> None:
        if self._trainable is not None:
            self._trainable.load_checkpoint(checkpoint_path)
            meta = os.path.join(checkpoint_path, ".tune_iteration")
            if os.path.exists(meta):
                with open(meta) as f:
                    self._trainable.iteration = int(f.read())
        self._latest_ckpt = checkpoint_path

    def stop(self) -> None:
        if self._trainable is not None:
            self._trainable.cleanup()
        elif self._thread is not None and self._thread.is_alive():
            self._sess.stopped = True
            self._sess.resume.set()
            self._thread.join(timeout=2.0)
