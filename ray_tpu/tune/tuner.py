"""Tuner + TuneController: the experiment event loop.

Counterpart of the reference's Tuner.fit (tune/tuner.py:312) →
TunerInternal (tune/impl/tuner_internal.py:63) → tune.run (tune/tune.py:267)
→ TuneController.step (tune/execution/tune_controller.py:666), which
manages trial actors (_schedule_trial_actor :964) and routes results
through searchers/schedulers. Redesigned: trials are plain ray_tpu actors
driven by an ObjectRef wait-loop — no separate RayTrialExecutor layer.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, Result, RunConfig
from ray_tpu.tune.schedulers import ExploitDecision, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import DONE, TRAINING_ITERATION, TrialActor

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class TuneConfig:
    """Reference: tune/tune_config.py TuneConfig."""

    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    search_alg: Searcher | None = None
    scheduler: TrialScheduler | None = None
    time_budget_s: float | None = None
    trial_resources: dict[str, float] | None = None
    reuse_actors: bool = False


class Trial:
    """One hyperparameter configuration's lifecycle
    (reference: tune/experiment/trial.py)."""

    def __init__(self, trial_id: str, config: dict, trial_dir: str):
        self.trial_id = trial_id
        self.config = config
        self.trial_dir = trial_dir
        self.status = PENDING
        self.actor = None
        self.last_result: dict = {}
        self.metrics_history: list[dict] = []
        self.checkpoint_path: str | None = None
        self.num_failures = 0
        self.error: Exception | None = None
        self.experiment_trials: list["Trial"] = []  # back-ref, set by controller

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, results: list[Result], trials: list[Trial], metric: str | None, mode: str):
        self._results = results
        self._trials = trials
        self._metric, self._mode = metric, mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    @property
    def errors(self) -> list[Exception]:
        return [t.error for t in self._trials if t.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    def get_best_result(self, metric: str | None = None, mode: str | None = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("pass metric= or set TuneConfig(metric=...)")
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics for r in self._results])


class TuneController:
    """The experiment loop (reference: tune/execution/tune_controller.py:68)."""

    def __init__(
        self,
        trainable: Any,
        param_space: dict | None,
        tune_config: TuneConfig,
        run_config: RunConfig,
    ):
        self.trainable = trainable
        self.tune_config = tune_config
        self.run_config = run_config
        self.experiment_dir = run_config.resolved_storage_path()
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(tune_config.metric, tune_config.mode)
        if tune_config.search_alg is not None:
            self.searcher = tune_config.search_alg
            self.searcher.set_search_properties(tune_config.metric, tune_config.mode, param_space or {})
            self._max_trials = tune_config.num_samples
        else:
            self.searcher = BasicVariantGenerator(param_space, tune_config.num_samples)
            self._max_trials = len(self.searcher)
        failure = run_config.failure_config or FailureConfig()
        self.max_failures_per_trial = failure.max_failures
        ckpt_cfg = run_config.checkpoint_config
        self.checkpoint_frequency = ckpt_cfg.checkpoint_frequency if ckpt_cfg else 0
        self.trials: list[Trial] = []
        self._futures: dict[ray_tpu.ObjectRef, Trial] = {}
        self._deadline = (
            time.monotonic() + tune_config.time_budget_s if tune_config.time_budget_s else None
        )
        # Per-trainable annotation (tune.with_resources) overrides the
        # TuneConfig-wide trial_resources.
        trial_res = (getattr(trainable, "_tune_resources", None)
                     or tune_config.trial_resources or {"num_cpus": 0})
        self._remote_actor_cls = ray_tpu.remote(**trial_res)(TrialActor)

    # ------------------------------------------------------------------

    def _next_trial(self) -> Optional[Trial]:
        if len(self.trials) >= self._max_trials:
            return None
        trial_id = f"{len(self.trials):05d}_{uuid.uuid4().hex[:4]}"
        config = self.searcher.suggest(trial_id)
        if config is None:
            self._max_trials = len(self.trials)
            return None
        import os

        trial = Trial(trial_id, config, os.path.join(self.experiment_dir, f"trial_{trial_id}"))
        self.trials.append(trial)
        for t in self.trials:
            t.experiment_trials = self.trials
        self.scheduler.on_trial_add(trial)
        return trial

    def _start_trial(self, trial: Trial, config: dict | None = None, checkpoint: str | None = None) -> None:
        if config is not None:
            trial.config = config
        trial.actor = self._remote_actor_cls.remote(
            self.trainable,
            trial.config,
            trial.trial_id,
            trial.trial_dir,
            checkpoint if checkpoint is not None else trial.checkpoint_path,
        )
        trial.status = RUNNING
        self._schedule_step(trial)

    def _schedule_step(self, trial: Trial) -> None:
        ref = trial.actor.step.remote()
        self._futures[ref] = trial

    def _stop_actor(self, trial: Trial, save: bool = False) -> None:
        if trial.actor is None:
            return
        try:
            if save:
                trial.checkpoint_path = ray_tpu.get(trial.actor.save.remote(), timeout=30)
            else:
                ray_tpu.get(trial.actor.stop.remote(), timeout=10)
        except RayTpuError:
            pass
        try:
            ray_tpu.kill(trial.actor)
        except RayTpuError:
            pass
        trial.actor = None

    # ------------------------------------------------------------------

    def _live(self) -> int:
        return sum(1 for t in self.trials if t.status == RUNNING)

    def _maybe_fill(self) -> None:
        # Scheduler-demanded terminations first (HyperBand rung losers are
        # PAUSED when the cut happens; the scheduler reaps them here).
        pending_stops = getattr(self.scheduler, "pending_stops", None)
        if pending_stops:
            for t in pending_stops(self.trials):
                if t.status == PAUSED:
                    self._complete(t, t.last_result)
        # Scheduler-gated resumes next (synch PBT exploit cycle).
        resume_decisions = getattr(self.scheduler, "resume_decisions", None)
        if resume_decisions:
            for trial, (cfg, ckpt) in resume_decisions(self.trials).items():
                if ckpt:
                    trial.checkpoint_path = ckpt
                self._start_trial(trial, config=cfg)
        may_resume = getattr(self.scheduler, "may_resume", lambda t: True)
        cap = self.tune_config.max_concurrent_trials or 2**31
        while self._live() < cap:
            paused = next(
                (t for t in self.trials if t.status == PAUSED and may_resume(t)), None
            )
            if paused is not None:
                self._start_trial(paused)
                continue
            trial = self._next_trial()
            if trial is None:
                break
            self._start_trial(trial)

    def _complete(self, trial: Trial, result: dict | None, error: Exception | None = None) -> None:
        trial.status = ERROR if error else TERMINATED
        trial.error = error
        self.scheduler.on_trial_complete(trial, result)
        self.searcher.on_trial_complete(trial.trial_id, result, error=error is not None)
        self._stop_actor(trial, save=False)

    def _handle_result(self, trial: Trial, result: dict) -> None:
        trial.last_result = result
        trial.metrics_history.append(result)
        self.searcher.on_trial_result(trial.trial_id, result)
        if result.get(DONE) or self._stop_criterion(result):
            self._complete(trial, result)
            return
        decision = self.scheduler.on_trial_result(trial, result)
        if isinstance(decision, ExploitDecision):
            self._exploit(trial, decision)
        elif decision == TrialScheduler.STOP:
            self._complete(trial, result)
        elif decision == TrialScheduler.PAUSE:
            self._stop_actor(trial, save=True)
            trial.status = PAUSED
        else:
            freq = self.checkpoint_frequency
            if freq and result.get(TRAINING_ITERATION, 0) % freq == 0:
                try:
                    path = ray_tpu.get(trial.actor.save.remote(), timeout=60)
                    if path:
                        trial.checkpoint_path = path
                except RayTpuError:
                    pass
            self._schedule_step(trial)

    def _exploit(self, trial: Trial, decision: ExploitDecision) -> None:
        """PBT: clone source's checkpoint into `trial` with a mutated config
        (reference: pbt.py _exploit → executor restore)."""
        source = decision.source
        if source.actor is None:
            ckpt = source.checkpoint_path
        else:
            try:
                ckpt = ray_tpu.get(source.actor.save.remote(), timeout=60)
                source.checkpoint_path = ckpt
            except RayTpuError:
                ckpt = source.checkpoint_path
        if ckpt is None:  # nothing to exploit yet
            self._schedule_step(trial)
            return
        self._stop_actor(trial, save=False)
        trial.checkpoint_path = ckpt
        self._start_trial(trial, config=decision.new_config, checkpoint=ckpt)

    def _stop_criterion(self, result: dict) -> bool:
        stop = getattr(self.run_config, "stop", None)
        if stop is None:
            return False
        if callable(stop):
            return bool(stop(result))
        return any(k in result and result[k] >= v for k, v in stop.items())

    def _handle_error(self, trial: Trial, err: Exception) -> None:
        trial.num_failures += 1
        self._stop_actor(trial, save=False)
        retry = (
            self.max_failures_per_trial < 0
            or trial.num_failures <= self.max_failures_per_trial
        )
        if retry:
            self._start_trial(trial)  # restores from trial.checkpoint_path
        else:
            self._complete(trial, None, error=err)

    # ------------------------------------------------------------------

    def run(self) -> list[Trial]:
        while True:
            # Fill at loop top: after the last running trial pauses (synch
            # PBT boundary) there are no futures, but resume_decisions will
            # mint new ones.
            self._maybe_fill()
            if not self._futures:
                break
            if self._deadline is not None and time.monotonic() > self._deadline:
                for t in list(self.trials):
                    if t.status in (RUNNING, PAUSED, PENDING):
                        self._stop_actor(t, save=False)
                        t.status = TERMINATED
                break
            ready, _ = ray_tpu.wait(list(self._futures), num_returns=1, timeout=1.0)
            for ref in ready:
                trial = self._futures.pop(ref)
                if trial.status != RUNNING:
                    continue
                try:
                    result = ray_tpu.get(ref)
                except RayTpuError as e:
                    self._handle_error(trial, e)
                    continue
                result.setdefault(TRAINING_ITERATION, len(trial.metrics_history) + 1)
                result["trial_id"] = trial.trial_id
                result["config"] = trial.config
                self._handle_result(trial, result)
        for t in self.trials:
            self._stop_actor(t, save=False)
        return self.trials


class Tuner:
    """Reference: tune/tuner.py Tuner. `Tuner(trainable).fit() -> ResultGrid`.

    `trainable` may be a function `(config) -> None` using `tune.report`,
    a `Trainable` subclass, or a `JaxTrainer` (its train_loop_config is
    merged with `param_space["train_loop_config"]`)."""

    def __init__(
        self,
        trainable: Any,
        *,
        param_space: dict | None = None,
        tune_config: TuneConfig | None = None,
        run_config: RunConfig | None = None,
    ):
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name=f"tune_{uuid.uuid4().hex[:6]}")
        from ray_tpu.train.trainer import JaxTrainer

        if isinstance(trainable, JaxTrainer):
            trainable = _trainer_to_trainable(trainable)
        self.trainable = trainable

    def fit(self) -> ResultGrid:
        ray_tpu.api.auto_init()
        controller = TuneController(
            self.trainable, self.param_space, self.tune_config, self.run_config
        )
        trials = controller.run()
        results = [
            Result(
                metrics=t.last_result,
                checkpoint=Checkpoint(t.checkpoint_path) if t.checkpoint_path else None,
                path=t.trial_dir,
                metrics_history=t.metrics_history,
                error=t.error,
                config=t.config,
            )
            for t in trials
        ]
        return ResultGrid(results, trials, self.tune_config.metric, self.tune_config.mode)


def _trainer_to_trainable(trainer) -> Callable:
    """Wrap a JaxTrainer so each trial runs trainer.fit() with the sampled
    `train_loop_config` merged in (reference: BaseTrainer.as_trainable,
    train/base_trainer.py:651ff)."""
    import copy

    def _fn(config: dict) -> None:
        from ray_tpu.tune import report

        t = copy.copy(trainer)
        merged = dict(t.train_loop_config or {})
        merged.update(config.get("train_loop_config", {k: v for k, v in config.items()}))
        t.train_loop_config = merged
        run_cfg = copy.copy(t.run_config)
        from ray_tpu.tune.trainable import get_trial_dir

        run_cfg.storage_path = get_trial_dir()
        run_cfg.name = "train"
        t.run_config = run_cfg
        result = t.fit()
        metrics = dict(result.metrics)
        report(metrics, checkpoint=result.checkpoint)

    return _fn


def run(
    trainable: Any,
    *,
    config: dict | None = None,
    num_samples: int = 1,
    metric: str | None = None,
    mode: str = "max",
    scheduler: TrialScheduler | None = None,
    search_alg: Searcher | None = None,
    stop: Any = None,
    storage_path: str | None = None,
    name: str | None = None,
    max_concurrent_trials: int | None = None,
    time_budget_s: float | None = None,
) -> ResultGrid:
    """Legacy-style entry (reference: tune/tune.py:267 tune.run)."""
    run_config = RunConfig(name=name or f"tune_{uuid.uuid4().hex[:6]}", storage_path=storage_path)
    run_config.stop = stop  # type: ignore[attr-defined]
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            scheduler=scheduler,
            search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
            time_budget_s=time_budget_s,
        ),
        run_config=run_config,
    )
    return tuner.fit()
