"""ray_tpu.util: collective groups, placement groups, pools, queues,
metrics, and the state/introspection API (reference: ray.util)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.serialization import deregister_serializer, register_serializer

def list_named_actors(all_namespaces: bool = False) -> list:
    """Names of live named actors (reference: util/__init__.py:29).

    With ``all_namespaces``, returns [{"namespace": ..., "name": ...}]
    dicts; otherwise the names in the CURRENT namespace."""
    from ray_tpu import api
    from ray_tpu._private.worker_context import global_runtime

    api.auto_init()
    return global_runtime().conn.call(
        "list_named_actors",
        {"all_namespaces": all_namespaces,
         "namespace": api._namespace},
    )["actors"]


__all__ = [
    "ActorPool",
    "Empty",
    "Full",
    "Queue",
    "deregister_serializer",
    "register_serializer",
    "list_named_actors",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
]
