"""ray_tpu.util: collective groups, placement groups, pools, queues,
metrics, and the state/introspection API (reference: ray.util)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.serialization import deregister_serializer, register_serializer

__all__ = [
    "ActorPool",
    "Empty",
    "Full",
    "Queue",
    "deregister_serializer",
    "register_serializer",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
]
