"""ActorPool: load-balance tasks over a fixed set of actors.

Counterpart of the reference's ray.util.ActorPool (util/actor_pool.py:13):
submit/get_next/get_next_unordered plus map/map_unordered convenience."""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        if not actors:
            raise ValueError("ActorPool requires at least one actor")
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list[tuple[Callable, Any]] = []

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef. Queues if all actors are busy."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    # -- retrieval ---------------------------------------------------------

    def _return_actor(self, ref) -> None:
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order. On timeout, pool state is left
        untouched (the task is still running; retry get_next later)."""
        from ray_tpu.exceptions import GetTimeoutError

        if not self.has_next():
            raise StopIteration("no pending results")
        ref = self._index_to_future[self._next_return_index]
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except GetTimeoutError:
            raise TimeoutError("get_next timed out; task still running") from None
        except Exception:
            # Task FAILED (completed with error): the actor is free again.
            del self._index_to_future[self._next_return_index]
            self._next_return_index += 1
            self._return_actor(ref)
            raise
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(ref)
        return value

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Whichever pending result finishes first."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._index_to_future.values()),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        for idx, fut in list(self._index_to_future.items()):
            if fut is ref or fut.hex() == ref.hex():
                del self._index_to_future[idx]
                break
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(ref)

    # -- bulk --------------------------------------------------------------

    def map(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
