"""Serializability inspector.

Counterpart of the reference's ray.util.check_serialize
(reference: python/ray/util/check_serialize.py —
inspect_serializability recursively probes an object and prints a tree of
the members that fail to pickle, so users can find the lambda/lock/socket
buried in their task closure). Same approach: try the runtime's
serializer, and on failure descend into closures, attributes, and
containers to locate the leaf offenders.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Any, Optional, Set, Tuple

from ray_tpu._private import serialization


@dataclass(eq=False)  # identity hash: leaves may be unhashable values
class FailureTuple:
    """One unserializable leaf: the object, its name, and its parent."""

    obj: Any
    name: str
    parent: Any

    def __repr__(self) -> str:
        return f"FailTuple({self.name} [obj={self.obj!r}, parent={self.parent!r}])"


def _serializable(obj: Any) -> bool:
    try:
        serialization.dumps(obj)
        return True
    except Exception:
        return False


def _inspect(obj: Any, name: str, parent: Any, failures: list[FailureTuple],
             seen: Set[int], depth: int) -> bool:
    """Returns True when serializable; appends leaf failures otherwise."""
    if _serializable(obj):
        return True
    if id(obj) in seen or depth > 10:
        return False
    seen.add(id(obj))

    children: list[Tuple[str, Any]] = []
    if inspect.isfunction(obj):
        # Closure cells and globals referenced by the function. This may
        # itself raise on broken closures (empty cells) — exactly the
        # objects under diagnosis, so degrade to a leaf report.
        try:
            closure = inspect.getclosurevars(obj)
            children += list(closure.nonlocals.items())
            children += list(closure.globals.items())
        except Exception:
            pass
    elif isinstance(obj, dict):
        children += [(str(k), v) for k, v in obj.items()]
    elif isinstance(obj, (list, tuple, set)):
        children += [(f"[{i}]", v) for i, v in enumerate(obj)]
    elif hasattr(obj, "__dict__"):
        children += list(vars(obj).items())

    found_deeper = False
    for child_name, child in children:
        if not _serializable(child):
            found_deeper = True
            _inspect(child, f"{name}.{child_name}", obj, failures, seen,
                     depth + 1)
    if not found_deeper:
        failures.append(FailureTuple(obj=obj, name=name, parent=parent))
    return False


def inspect_serializability(
    base_obj: Any, name: Optional[str] = None
) -> Tuple[bool, Set[FailureTuple]]:
    """Check whether ``base_obj`` is serializable by the runtime; returns
    (ok, failures) where each failure names a leaf object that cannot be
    pickled (reference: check_serialize.py inspect_serializability)."""
    name = name or getattr(base_obj, "__name__", repr(base_obj)[:40])
    failures: list[FailureTuple] = []
    ok = _inspect(base_obj, name, None, failures, set(), 0)
    if not ok and not failures:
        failures.append(FailureTuple(obj=base_obj, name=name, parent=None))
    return ok, set(failures)
