"""Collective communication library.

Counterpart of the reference's ``ray.util.collective``
(reference: python/ray/util/collective/collective.py:123 init_collective_group,
:160 create_collective_group, :268-625 allreduce/allgather/reducescatter/
broadcast/send/recv/barrier; NCCL backend
collective_group/nccl_collective_group.py, gloo backend
gloo_collective_group.py).

TPU-native design: there are two planes, and this module is ONLY the slow
one —

  1. **In-jit collectives (the data plane).** Gradient/activation collectives
     compile into the XLA program (``jax.lax.psum``/``all_gather``/
     ``ppermute`` under ``shard_map``) and ride ICI. See
     ray_tpu.parallel.ops. Never route tensors through this module in a
     training step.
  2. **Host-level collectives (this module, the control plane).** CPU-side
     rendezvous between actors/tasks: weight broadcast at init, metric
     reduction, barriers. Backed by the head's KV store for rendezvous and
     the shm object store for payloads — the role gloo plays in the
     reference.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import numpy as np

from ray_tpu._private.worker_context import global_runtime

_DEFAULT_GROUP = "default"
_groups: dict[str, "CollectiveGroup"] = {}


class CollectiveGroup:
    """One named world of `world_size` ranks; this process is `rank`."""

    def __init__(self, world_size: int, rank: int, group_name: str = _DEFAULT_GROUP):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.name = group_name
        self._seq: dict[str, int] = {}  # per-op-type sequence counters
        self._rt = global_runtime()
        # A re-created group (same name, new world) must not consume keys a
        # previous incarnation left behind: purge everything this rank ever
        # posted under this group name.
        suffix = f":{rank}"
        for key in self._rt.kv_keys(prefix=f"collective:{group_name}:", ns="__collective__"):
            if key.endswith(suffix):
                self._rt.kv_del(key, ns="__collective__")

    # --- rendezvous keys ---

    def _key(self, op: str, seq: int, rank: int) -> str:
        return f"collective:{self.name}:{op}:{seq}:{rank}"

    def _next_seq(self, op: str) -> int:
        seq = self._seq.get(op, 0)
        self._seq[op] = seq + 1
        return seq

    def _post(self, op: str, seq: int, value: Any, gc: bool = True) -> None:
        ref = self._rt.put(value)
        # Keep the payload alive until every rank consumed it: the KV holds
        # the ref hex; each consumer reads through a borrowed ref while this
        # owner's ref is pinned in _live until trimmed.
        self._live = getattr(self, "_live", [])
        self._live.append(ref)
        limit = max(4 * self.world_size, 128)
        if len(self._live) > limit:
            self._live = self._live[-limit // 2 :]
        self._rt.kv_put(self._key(op, seq, self.rank), ref.hex().encode(), ns="__collective__")
        # Lazy GC for ALL-BLOCKING ops only: by the time this rank reaches
        # seq, every rank consumed seq-2 of the same op, so our old key is
        # dead. Never applied to p2p (a receiver may lag arbitrarily; its
        # fetch deletes the key instead).
        if gc and seq >= 2:
            self._rt.kv_del(self._key(op, seq - 2, self.rank), ns="__collective__")

    def _fetch(self, op: str, seq: int, rank: int, timeout: float) -> Any:
        from ray_tpu._private.ids import ObjectRef

        deadline = time.monotonic() + timeout
        key = self._key(op, seq, rank)
        while time.monotonic() < deadline:
            raw = self._rt.kv_get(key, ns="__collective__")
            if raw is not None:
                return self._rt.get(ObjectRef(raw.decode()), timeout=timeout)
            time.sleep(0.002)
        raise TimeoutError(f"collective {op} seq={seq}: rank {rank} missing after {timeout}s")

    # --- ops (API shape mirrors reference collective.py:268-625) ---

    def allreduce(self, tensor: np.ndarray, op: str = "sum", timeout: float = 60.0) -> np.ndarray:
        seq = self._next_seq("allreduce")
        self._post("allreduce", seq, np.asarray(tensor))
        parts = [self._fetch("allreduce", seq, r, timeout) for r in range(self.world_size)]
        out = np.stack(parts)
        if op == "sum":
            return out.sum(axis=0)
        if op == "mean":
            return out.mean(axis=0)
        if op == "max":
            return out.max(axis=0)
        if op == "min":
            return out.min(axis=0)
        raise ValueError(f"unknown reduce op {op!r}")

    def allgather(self, tensor: np.ndarray, timeout: float = 60.0) -> list[np.ndarray]:
        seq = self._next_seq("allgather")
        self._post("allgather", seq, np.asarray(tensor))
        return [self._fetch("allgather", seq, r, timeout) for r in range(self.world_size)]

    def reducescatter(self, tensor: np.ndarray, op: str = "sum", timeout: float = 60.0) -> np.ndarray:
        """Each rank gets its 1/world_size shard of the reduction (axis 0)."""
        total = self.allreduce(tensor, op=op, timeout=timeout)
        shards = np.array_split(total, self.world_size, axis=0)
        return shards[self.rank]

    def broadcast(self, tensor: np.ndarray | None, src: int = 0, timeout: float = 60.0) -> np.ndarray:
        return np.asarray(self.broadcast_object(
            None if tensor is None else np.asarray(tensor), src, timeout))

    def broadcast_object(self, obj: Any, src: int = 0, timeout: float = 60.0) -> Any:
        """Broadcast any picklable object. All-blocking: every rank acks
        and the source waits for the acks (NCCL-style synchronous
        collective). This is load-bearing for GC, not just semantics —
        _post's lazy seq-2 deletion is only sound when no rank can run
        two sequences ahead of the slowest; a fire-and-forget source
        posting K broadcasts would delete keys a slow joiner (e.g. a
        worker still importing jax) has not read yet, deadlocking it."""
        seq = self._next_seq("broadcast")
        if self.rank == src:
            self._post("broadcast", seq, obj)
            out = obj
        else:
            out = self._fetch("broadcast", seq, src, timeout)
        self._post("broadcast_ack", seq, 0)
        for r in range(self.world_size):
            self._fetch("broadcast_ack", seq, r, timeout)
        return out

    def barrier(self, timeout: float = 60.0) -> None:
        self.allgather(np.zeros(1), timeout=timeout)

    def _p2p_seq(self, src: int, dst: int) -> int:
        # P2P sequencing is per (src, dst) pair — uninvolved ranks don't
        # advance it, so send/recv interleave freely with collectives.
        self._p2p = getattr(self, "_p2p", {})
        seq = self._p2p.get((src, dst), 0)
        self._p2p[(src, dst)] = seq + 1
        return seq

    def send(self, tensor: np.ndarray, dst_rank: int, timeout: float = 60.0) -> None:
        seq = self._p2p_seq(self.rank, dst_rank)
        self._post(f"p2p:{self.rank}->{dst_rank}", seq, np.asarray(tensor), gc=False)

    def recv(self, src_rank: int, timeout: float = 60.0) -> np.ndarray:
        seq = self._p2p_seq(src_rank, self.rank)
        op = f"p2p:{src_rank}->{self.rank}"
        value = self._fetch(op, seq, src_rank, timeout)
        # Receiver-side GC: the message is consumed exactly once.
        self._rt.kv_del(self._key(op, seq, src_rank), ns="__collective__")
        return value


# --- module-level API (reference collective.py shape) ---


def init_collective_group(
    world_size: int, rank: int, backend: str = "kv", group_name: str = _DEFAULT_GROUP
) -> CollectiveGroup:
    """Call once per participant process (reference :123)."""
    group = CollectiveGroup(world_size, rank, group_name)
    _groups[group_name] = group
    return group


def get_group(group_name: str = _DEFAULT_GROUP) -> CollectiveGroup:
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} not initialized in this process")
    return _groups[group_name]


def destroy_collective_group(group_name: str = _DEFAULT_GROUP) -> None:
    _groups.pop(group_name, None)


def allreduce(tensor, group_name: str = _DEFAULT_GROUP, op: str = "sum"):
    return get_group(group_name).allreduce(tensor, op=op)


def allgather(tensor, group_name: str = _DEFAULT_GROUP):
    return get_group(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = _DEFAULT_GROUP, op: str = "sum"):
    return get_group(group_name).reducescatter(tensor, op=op)


def broadcast(tensor, src_rank: int = 0, group_name: str = _DEFAULT_GROUP):
    return get_group(group_name).broadcast(tensor, src=src_rank)


def barrier(group_name: str = _DEFAULT_GROUP):
    get_group(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = _DEFAULT_GROUP):
    get_group(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = _DEFAULT_GROUP):
    return get_group(group_name).recv(src_rank)
