"""Dask-graph scheduler over ray_tpu tasks.

Counterpart of the reference's ray.util.dask
(reference: python/ray/util/dask/scheduler.py — ray_dask_get walks a dask
task graph and submits each task as a Ray task, wiring dependencies as
ObjectRefs). The dask graph protocol is plain data (dict of
key -> task tuple), so this scheduler works standalone; with the dask
package installed it plugs straight into ``dask.compute(...,
scheduler=ray_dask_get)``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Hashable, Mapping

import ray_tpu


def _is_task(v: Any) -> bool:
    return isinstance(v, tuple) and len(v) > 0 and callable(v[0])


@dataclasses.dataclass
class _Nested:
    """A nested task shipped INSIDE its parent ray task: evaluated on the
    worker at materialization (dask semantics — nested tuples are not
    separate graph nodes), never inline on the driver."""

    fn: Any
    args: list


def _in_table(expr: Any, table: Mapping) -> bool:
    """True iff expr is a key of table. Tuples pass isinstance(x, Hashable)
    even when their elements don't, so membership itself can raise."""
    if not isinstance(expr, Hashable):
        return False
    try:
        return expr in table
    except TypeError:
        return False


def _resolve(expr: Any, refs: dict):
    """Rewrite graph keys to ObjectRefs and nested tasks to _Nested.

    Key lookup happens BEFORE any tuple handling other than the task
    check: dask collections use tuple keys like ("chunk-...", 0), and
    dask.core treats a non-task tuple that is a graph key as a key, not
    as a structure to descend.  Only lists are descended (dask.core
    semantics) — a non-task, non-key tuple is a literal.
    """
    if _is_task(expr):
        fn, *args = expr
        return _Nested(fn, [_resolve(a, refs) for a in args])
    if _in_table(expr, refs):
        return refs[expr]
    if isinstance(expr, list):
        return [_resolve(e, refs) for e in expr]
    return expr


def _materialize(v: Any):
    from ray_tpu._private.ids import ObjectRef

    if isinstance(v, ObjectRef):
        return ray_tpu.get(v)
    if isinstance(v, _Nested):
        return v.fn(*[_materialize(a) for a in v.args])
    if isinstance(v, (list, tuple)):
        return type(v)(_materialize(x) for x in v)
    return v


def _run_task(fn, *args):
    return fn(*[_materialize(a) for a in args])


def ray_dask_get(dsk: Mapping, keys, **kwargs):
    """Execute a dask graph; each graph task becomes one ray_tpu task with
    ObjectRef-wired dependencies (reference: scheduler.py ray_dask_get).

        dsk = {"x": 1, "y": (add, "x", 2), "z": (mul, "y", "y")}
        ray_dask_get(dsk, ["z"])  ->  [9]
    """
    remote_run = ray_tpu.remote(_run_task)
    # Standard Kahn: dependency sets computed once, ready-queue driven —
    # O(V + E) submission.
    deps = {k: _graph_deps(v, dsk) for k, v in dsk.items()}
    dependents: dict = {k: set() for k in dsk}
    for k, ds in deps.items():
        for d in ds:
            dependents[d].add(k)
    ready = [k for k, ds in deps.items() if not ds]
    refs: dict = {}
    submitted = 0
    while ready:
        key = ready.pop()
        expr = dsk[key]
        if _is_task(expr):
            fn, *args = expr
            refs[key] = remote_run.remote(fn, *[_resolve(a, refs) for a in args])
        else:
            refs[key] = _resolve(expr, refs)
        submitted += 1
        for child in dependents[key]:
            deps[child].discard(key)
            if not deps[child]:
                ready.append(child)
    if submitted != len(dsk):
        unsubmitted = sorted(k for k in dsk if k not in refs)
        raise ValueError(
            f"dask graph has a cycle or missing keys: {unsubmitted}"
        )

    def fetch(k):
        if isinstance(k, list):
            return [fetch(x) for x in k]
        if k not in refs:
            raise KeyError(f"requested key {k!r} is not in the graph")
        return _materialize(refs[k])

    return [fetch(k) for k in keys]


def _graph_deps(expr: Any, dsk: Mapping) -> set:
    """Same traversal order as _resolve: task → key (tuples included) →
    list descent.  Checking the tuple itself against dsk before
    descending is what keeps dask-collection tuple keys intact."""
    out: set = set()
    if _is_task(expr):
        for a in expr[1:]:
            out |= _graph_deps(a, dsk)
    elif _in_table(expr, dsk):
        out.add(expr)
    elif isinstance(expr, list):
        for a in expr:
            out |= _graph_deps(a, dsk)
    return out


def enable_dask_on_ray() -> None:
    """Install ray_dask_get as dask's default scheduler (reference:
    util/dask/__init__.py enable_dask_on_ray). Requires dask."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires the 'dask' package, which is not "
            "installed in this environment; ray_dask_get still executes "
            "plain dask-protocol graphs without it"
        ) from e
    dask.config.set(scheduler=ray_dask_get)
