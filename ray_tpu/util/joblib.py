"""joblib backend on cluster tasks (sklearn parallelism on the cluster).

Counterpart of the reference's ray.util.joblib
(python/ray/util/joblib/__init__.py register_ray + ray_backend.py —
a joblib ParallelBackendBase whose effective_n_jobs is the cluster CPU
count and whose apply_async ships batches as tasks).

    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel(n_jobs=-1)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations


def register_ray_tpu() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _make_backend())


def _make_backend():
    from joblib._parallel_backends import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            ray_tpu.api.auto_init()
            cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
            if n_jobs is None:
                return cpus
            if n_jobs < 0:
                # joblib convention: -1 = all CPUs, -2 = all but one, ...
                return max(1, cpus + 1 + n_jobs)
            return min(n_jobs, cpus)

        def apply_async(self, func, callback=None):
            import ray_tpu

            @ray_tpu.remote
            def run():
                return func()

            ref = run.remote()
            fut = _Future(ref)
            if callback is not None:
                import threading

                def waiter():
                    try:
                        callback(fut.get())
                    except Exception:
                        pass

                threading.Thread(target=waiter, daemon=True).start()
            return fut

        def configure(self, n_jobs=1, parallel=None, **kwargs):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

    return RayTpuBackend


class _Future:
    def __init__(self, ref):
        self._ref = ref
        self._result = None
        self._done = False

    def get(self, timeout=None):
        import ray_tpu

        if not self._done:
            self._result = ray_tpu.get(self._ref, timeout=timeout)
            self._done = True
        return self._result
