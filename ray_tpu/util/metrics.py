"""User-defined metrics: Counter / Gauge / Histogram.

Counterpart of the reference's ray.util.metrics (util/metrics.py —
Counter :163, Gauge :216, Histogram :294, exported via the per-node
Prometheus agent). Here metrics are pushed to the head's metric table
keyed by (name, reporter, tags) and aggregated on read; `get_metrics_report`
/ `prometheus_text` are the scrape surface."""

from __future__ import annotations

import bisect
import os
import threading
import time
import uuid
from typing import Any, Optional, Sequence

from ray_tpu._private.worker_context import global_runtime

_FLUSH_INTERVAL_S = 1.0


class _MetricBase:
    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] | None = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict[str, str] = {}
        self._values: dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self._last_flush = 0.0
        # Per-instance series id: a re-created metric object (reused
        # worker, new task) contributes a NEW series instead of
        # overwriting the previous instance's accumulated value.
        self._instance_id = uuid.uuid4().hex[:8]

    def set_default_tags(self, tags: dict[str, str]) -> "_MetricBase":
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[dict]) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"undeclared tag keys {sorted(extra)} for {self.name}")
        return tuple((k, merged.get(k, "")) for k in self.tag_keys)

    def _flush(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_flush < _FLUSH_INTERVAL_S:
            return
        self._last_flush = now
        try:
            rt = global_runtime()
        except Exception:
            return  # not connected; metrics are best-effort
        with self._lock:
            payload = {}
            for tags, value in self._values.items():
                if isinstance(value, dict):
                    # Snapshot mutable state (histograms) under the lock —
                    # conn.cast serializes after release and must not race
                    # concurrent observe() mutations.
                    value = {
                        k: (list(v) if isinstance(v, list) else v)
                        for k, v in value.items()
                    }
                key = f"{self.name}|{rt.client_id}|{self._instance_id}|{dict(tags)}"
                payload[key] = {
                    "name": self.name,
                    "type": self.TYPE,
                    "description": self.description,
                    "tags": dict(tags),
                    "value": value,
                    "reporter": f"{rt.client_id}/{self._instance_id}",
                    "ts": time.time(),
                }
        try:
            rt.conn.cast("report_metrics", {"metrics": payload})
        except Exception:
            pass


class Counter(_MetricBase):
    """Monotonic counter (reference: util/metrics.py:163)."""

    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        self._flush()


class Gauge(_MetricBase):
    """Point-in-time value (reference: util/metrics.py:216)."""

    TYPE = "gauge"

    def set(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._tag_tuple(tags)] = float(value)
        self._flush()


class Histogram(_MetricBase):
    """Bucketed observations (reference: util/metrics.py:294)."""

    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] | None = None,
                 tag_keys: Sequence[str] | None = None):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            boundaries = [0.001, 0.01, 0.1, 1.0, 10.0, 100.0]
        self.boundaries = sorted(float(b) for b in boundaries)

    def observe(self, value: float, tags: dict | None = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                state = {
                    "buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0,
                    "count": 0,
                    "boundaries": self.boundaries,
                }
                self._values[key] = state
            idx = bisect.bisect_left(self.boundaries, value)
            state["buckets"][idx] += 1
            state["sum"] += value
            state["count"] += 1
        self._flush()


def flush_all_of(*metrics: _MetricBase) -> None:
    for m in metrics:
        m._flush(force=True)


def get_metrics_report() -> dict[str, dict]:
    """All reported metric points, aggregated across reporters: counters
    and histograms sum; gauges keep the latest per reporter-tagset."""
    raw = global_runtime().conn.call("get_metrics", {})["metrics"]
    agg: dict[str, dict] = {}
    for point in raw.values():
        name = point["name"]
        tags = tuple(sorted(point["tags"].items()))
        entry = agg.setdefault(name, {"type": point["type"], "series": {}})
        series = entry["series"]
        if point["type"] == "counter":
            series[tags] = series.get(tags, 0.0) + point["value"]
        elif point["type"] == "histogram":
            cur = series.get(tags)
            if cur is None:
                series[tags] = {k: (list(v) if isinstance(v, list) else v)
                                for k, v in point["value"].items()}
            else:
                cur["sum"] += point["value"]["sum"]
                cur["count"] += point["value"]["count"]
                cur["buckets"] = [a + b for a, b in zip(cur["buckets"], point["value"]["buckets"])]
        else:  # gauge: one series per (reporter, tags); latest write wins
            series[(("__reporter__", point["reporter"]),) + tags] = point["value"]
    return agg


def _timestamp_suffix(now_ms: "int | None" = None) -> str:
    """Optional millisecond sample timestamps on gauge lines
    (exposition-format spec: ``name{labels} value [timestamp_ms]``),
    OFF by default — turned on via RAY_TPU_METRICS_TIMESTAMPS so
    scrape-time vs sample-time skew becomes visible. Counters stay
    bare: their value IS cumulative, the scrape time is the honest
    sample time."""
    if os.environ.get("RAY_TPU_METRICS_TIMESTAMPS", "0").lower() \
            not in ("1", "true", "yes", "on"):
        return ""
    return f" {now_ms if now_ms is not None else int(time.time() * 1000)}"


def _escape_label_value(value) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote, and newline must be escaped or the sample line is invalid
    (and silently corrupts every later line of the scrape)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def runtime_stats_text() -> str:
    """Core runtime metric exposition (reference: the C++ DEFINE_stats
    set — tasks/actors/objects — exported through the metrics agent),
    plus the flight-recorder phase-latency histograms (queue wait /
    dispatch / exec / result transfer)."""
    try:
        snap = global_runtime().conn.call("runtime_stats", {}, timeout=10)
    except Exception:
        return ""
    lines = []
    ts_suffix = _timestamp_suffix()
    for name, value in snap.get("counters", {}).items():
        full = f"ray_tpu_{name}_total"
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {value}")
    for name, value in snap.get("gauges", {}).items():
        full = f"ray_tpu_{name}"
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {value}{ts_suffix}")
    for name, h in snap.get("histograms", {}).items():
        full = f"ray_tpu_phase_{name}_seconds"
        lines.append(f"# TYPE {full} histogram")
        for b, c in zip(list(h["boundaries"]) + [float("inf")],
                        _cumulative(h["buckets"])):
            lines.append(f'{full}_bucket{{le="{b}"}} {c}')
        lines.append(f"{full}_sum {h['sum']}")
        lines.append(f"{full}_count {h['count']}")
    # Crash-forensics plane: classified worker deaths by reason
    # (reference analogue: the worker-death metrics keyed by
    # WorkerExitType in the GCS).
    deaths = snap.get("worker_deaths") or {}
    if deaths:
        lines.append("# TYPE ray_tpu_worker_deaths_total counter")
        for reason in sorted(deaths):
            lines.append(
                f'ray_tpu_worker_deaths_total'
                f'{{reason="{_escape_label_value(reason)}"}} '
                f"{deaths[reason]}")
    # Overload-protection plane: deadline sheds by queue hop. The
    # admission counter rides the generic counters block above
    # (ray_tpu_admission_rejected_total) and the pressure gauge the
    # gauges block (ray_tpu_mem_pressured_nodes).
    tracing = snap.get("tracing") or {}
    exemplar_ids = tracing.get("exemplar_ids") or {}
    shed = snap.get("tasks_shed") or {}
    if shed:
        # OpenMetrics-style exemplar suffix: a shed spike comes with a
        # retained trace id to drill into (`ray-tpu trace <id>`).
        ex = (f' # {{trace_id="{exemplar_ids["shed"]}"}} 1'
              if exemplar_ids.get("shed") else "")
        lines.append("# TYPE ray_tpu_tasks_shed_total counter")
        for where in sorted(shed):
            lines.append(
                f'ray_tpu_tasks_shed_total'
                f'{{where="{_escape_label_value(where)}"}} '
                f'{shed[where]}{ex}')
    # Request-tracing plane: retention/fold/drop gauges, plus one info
    # series per exemplar kind so the serve p99 dashboards can link
    # "slow right now" to a concrete retained trace.
    if tracing:
        for key, metric in (("retained", "ray_tpu_traces_retained"),
                            ("exemplars", "ray_tpu_traces_exemplars")):
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {tracing.get(key, 0)}")
        folded = tracing.get("folded") or {}
        lines.append("# TYPE ray_tpu_traces_folded_total counter")
        lines.append(f"ray_tpu_traces_folded_total {folded.get('count', 0)}")
        lines.append("# TYPE ray_tpu_trace_spans_dropped_total counter")
        lines.append(f"ray_tpu_trace_spans_dropped_total "
                     f"{tracing.get('spans_dropped_owner_side', 0)}")
        if exemplar_ids:
            lines.append("# TYPE ray_tpu_trace_exemplar_info gauge")
            for kind in sorted(exemplar_ids):
                lines.append(
                    f'ray_tpu_trace_exemplar_info'
                    f'{{kind="{_escape_label_value(kind)}",'
                    f'trace_id="{_escape_label_value(exemplar_ids[kind])}"'
                    f'}} 1')
    # Unified retry plane: open circuit breakers in the head process
    # (per-client breakers ride the rpc clients snapshots).
    breakers = snap.get("breakers") or {}
    if breakers:
        lines.append("# TYPE ray_tpu_rpc_breaker_open gauge")
        for target in sorted(breakers):
            lines.append(
                f'ray_tpu_rpc_breaker_open'
                f'{{target="{_escape_label_value(target)}"}} '
                f"{1 if breakers[target].get('open') else 0}")
    # Object-plane observability: store bytes by node/state, live refs
    # by census kind, top callsites by live bytes, and the leak
    # detector's suspect count.
    objects = snap.get("objects") or {}
    by_node_state = objects.get("by_node_state") or {}
    if by_node_state:
        lines.append("# TYPE ray_tpu_object_store_bytes gauge")
        for node in sorted(by_node_state):
            for state in sorted(by_node_state[node]):
                lines.append(
                    f'ray_tpu_object_store_bytes'
                    f'{{node="{_escape_label_value(node)}",'
                    f'state="{_escape_label_value(state)}"}} '
                    f"{by_node_state[node][state]}")
    live_by_kind = objects.get("live_by_kind") or {}
    if live_by_kind:
        lines.append("# TYPE ray_tpu_objects_live gauge")
        for kind in sorted(live_by_kind):
            lines.append(
                f'ray_tpu_objects_live'
                f'{{kind="{_escape_label_value(kind)}"}} '
                f"{live_by_kind[kind]}")
    top_cs = objects.get("top_callsite_bytes") or {}
    if top_cs:
        lines.append("# TYPE ray_tpu_object_callsite_bytes gauge")
        for site in sorted(top_cs):
            lines.append(
                f'ray_tpu_object_callsite_bytes'
                f'{{callsite="{_escape_label_value(site)}"}} '
                f"{top_cs[site]}")
    if "leak_suspects" in objects:
        lines.append("# TYPE ray_tpu_object_leak_suspects gauge")
        lines.append(
            f"ray_tpu_object_leak_suspects {objects['leak_suspects']}")
    # Zero-copy data plane: payload bytes moved by transfer path
    # (p2p primary pulls, relay pulls, host-local arena reads,
    # zero-copy aliasing views, inline control-plane payloads, spill
    # restores) and the host-side copy census behind the one-copy
    # structural guard.
    transfers = snap.get("transfers") or {}
    xfer_bytes = transfers.get("bytes") or {}
    if xfer_bytes:
        lines.append("# TYPE ray_tpu_object_bytes_transferred_total"
                     " counter")
        for path in sorted(xfer_bytes):
            lines.append(
                f'ray_tpu_object_bytes_transferred_total'
                f'{{path="{_escape_label_value(path)}"}} '
                f"{xfer_bytes[path]}")
    xfer_copies = transfers.get("host_copies") or {}
    if xfer_copies:
        lines.append("# TYPE ray_tpu_object_host_copies_total counter")
        for path in sorted(xfer_copies):
            lines.append(
                f'ray_tpu_object_host_copies_total'
                f'{{path="{_escape_label_value(path)}"}} '
                f"{xfer_copies[path]}")
    # Continuous profiling plane: cluster profile table occupancy and
    # churn, plus per-(role, frame) self-time hits — the top-N leaf
    # frames per role, bounded by the head's top-N fold so the frame
    # label cardinality stays fixed regardless of code shape.
    profiling = snap.get("profiling") or {}
    if profiling:
        lines.append("# TYPE ray_tpu_profile_windows gauge")
        lines.append(f"ray_tpu_profile_windows "
                     f"{profiling.get('windows', 0)}")
        lines.append("# TYPE ray_tpu_profile_pinned_windows gauge")
        lines.append(f"ray_tpu_profile_pinned_windows "
                     f"{profiling.get('pinned', 0)}")
        for key, metric in (
                ("windows_total", "ray_tpu_profile_windows_total"),
                ("dropped_windows",
                 "ray_tpu_profile_windows_dropped_total"),
                ("samples_total", "ray_tpu_profile_samples_total"),
                ("gil_exemplars",
                 "ray_tpu_profile_gil_exemplars_total")):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {profiling.get(key, 0)}")
        self_time = profiling.get("self_time") or {}
        if self_time:
            lines.append("# TYPE ray_tpu_profile_self_hits gauge")
            for role in sorted(self_time):
                for frame in sorted(self_time[role]):
                    lines.append(
                        f'ray_tpu_profile_self_hits'
                        f'{{role="{_escape_label_value(role)}",'
                        f'frame="{_escape_label_value(frame)}"}} '
                        f"{self_time[role][frame]}")
    # Telemetry history + SLO alerting plane self-metrics: store
    # occupancy (series/points), the (other series) fold counter, and
    # the firing-alert gauge by severity — the plane watching the
    # cluster must itself be watchable, or a melted store goes
    # unnoticed until an alert silently fails to fire.
    telemetry = snap.get("telemetry") or {}
    if telemetry:
        lines.append("# TYPE ray_tpu_tsdb_series gauge")
        lines.append(f"ray_tpu_tsdb_series "
                     f"{telemetry.get('series', 0)}{ts_suffix}")
        lines.append("# TYPE ray_tpu_tsdb_points gauge")
        lines.append(f"ray_tpu_tsdb_points "
                     f"{telemetry.get('points', 0)}{ts_suffix}")
        lines.append("# TYPE ray_tpu_tsdb_dropped_total counter")
        lines.append(f"ray_tpu_tsdb_dropped_total "
                     f"{telemetry.get('dropped_total', 0)}")
    alerts = snap.get("alerts") or {}
    if alerts:
        from ray_tpu._private.alertplane import SEVERITIES

        by_sev = alerts.get("firing_by_severity") or {}
        lines.append("# TYPE ray_tpu_alerts_firing gauge")
        for sev in SEVERITIES:
            lines.append(
                f'ray_tpu_alerts_firing'
                f'{{severity="{_escape_label_value(sev)}"}} '
                f"{by_sev.get(sev, 0)}{ts_suffix}")
        for key, metric in (
                ("fired_total", "ray_tpu_alerts_fired_total"),
                ("resolved_total", "ray_tpu_alerts_resolved_total")):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {alerts.get(key, 0)}")
    # Cluster-wide head frame census (the zero-per-call-head-frames
    # property, scrapeable): total frames every reporting process has
    # sent the head.
    rpc = snap.get("rpc") or {}
    if rpc.get("total_head_frames") is not None:
        lines.append("# TYPE ray_tpu_rpc_head_frames_total counter")
        lines.append(
            f"ray_tpu_rpc_head_frames_total {rpc['total_head_frames']}")
    return "\n".join(lines) + ("\n" if lines else "")


def prometheus_text() -> str:
    """Prometheus exposition format (the per-node MetricsAgent surface,
    reference: _private/metrics_agent.py:492). Core runtime metrics
    first, then user-defined Counter/Gauge/Histogram series."""
    lines = [runtime_stats_text().rstrip("\n")]
    lines = [ln for ln in lines if ln]
    ts_suffix = _timestamp_suffix()
    for name, entry in get_metrics_report().items():
        lines.append(f"# TYPE {name} {entry['type']}")
        for tags, value in entry["series"].items():
            # "__reporter__" (gauge per-reporter series) renders as a
            # reporter label so duplicate-named samples stay distinct.
            pairs = [("reporter", v) if k == "__reporter__" else (k, v)
                     for k, v in tags]
            label_body = ",".join(
                f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
            label = "{" + label_body + "}" if label_body else ""
            if entry["type"] == "histogram":
                for b, c in zip(value["boundaries"] + [float("inf")],
                                _cumulative(value["buckets"])):
                    le = f'le="{b}"'
                    bucket_label = "{" + (label_body + "," if label_body else "") + le + "}"
                    lines.append(f"{name}_bucket{bucket_label} {c}")
                lines.append(f"{name}_sum{label} {value['sum']}")
                lines.append(f"{name}_count{label} {value['count']}")
            else:
                suffix = ts_suffix if entry["type"] == "gauge" else ""
                lines.append(f"{name}{label} {value}{suffix}")
    return "\n".join(lines) + "\n"


def _cumulative(buckets: list[int]) -> list[int]:
    out, total = [], 0
    for b in buckets:
        total += b
        out.append(total)
    return out


def rpc_counters() -> dict:
    """Dispatch-plane RPC counters for THIS process (cheap ints kept on
    every rpc.Connection): per-kind message census and frame counts for
    the head connection and every peer (direct-call) connection, plus
    the direct plane's own dispatch counters. The frame-count
    regression guard (tests/test_dispatch_fastpath.py) is built on
    these — steady-state direct dispatch must add ZERO per-call frames
    on the head connection."""
    rt = global_runtime()

    def _conn(c) -> dict:
        sync = getattr(c, "_sync_native_counters", None)
        if sync is not None:
            sync()  # fold native-lane flusher frames before reading
        return {"frames_sent": c.frames_sent, "calls_sent": c.calls_sent,
                "sent_kinds": dict(c.sent_kinds)}

    with rt._owner_conns_lock:
        peers = {f"{a[0]}:{a[1]}": _conn(c)
                 for a, c in rt._owner_conns.items()}
    direct = rt._direct.snapshot() if rt._direct is not None else {}
    return {"head": _conn(rt.conn), "peers": peers, "direct": direct}


def cluster_rpc_counters() -> dict:
    """CLUSTER-wide rpc counters: every runtime's snapshot as last
    reported to the head (workers/drivers piggyback on the amortized
    rpc_report cast, node agents on their heartbeats). The whole-cluster
    complement of rpc_counters() — lets the zero-head-frames property of
    the direct plane be checked for every process, not just this one.
    Shape: {"clients": {client_id: snapshot}, "total_head_frames": int,
    "clock_offsets": {node_id: seconds}}."""
    snap = global_runtime().conn.call("runtime_stats", {}, timeout=10)
    return snap.get("rpc") or {"clients": {}, "total_head_frames": 0}
