"""Prometheus + Grafana integration artifacts.

Counterpart of the reference's dashboard metrics module
(reference: python/ray/dashboard/modules/metrics/ — it writes a
Prometheus scrape config and generates Grafana dashboard JSON for the
cluster's metric set). Here the same two artifacts are generated from
the live registry and the dashboard's exposition endpoint:

    GET /api/prometheus_sd       Prometheus HTTP service-discovery body
    GET /api/grafana_dashboard   importable Grafana dashboard JSON

or from Python::

    from ray_tpu.util.metrics_export import (
        grafana_dashboard, prometheus_scrape_config)
"""

from __future__ import annotations

import json


def prometheus_sd(dashboard_host: str, dashboard_port: int) -> list:
    """HTTP service-discovery payload (prometheus http_sd_configs):
    point prometheus at GET /api/prometheus_sd and it scrapes every
    listed target's /metrics (reference: the dashboard's
    prometheus_service_discovery file)."""
    return [{
        "targets": [f"{dashboard_host}:{dashboard_port}"],
        "labels": {"job": "ray_tpu", "__metrics_path__": "/metrics"},
    }]


def prometheus_scrape_config(dashboard_host: str,
                             dashboard_port: int) -> str:
    """A ready-to-paste prometheus.yml scrape_configs entry."""
    return (
        "scrape_configs:\n"
        "  - job_name: ray_tpu\n"
        "    metrics_path: /metrics\n"
        "    static_configs:\n"
        f"      - targets: ['{dashboard_host}:{dashboard_port}']\n"
    )


def _panel(panel_id: int, title: str, expr, unit: str,
           x: int, y: int) -> dict:
    """``expr`` is one PromQL string or a list of (expr, legend)
    pairs — multi-target panels render each series with its legend
    (p50/p95 pairs share one panel)."""
    if isinstance(expr, str):
        targets = [{"expr": expr, "refId": "A"}]
    else:
        targets = [{"expr": e, "legendFormat": legend,
                    "refId": chr(ord("A") + i)}
                   for i, (e, legend) in enumerate(expr)]
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": targets,
    }


def _quantile_targets(phase: str) -> list:
    hist = f"ray_tpu_phase_{phase}_seconds_bucket"
    return [
        (f"histogram_quantile(0.5, sum(rate({hist}[5m])) by (le))", "p50"),
        (f"histogram_quantile(0.95, sum(rate({hist}[5m])) by (le))", "p95"),
    ]


# Flight-recorder phase-latency histograms exported by the head
# (ray_tpu_phase_*_seconds, util/metrics.runtime_stats_text).
_PHASES = ("queue_wait", "dispatch", "exec", "result_transfer")


def grafana_dashboard(extra_metrics: "list[str] | None" = None) -> dict:
    """Importable Grafana dashboard covering the core runtime metrics
    (reference: dashboard/modules/metrics/dashboards/*_dashboard_panels
    — default panels generated for the cluster metric set), the
    flight-recorder phase-latency histograms (p50/p95 of queue wait /
    dispatch / exec / result transfer), the cluster RPC head-frame
    census, and the crash-forensics deaths-by-reason counters. User
    metrics passed in ``extra_metrics`` get a generic panel each."""
    panels = [
        _panel(1, "Tasks finished / s",
               "rate(ray_tpu_tasks_finished_total[1m])", "ops", 0, 0),
        _panel(2, "Tasks failed / s",
               "rate(ray_tpu_tasks_failed_total[1m])", "ops", 12, 0),
        _panel(3, "Object store used bytes",
               "ray_tpu_object_store_used_bytes", "bytes", 0, 8),
        _panel(4, "Objects in store",
               "ray_tpu_object_store_num_objects", "short", 12, 8),
        _panel(5, "Workers alive",
               "ray_tpu_workers_alive", "short", 0, 16),
        _panel(6, "Actors alive",
               "ray_tpu_actors_alive", "short", 12, 16),
    ]
    next_id = 7
    y = 24
    # Per-phase latency quantiles (PR 3 tracing plane).
    for i, phase in enumerate(_PHASES):
        panels.append(_panel(
            next_id, f"Task {phase.replace('_', ' ')} latency (p50/p95)",
            _quantile_targets(phase), "s",
            (i % 2) * 12, y + (i // 2) * 8))
        next_id += 1
    y += 16
    # Cluster RPC census + crash-forensics deaths.
    panels.append(_panel(
        next_id, "Head control-plane frames / s (cluster total)",
        "rate(ray_tpu_rpc_head_frames_total[1m])", "ops", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Worker deaths by reason / 5m",
        "sum by (reason) (increase(ray_tpu_worker_deaths_total[5m]))",
        "short", 12, y))
    next_id += 1
    y += 8
    # Object-plane observability row (PR 7): live bytes by state,
    # top-callsite attribution, leak-suspect trend.
    panels.append(_panel(
        next_id, "Object store bytes by state",
        "sum by (state) (ray_tpu_object_store_bytes)", "bytes", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Object bytes by top callsites",
        "topk(10, ray_tpu_object_callsite_bytes)", "bytes", 12, y))
    next_id += 1
    y += 8
    panels.append(_panel(
        next_id, "Live object refs by kind",
        "sum by (kind) (ray_tpu_objects_live)", "short", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Object leak suspects",
        "ray_tpu_object_leak_suspects", "short", 12, y))
    next_id += 1
    y += 8
    # Data-plane row (PR 8): payload movement by path (p2p primaries,
    # relay sources, host-local arena reads, zero-copy views, inline
    # control-plane payloads, spill restores) + the host-copy census
    # behind the one-copy guard.
    panels.append(_panel(
        next_id, "Object bytes transferred / s by path",
        "sum by (path) "
        "(rate(ray_tpu_object_bytes_transferred_total[1m]))",
        "Bps", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Host-side payload copies / s by path",
        "sum by (path) (rate(ray_tpu_object_host_copies_total[1m]))",
        "ops", 12, y))
    next_id += 1
    y += 8
    # Serving-plane row (PR 10): ingress QPS + replica count, batch
    # queue depth, continuous-batching batch size, and overload sheds.
    panels.append(_panel(
        next_id, "Serve ingress QPS by deployment",
        "sum by (deployment) (ray_tpu_serve_qps)", "ops", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Serve replicas / ongoing by deployment",
        [("sum by (deployment) (ray_tpu_serve_replicas)", "replicas"),
         ("sum by (deployment) (ray_tpu_serve_ongoing)", "ongoing")],
        "short", 12, y))
    next_id += 1
    y += 8
    panels.append(_panel(
        next_id, "Serve batch queue depth / batch size p50",
        [("sum by (deployment) (ray_tpu_serve_queue_depth)", "queue depth"),
         ("max by (deployment) (ray_tpu_serve_batch_size_p50)",
          "batch size p50")],
        "short", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Serve requests shed / 5m (deadline + queue-full)",
        "sum by (deployment) (increase(ray_tpu_serve_shed_total[5m]))",
        "short", 12, y))
    next_id += 1
    y += 8
    # Request-tracing row: retention/exemplar gauges plus the per-kind
    # exemplar-id info series (the drill-down trace id for a p99/shed
    # spike — `ray-tpu trace <id>` renders the waterfall).
    panels.append(_panel(
        next_id, "Traces retained / exemplars",
        [("ray_tpu_traces_retained", "retained"),
         ("ray_tpu_traces_exemplars", "exemplars")], "short", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Trace folds + span drops / 5m",
        [("increase(ray_tpu_traces_folded_total[5m])", "folded"),
         ("increase(ray_tpu_trace_spans_dropped_total[5m])",
          "spans dropped")],
        "short", 12, y))
    next_id += 1
    y += 8
    # LLM serving row: prefix-cache efficiency + paged-KV pressure per
    # pool (mono / prefill / decode), decode-queue depth, and the
    # disaggregation handoff's byte rate (the data-plane transfer
    # counter's "handoff" path — no dedicated LLM byte gauge exists).
    panels.append(_panel(
        next_id, "LLM prefix-cache hit rate / queue depth by pool",
        [("ray_tpu_llm_prefix_hit_rate", "hit rate"),
         ("sum by (pool) (ray_tpu_llm_queue_depth)", "queue depth")],
        "short", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "LLM KV pages by pool (in use / free)",
        [("sum by (pool) (ray_tpu_llm_kv_pages_in_use)", "in use"),
         ("sum by (pool) (ray_tpu_llm_kv_pages_free)", "free")],
        "short", 12, y))
    next_id += 1
    y += 8
    panels.append(_panel(
        next_id, "LLM prefill→decode handoff bytes / s",
        "sum(rate(ray_tpu_object_bytes_transferred_total"
        "{path=\"handoff\"}[1m]))",
        "Bps", 0, y))
    next_id += 1
    y += 8
    # Continuous-profiling row (PR 18): self-time top-N frames per role
    # (where the cluster's CPU cycles GO, from the always-on sampler)
    # and the plane's window/exemplar churn.
    panels.append(_panel(
        next_id, "Profile self-time top frames (hits, by role)",
        "topk(10, sum by (role, frame) (ray_tpu_profile_self_hits))",
        "short", 0, y))
    next_id += 1
    panels.append(_panel(
        next_id, "Profile windows / GIL exemplars / pins",
        [("ray_tpu_profile_windows", "windows held"),
         ("ray_tpu_profile_pinned_windows", "pinned"),
         ("increase(ray_tpu_profile_gil_exemplars_total[5m])",
          "GIL exemplars / 5m")],
        "short", 12, y))
    next_id += 1
    y += 8
    for i, name in enumerate(extra_metrics or []):
        panels.append(_panel(next_id, name, name, "short",
                             (i % 2) * 12, y + (i // 2) * 8))
        next_id += 1
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "schemaVersion": 39,
        "timezone": "browser",
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
    }


def grafana_dashboard_json(extra_metrics: "list[str] | None" = None) -> str:
    return json.dumps(grafana_dashboard(extra_metrics), indent=2)


# ----------------------------------------------------------------------
# Alert-rule export: the SAME registry the head's in-cluster engine
# evaluates (alertplane.default_rules), rendered to a Grafana
# provisioning bundle — external alerting can never drift from what the
# cluster itself watches.

def _selector(name: str, labels: "dict | None") -> str:
    if not labels:
        return name
    sel = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{sel}}}"


def _window(seconds: float) -> str:
    s = int(seconds)
    return f"{s // 60}m" if s >= 60 and s % 60 == 0 else f"{s}s"


def _threshold_expr(rule: dict) -> str:
    sel = _selector(rule["series"], rule.get("labels"))
    win = _window(float(rule.get("window_s", 60.0)))
    agg = rule.get("agg", "last")
    if agg == "rate":
        return f"sum(rate({sel}[{win}]))"
    if agg == "avg":
        return f"avg(avg_over_time({sel}[{win}]))"
    if agg == "min":
        return f"min(min_over_time({sel}[{win}]))"
    if agg == "max":
        return f"max(max_over_time({sel}[{win}]))"
    return f"max({sel})"  # "last": newest sample per series, folded


def _burn_expr(rule: dict, window_s: float) -> str:
    budget = max(1e-9, 1.0 - float(rule["objective"]))
    win = _window(window_s)
    if rule.get("bad") and rule.get("total"):
        bad = _selector(rule["bad"], rule.get("bad_labels"))
        total = _selector(rule["total"], rule.get("total_labels"))
        return (f"(sum(rate({bad}[{win}])) / "
                f"sum(rate({total}[{win}]))) / {budget:g}")
    sel = _selector(rule["series"], rule.get("labels"))
    over = float(rule["over"])
    # Time-fraction the gauge sat above the SLO bound, vs budget.
    return (f"avg_over_time(({sel} > bool {over:g})[{win}:]) "
            f"/ {budget:g}")


def grafana_alert_rules(rules: "list[dict] | None" = None) -> dict:
    """Grafana alert-provisioning bundle (apiVersion 1 file format)
    rendered from the head's rule registry. Threshold rules become a
    single-query classic condition; burn-rate rules render the
    multi-window AND (fast > factor and slow > factor) exactly as the
    in-cluster engine evaluates them."""
    if rules is None:
        from ray_tpu._private import alertplane
        from ray_tpu._private.config import Config

        rules = alertplane.default_rules(Config())
    out_rules = []
    for rule in rules:
        if rule.get("kind") == "burn_rate":
            factor = float(rule.get("burn_factor", 14.4))
            fast = _burn_expr(rule,
                              float(rule.get("fast_window_s", 300.0)))
            slow = _burn_expr(rule,
                              float(rule.get("slow_window_s", 3600.0)))
            expr = f"({fast} > {factor:g}) and ({slow} > {factor:g})"
        else:
            op = rule.get("op", ">")
            expr = (f"{_threshold_expr(rule)} {op} "
                    f"{float(rule['threshold']):g}")
        out_rules.append({
            "uid": f"ray-tpu-{rule['name']}",
            "title": rule["name"],
            "condition": "A",
            "for": _window(float(rule.get("for_s", 0.0))) if
                   rule.get("for_s") else "0s",
            "labels": {"severity": rule.get("severity", "warn"),
                       "source": "ray_tpu"},
            "annotations": {"summary": rule.get("summary", "")},
            "data": [{
                "refId": "A",
                "relativeTimeRange": {"from": 3600, "to": 0},
                "datasourceUid": "${datasource}",
                "model": {"expr": expr, "refId": "A",
                          "instant": True},
            }],
        })
    return {
        "apiVersion": 1,
        "groups": [{
            "orgId": 1,
            "name": "ray_tpu_slo",
            "folder": "ray_tpu",
            "interval": "30s",
            "rules": out_rules,
        }],
    }


def grafana_alert_rules_json(rules: "list[dict] | None" = None) -> str:
    return json.dumps(grafana_alert_rules(rules), indent=2)
