"""Prometheus + Grafana integration artifacts.

Counterpart of the reference's dashboard metrics module
(reference: python/ray/dashboard/modules/metrics/ — it writes a
Prometheus scrape config and generates Grafana dashboard JSON for the
cluster's metric set). Here the same two artifacts are generated from
the live registry and the dashboard's exposition endpoint:

    GET /api/prometheus_sd       Prometheus HTTP service-discovery body
    GET /api/grafana_dashboard   importable Grafana dashboard JSON

or from Python::

    from ray_tpu.util.metrics_export import (
        grafana_dashboard, prometheus_scrape_config)
"""

from __future__ import annotations

import json


def prometheus_sd(dashboard_host: str, dashboard_port: int) -> list:
    """HTTP service-discovery payload (prometheus http_sd_configs):
    point prometheus at GET /api/prometheus_sd and it scrapes every
    listed target's /metrics (reference: the dashboard's
    prometheus_service_discovery file)."""
    return [{
        "targets": [f"{dashboard_host}:{dashboard_port}"],
        "labels": {"job": "ray_tpu", "__metrics_path__": "/metrics"},
    }]


def prometheus_scrape_config(dashboard_host: str,
                             dashboard_port: int) -> str:
    """A ready-to-paste prometheus.yml scrape_configs entry."""
    return (
        "scrape_configs:\n"
        "  - job_name: ray_tpu\n"
        "    metrics_path: /metrics\n"
        "    static_configs:\n"
        f"      - targets: ['{dashboard_host}:{dashboard_port}']\n"
    )


def _panel(panel_id: int, title: str, expr: str, unit: str,
           x: int, y: int) -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [{"expr": expr, "refId": "A"}],
    }


def grafana_dashboard(extra_metrics: "list[str] | None" = None) -> dict:
    """Importable Grafana dashboard covering the core runtime metrics
    (reference: dashboard/modules/metrics/dashboards/*_dashboard_panels
    — default panels generated for the cluster metric set). User
    metrics passed in ``extra_metrics`` get a generic panel each."""
    panels = [
        _panel(1, "Tasks finished / s",
               "rate(ray_tpu_tasks_finished_total[1m])", "ops", 0, 0),
        _panel(2, "Tasks failed / s",
               "rate(ray_tpu_tasks_failed_total[1m])", "ops", 12, 0),
        _panel(3, "Object store used bytes",
               "ray_tpu_object_store_used_bytes", "bytes", 0, 8),
        _panel(4, "Objects in store",
               "ray_tpu_object_store_num_objects", "short", 12, 8),
        _panel(5, "Workers alive",
               "ray_tpu_workers_alive", "short", 0, 16),
        _panel(6, "Actors alive",
               "ray_tpu_actors_alive", "short", 12, 16),
    ]
    next_id = 7
    y = 24
    for i, name in enumerate(extra_metrics or []):
        panels.append(_panel(next_id, name, name, "short",
                             (i % 2) * 12, y + (i // 2) * 8))
        next_id += 1
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "schemaVersion": 39,
        "timezone": "browser",
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
    }


def grafana_dashboard_json(extra_metrics: "list[str] | None" = None) -> str:
    return json.dumps(grafana_dashboard(extra_metrics), indent=2)
