"""multiprocessing.Pool API over cluster tasks.

Counterpart of the reference's ray.util.multiprocessing
(python/ray/util/multiprocessing/pool.py — a Pool whose workers are Ray
actors, drop-in for the stdlib API). Here ``processes`` bounds in-flight
concurrency via chunked task submission; stdlib semantics covered:
map/starmap/imap/imap_unordered/apply/apply_async + context manager.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable

import ray_tpu

# Worker-process-local record of pools whose initializer already ran here —
# stdlib contract: initializer fires once per worker process, not per task.
_initialized_pools: set = set()


def _maybe_init(pool_id: str, init, initargs) -> None:
    if init is None or pool_id in _initialized_pools:
        return
    # Record success only AFTER the initializer returns: a transient
    # failure must be retried on this worker's next task, not silently
    # skipped leaving every later task uninitialized.
    init(*initargs)
    _initialized_pools.add(pool_id)


class AsyncResult:
    def __init__(self, refs: list, single: bool):
        self._refs = refs
        self._single = single
        self._outcome: tuple[bool, Any] | None = None  # (ok, value/exc)

    def get(self, timeout: float | None = None):
        if self._outcome is None:
            try:
                out = ray_tpu.get(self._refs, timeout=timeout)
            except ray_tpu.exceptions.GetTimeoutError:
                # Stdlib contract: Pool results raise
                # multiprocessing.TimeoutError (NOT builtin TimeoutError).
                import multiprocessing

                raise multiprocessing.TimeoutError() from None
            except Exception as e:  # noqa: BLE001 — stdlib Pool re-raises
                self._outcome = (False, e)
            else:
                flat = [x for chunk in out for x in chunk]
                self._outcome = (True, flat[0] if self._single else flat)
        ok, value = self._outcome
        if ok:
            return value
        raise value

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if self._outcome is None:
            try:
                self.get()
            except Exception:
                pass
        return bool(self._outcome and self._outcome[0])


class Pool:
    """API-compatible subset of multiprocessing.Pool on cluster tasks."""

    def __init__(self, processes: int | None = None, initializer=None,
                 initargs: tuple = ()):
        import uuid

        ray_tpu.api.auto_init()
        self._processes = processes or int(
            ray_tpu.cluster_resources().get("CPU", 4)
        )
        self._initializer = initializer
        self._initargs = initargs
        self._pool_id = uuid.uuid4().hex  # once-per-worker initializer key
        self._closed = False

    # -- helpers -----------------------------------------------------------

    def _chunked_task(self):
        init, initargs, pool_id = self._initializer, self._initargs, self._pool_id

        @ray_tpu.remote
        def run_chunk(fn: Callable, chunk: list, star: bool):
            _maybe_init(pool_id, init, initargs)
            return [fn(*args) if star else fn(args) for args in chunk]

        return run_chunk

    def _submit(self, fn, iterable, star: bool, chunksize: int | None):
        items = list(iterable)
        if not items:
            return []
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        task = self._chunked_task()
        return [
            task.remote(fn, items[i:i + chunksize], star)
            for i in range(0, len(items), chunksize)
        ]

    # -- stdlib surface ----------------------------------------------------

    def map(self, fn: Callable, iterable: Iterable, chunksize: int | None = None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        self._check_open()
        return AsyncResult(self._submit(fn, iterable, False, chunksize), False)

    def starmap(self, fn: Callable, iterable: Iterable, chunksize=None):
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        self._check_open()
        return AsyncResult(self._submit(fn, iterable, True, chunksize), False)

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        self._check_open()
        refs = self._submit(fn, iterable, False, chunksize)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        self._check_open()
        refs = self._submit(fn, iterable, False, chunksize)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in ready:
                yield from ray_tpu.get(ref)

    def apply(self, fn: Callable, args: tuple = (), kwargs: dict | None = None):
        return self.apply_async(fn, args, kwargs).get()

    def apply_async(self, fn, args: tuple = (), kwargs: dict | None = None) -> AsyncResult:
        self._check_open()
        kwargs = kwargs or {}
        init, initargs, pool_id = self._initializer, self._initargs, self._pool_id

        @ray_tpu.remote
        def run_one():
            _maybe_init(pool_id, init, initargs)
            return [fn(*args, **kwargs)]

        return AsyncResult([run_one.remote()], True)

    # -- lifecycle ---------------------------------------------------------

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def close(self) -> None:
        self._closed = True

    terminate = close

    def join(self) -> None:
        if not self._closed:
            raise ValueError("join() before close()")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
