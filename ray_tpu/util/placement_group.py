"""Placement groups: gang-scheduled resource bundles.

Counterpart of the reference's placement group API (reference:
python/ray/util/placement_group.py:41 PlacementGroup, :145 placement_group();
GCS-side 2PC scheduler gcs_placement_group_scheduler.h). On TPU clusters a
placement group is the unit that maps to a pod slice: reserving a
``{"TPU": k}`` bundle per host pins the gang to the slice's ICI domain
(SURVEY.md §7 "mesh-aware placement groups").
"""

from __future__ import annotations

from typing import Sequence

from ray_tpu._private.ids import ObjectRef
from ray_tpu._private.worker_context import global_runtime

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: list[dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self) -> ObjectRef:
        """ObjectRef sealed when all bundles are reserved; use with get()."""
        return ObjectRef(self.id + ":ready")

    def wait(self, timeout_seconds: float | None = None) -> bool:
        from ray_tpu import api

        try:
            api.get(self.ready(), timeout=timeout_seconds)
            return True
        except Exception:
            return False

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: Sequence[dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    bundles = [dict(b) for b in bundles]
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    rt = global_runtime()
    reply = rt.conn.call(
        "create_pg", {"bundles": bundles, "strategy": strategy, "name": name}
    )
    return PlacementGroup(reply["pg_id"], bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    global_runtime().conn.call("remove_pg", {"pg_id": pg.id})


def placement_group_table() -> dict:
    rt = global_runtime()
    nodes = rt.conn.call("get_nodes", {})["nodes"]
    return {"nodes": nodes}
