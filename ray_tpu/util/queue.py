"""Distributed Queue backed by an actor.

Counterpart of the reference's ray.util.queue.Queue (util/queue.py:21):
a named-or-anonymous queue actor shared across drivers/workers, with
blocking put/get via short polling (the actor itself never blocks its
executor thread indefinitely)."""

from __future__ import annotations

import collections
import time
from typing import Any, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: collections.deque = collections.deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def put_many(self, items: list) -> int:
        n = 0
        for it in items:
            if not self.put(it):
                break
            n += 1
        return n

    def put_many_atomic(self, items: list) -> bool:
        """All-or-nothing insert (capacity checked before mutating)."""
        if self.maxsize > 0 and len(self.items) + len(items) > self.maxsize:
            return False
        self.items.extend(items)
        return True

    def get(self, n: int = 1) -> tuple[list, bool]:
        if not self.items:
            return [], False
        out = [self.items.popleft() for _ in range(min(n, len(self.items)))]
        return out, True

    def get_exact(self, n: int) -> tuple[list, bool]:
        """All-or-nothing batch pop (reference get_nowait_batch semantics)."""
        if len(self.items) < n:
            return [], False
        return [self.items.popleft() for _ in range(n)], True


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: dict | None = None):
        ray_tpu.api.auto_init()
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self._actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item: Any, block: bool = True, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full("queue is full")
            if deadline is not None and time.monotonic() > deadline:
                raise Full("queue put timed out")
            time.sleep(0.05)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            items, ok = ray_tpu.get(self._actor.get.remote(1))
            if ok:
                return items[0]
            if not block:
                raise Empty("queue is empty")
            if deadline is not None and time.monotonic() > deadline:
                raise Empty("queue get timed out")
            time.sleep(0.05)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, n: int) -> list:
        items, ok = ray_tpu.get(self._actor.get_exact.remote(n))
        if not ok:
            raise Empty(f"queue holds fewer than {n} items")
        return items

    def put_nowait_batch(self, items: list) -> None:
        if not ray_tpu.get(self._actor.put_many_atomic.remote(list(items))):
            raise Full(f"queue lacks capacity for {len(items)} items")

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass
