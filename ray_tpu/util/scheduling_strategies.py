"""Public scheduling strategies (reference:
python/ray/util/scheduling_strategies.py — NodeAffinitySchedulingStrategy,
PlacementGroupSchedulingStrategy, plus the "DEFAULT"/"SPREAD" string
strategies). The dataclasses live with the cluster scheduler; this module
is the user-facing import path:

    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
    f.options(scheduling_strategy=NodeAffinitySchedulingStrategy(node_id, soft=True))
"""

from ray_tpu._private.scheduler import (
    DoesNotExist,
    Exists,
    In,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    NotIn,
    PlacementGroupSchedulingStrategy,
)

DEFAULT_SCHEDULING_STRATEGY = "DEFAULT"
SPREAD_SCHEDULING_STRATEGY = "SPREAD"

__all__ = [
    "DEFAULT_SCHEDULING_STRATEGY",
    "SPREAD_SCHEDULING_STRATEGY",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "In",
    "NotIn",
    "Exists",
    "DoesNotExist",
]
