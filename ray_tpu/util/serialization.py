"""Custom serializer registration.

Counterpart of the reference's ray.util.serialization
(reference: python/ray/util/serialization.py — register_serializer /
deregister_serializer installing per-class reducers into the worker's
serialization context). The reducer embeds the deserializer (cloudpickle
serializes it by value), so workers reconstruct objects without any
receiver-side registration step.

Scoping matches the reference: the reducer lives in the runtime's own
serialization context (`_private.serialization.custom_reducers`), NOT in
the process-global copyreg dispatch table — `copy.deepcopy` and user
`pickle.dumps` of the class are unaffected.

    class Conn: ...                      # unpicklable (sockets inside)
    ray_tpu.util.register_serializer(
        Conn,
        serializer=lambda c: c.address,
        deserializer=lambda addr: Conn(addr),
    )
"""

from __future__ import annotations

from typing import Any, Callable

from ray_tpu._private.serialization import custom_reducers


def _reconstruct(deserializer: Callable, payload: Any):
    return deserializer(payload)


def register_serializer(cls: type, *, serializer: Callable[[Any], Any],
                        deserializer: Callable[[Any], Any]) -> None:
    """Route object-store serialization of ``cls`` instances through
    ``serializer`` (must return something picklable); workers rebuild via
    ``deserializer``. Only ray_tpu transfers are affected — in-process
    pickling of the class keeps its normal behavior."""

    def reducer(obj):
        return _reconstruct, (deserializer, serializer(obj))

    custom_reducers[cls] = reducer


def deregister_serializer(cls: type) -> None:
    custom_reducers.pop(cls, None)
