"""Custom serializer registration.

Counterpart of the reference's ray.util.serialization
(reference: python/ray/util/serialization.py — register_serializer /
deregister_serializer installing per-class reducers into the worker's
serialization context). Implementation: a copyreg reducer that embeds the
deserializer (cloudpickle serializes it by value), so workers reconstruct
objects without any receiver-side registration step.

    class Conn: ...                      # unpicklable (sockets inside)
    ray_tpu.util.register_serializer(
        Conn,
        serializer=lambda c: c.address,
        deserializer=lambda addr: Conn(addr),
    )
"""

from __future__ import annotations

import copyreg
from typing import Any, Callable


def _reconstruct(deserializer: Callable, payload: Any):
    return deserializer(payload)


# cls -> the dispatch entry (if any) that existed before registration,
# restored on deregister so user-installed copyreg reducers survive.
_previous_entries: dict[type, Any] = {}


def register_serializer(cls: type, *, serializer: Callable[[Any], Any],
                        deserializer: Callable[[Any], Any]) -> None:
    """Route pickling of ``cls`` instances through ``serializer`` (must
    return something picklable); workers rebuild via ``deserializer``.

    Scope note (design difference vs the reference, which hooks only
    Ray's serialization context): this installs a copyreg reducer, so it
    affects EVERY pickle of ``cls`` in this process — including
    copy.deepcopy and user pickle.dumps. That is what makes the hook
    work with zero receiver-side setup (the deserializer ships by value
    inside the stream)."""
    if cls not in _previous_entries:
        _previous_entries[cls] = copyreg.dispatch_table.get(cls)

    def reducer(obj):
        return _reconstruct, (deserializer, serializer(obj))

    copyreg.pickle(cls, reducer)


def deregister_serializer(cls: type) -> None:
    prev = _previous_entries.pop(cls, None)
    if prev is not None:
        copyreg.dispatch_table[cls] = prev
    else:
        copyreg.dispatch_table.pop(cls, None)
