"""Custom serializer registration.

Counterpart of the reference's ray.util.serialization
(reference: python/ray/util/serialization.py — register_serializer /
deregister_serializer installing per-class reducers into the worker's
serialization context). Implementation: a copyreg reducer that embeds the
deserializer (cloudpickle serializes it by value), so workers reconstruct
objects without any receiver-side registration step.

    class Conn: ...                      # unpicklable (sockets inside)
    ray_tpu.util.register_serializer(
        Conn,
        serializer=lambda c: c.address,
        deserializer=lambda addr: Conn(addr),
    )
"""

from __future__ import annotations

import copyreg
from typing import Any, Callable


def _reconstruct(deserializer: Callable, payload: Any):
    return deserializer(payload)


def register_serializer(cls: type, *, serializer: Callable[[Any], Any],
                        deserializer: Callable[[Any], Any]) -> None:
    """Route pickling of ``cls`` instances through ``serializer`` (must
    return something picklable); workers rebuild via ``deserializer``."""

    def reducer(obj):
        return _reconstruct, (deserializer, serializer(obj))

    copyreg.pickle(cls, reducer)


def deregister_serializer(cls: type) -> None:
    copyreg.dispatch_table.pop(cls, None)
