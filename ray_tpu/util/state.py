"""State API: cluster introspection.

Counterpart of the reference's ray.util.state (util/state/api.py —
list_actors :784, list_tasks :1011, summarize_tasks :1368), backed by the
head's task/actor/object/worker tables instead of GCS task events."""

from __future__ import annotations

import time
from collections import Counter

from ray_tpu._private.worker_context import global_runtime


def _call(method: str, body: dict | None = None) -> dict:
    return global_runtime().conn.call(method, body or {})


def _filtered(rows: list[dict], filters) -> list[dict]:
    """filters: list of (key, predicate '=' or '!=', value) tuples."""
    if not filters:
        return rows
    out = []
    for r in rows:
        ok = True
        for key, op, value in filters:
            have = r.get(key)
            if op == "=":
                ok = ok and str(have) == str(value)
            elif op == "!=":
                ok = ok and str(have) != str(value)
            else:
                raise ValueError(f"unsupported filter op {op!r}")
        if ok:
            out.append(r)
    return out


def list_tasks(*, filters=None, limit: int = 1000) -> list[dict]:
    # A state equality filter is pushed down to the head (hot path for
    # autoscaler/dashboard polls); remaining filters apply client-side
    # over the full table window so matches outside the last `limit`
    # rows aren't silently missed.
    filters = list(filters or [])
    body: dict = {}
    for f in list(filters):
        # Equality filters on indexed/point keys push down to the head
        # (hot path for autoscaler/dashboard polls and drill-downs).
        if f[1] == "=" and f[0] in ("state", "task_id", "worker_id"):
            body[f[0]] = f[2]
            filters.remove(f)
    # Only filters that remain CLIENT-side force a full-table fetch.
    body["limit"] = limit if not filters else 1_000_000
    rows = _call("list_tasks", body)["tasks"]
    return _filtered([dict(r) for r in rows], filters)[:limit]


def list_actors(*, filters=None, limit: int = 1000) -> list[dict]:
    # An actor_id equality filter is a point lookup — pushed down to the
    # head (mirrors the task_id pushdown in list_tasks) so drill-downs
    # never ship the whole actor table.
    filters = list(filters or [])
    body: dict = {}
    for f in list(filters):
        if f[1] == "=" and f[0] == "actor_id":
            body["actor_id"] = f[2]
            filters.remove(f)
    rows = _call("list_actors", body)["actors"]
    return _filtered(rows, filters)[:limit]


def list_objects(*, filters=None, limit: int = 1000) -> list[dict]:
    # An object_id equality filter is a point lookup — pushed down to
    # the head (mirrors the task_id/actor_id pushdowns above) so
    # drill-downs never transfer the whole object table.
    filters = list(filters or [])
    body: dict = {}
    for f in list(filters):
        if f[1] == "=" and f[0] == "object_id":
            body["object_id"] = f[2]
            filters.remove(f)
    body["limit"] = limit if not filters else 1_000_000
    rows = _call("list_objects", body)["objects"]
    return _filtered(rows, filters)[:limit]


def get_object(object_id: str) -> "dict | None":
    """One object's full record + lineage chain (``obj ← task ← args ←
    …``) and the producing task's flight-recorder phases — the
    `ray-tpu memory <object_id>` drill-down. Point lookup pushed down
    to the head."""
    reply = _call("get_object", {"object_id": object_id})
    return reply.get("object")


def list_workers(*, filters=None, limit: int = 1000) -> list[dict]:
    rows = _call("list_workers")["workers"]
    return _filtered(rows, filters)[:limit]


def list_nodes(*, filters=None, limit: int = 1000) -> list[dict]:
    rows = _call("get_nodes")["nodes"]
    return _filtered(rows, filters)[:limit]


def list_placement_groups(*, filters=None, limit: int = 1000) -> list[dict]:
    """Reference: util/state list_placement_groups."""
    rows = _call("list_placement_groups")["placement_groups"]
    return _filtered(rows, filters)[:limit]


def list_jobs(*, filters=None, limit: int = 1000) -> list[dict]:
    """Submitted jobs (reference: util/state list_jobs / JobSubmissionClient
    list_jobs)."""
    from ray_tpu import job_submission

    rows = [dict(j) for j in job_submission.list_jobs()]
    return _filtered(rows, filters)[:limit]


def get_task(task_id: str) -> "dict | None":
    """One task's record (reference: util/state/api.py get_task).
    Point lookup pushed down to the head — never ships the table."""
    rows = _call("list_tasks", {"task_id": task_id, "limit": 1})["tasks"]
    return dict(rows[0]) if rows else None


def get_actor(actor_id: str) -> "dict | None":
    """One actor's record (reference: util/state/api.py get_actor).
    Point lookup pushed down to the head — never ships the table."""
    rows = _call("list_actors", {"actor_id": actor_id})["actors"]
    return dict(rows[0]) if rows else None


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize_tasks() -> dict:
    """Counts by (name, state) — reference: util/state/api.py:1368 —
    plus per-phase latency breakdowns (p50/p95 of queue wait, dispatch,
    exec, result transfer) derived from the flight-recorder lifecycle
    events, clock-aligned across nodes."""
    from ray_tpu._private.events import phase_latencies

    by_name: dict[str, Counter] = {}
    for t in list_tasks(limit=100000):
        by_name.setdefault(t["name"], Counter())[t["state"]] += 1
    # Phase latencies per task name from the head's event table.
    lat_by_name: dict[str, dict[str, list]] = {}
    data = get_timeline_data()
    for ev in data["events"]:
        if not isinstance(ev, dict) or "phases" not in ev \
                or not ev.get("name"):
            continue
        aligned = _aligned(ev, data)
        bucket = lat_by_name.setdefault(ev["name"], {})
        for phase, dt in phase_latencies(aligned).items():
            bucket.setdefault(phase, []).append(max(0.0, dt))
        # Executor-thread CPU seconds (worker-stamped): exec_cpu far
        # below exec reads as a GIL-starved or IO/lock-blocked task —
        # visible here instead of the old stderr timing prints.
        if isinstance(ev.get("cpu_time"), (int, float)):
            bucket.setdefault("exec_cpu", []).append(
                max(0.0, ev["cpu_time"]))
    out = {}
    for name, states in by_name.items():
        entry = {"state_counts": dict(states),
                 "total": sum(states.values())}
        lats = lat_by_name.get(name)
        if lats:
            entry["phase_latency_s"] = {
                phase: {"p50": _percentile(sorted(vals), 0.50),
                        "p95": _percentile(sorted(vals), 0.95),
                        "count": len(vals)}
                for phase, vals in lats.items()}
        out[name] = entry
    return out


def summarize_actors() -> dict:
    states = Counter(a["state"] for a in list_actors(limit=100000))
    return {"state_counts": dict(states), "total": sum(states.values())}


def summarize_objects() -> dict:
    """Counts + bytes by state (reference: util/state summarize_objects),
    plus per-callsite and per-node groupings from the object census
    (head-merged owner reports; see memory_summary for the raw feed)."""
    objs = list_objects(limit=100000)
    states = Counter(o["state"] for o in objs)
    size_by_state: dict[str, int] = Counter()
    for o in objs:
        size_by_state[o["state"]] += int(o.get("size", 0) or 0)
    mem = memory_summary()
    return {
        "state_counts": dict(states),
        "bytes_by_state": dict(size_by_state),
        "total": len(objs),
        "total_bytes": sum(size_by_state.values()),
        # Callsite-attributed live refs (owner censuses, merged across
        # clients by the head) and directory bytes per node.
        "by_callsite": mem.get("groups") or {},
        "by_node": mem.get("by_node") or {},
    }


def object_store_stats() -> dict:
    """Shm-store stats incl. the pin/fragmentation breakdown
    (pinned vs reclaimable sealed bytes, eviction-candidate count,
    fragmented free space) that explains memory-pressure decisions."""
    return _call("store_stats")


def memory_summary() -> dict:
    """The `ray-tpu memory` feed (reference: `ray memory` /
    internal_api.py memory_summary): owner censuses merged by callsite
    (count/bytes/kinds/unawaited per creating callsite), directory
    bytes by node and state, store stats, per-client census health,
    and the leak detector's current suspects with trend data."""
    return _call("memory_summary")


def list_logs(*, node_id: "str | None" = None) -> list[dict]:
    """Worker log index (reference: util/state list_logs). With a
    node_id the head forwards to that node's agent, so every node's
    logs are listable from the driver."""
    body = {"node_id": node_id} if node_id else {}
    return _call("log_index", body)["logs"]


def get_log(name: str, *, tail: int = 500, max_bytes: int = 64 * 1024,
            node_id: "str | None" = None) -> list[str]:
    """Tail one worker log (reference: util/state get_log), locally or
    on a remote node via its agent."""
    body = {"name": name, "max_bytes": max_bytes}
    if node_id:
        body["node_id"] = node_id
    reply = _call("log_tail", body)
    return reply["lines"][-tail:] if tail > 0 else []


def get_trace(trace_id: str) -> "dict | None":
    """One causal trace tree: summary plus full span detail
    (`ray-tpu trace <id>` backs onto this)."""
    return _call("get_trace", {"trace_id": trace_id})["trace"]


def list_traces(*, limit: int = 100,
                exemplars_only: bool = False) -> list[dict]:
    """Retained trace summaries, newest first. Tail-based retention:
    slow/error/shed exemplars and a uniform 1-in-N sample keep full
    detail; folded traces appear only in runtime_stats counters."""
    return _call("list_traces", {
        "limit": limit, "exemplars_only": exemplars_only})["traces"]


def health_summary() -> dict:
    """Operator health view (`ray-tpu health` backs onto this): overload
    state (pending budgets, deadline sheds, admission rejections,
    memory-pressured nodes) and the unified retry plane's circuit
    breakers — the head process's own plus every reporting client's, so
    "why is traffic to that peer being shed" has one answer surface."""
    snap = _call("runtime_stats")
    clients = (snap.get("rpc") or {}).get("clients") or {}
    client_breakers = {
        cid: {t: b for t, b in (c.get("breakers") or {}).items()}
        for cid, c in clients.items() if c.get("breakers")}
    open_breakers = {}
    for scope, table in [("head", snap.get("breakers") or {})] + [
            (cid, t) for cid, t in client_breakers.items()]:
        for target, b in table.items():
            if b.get("open") or b.get("trip_count"):
                open_breakers.setdefault(scope, {})[target] = b
    gauges = snap.get("gauges") or {}
    return {
        "gauges": gauges,
        "counters": snap.get("counters") or {},
        "tasks_shed": snap.get("tasks_shed") or {},
        "pressured_nodes": snap.get("pressured_nodes") or {},
        "worker_deaths": snap.get("worker_deaths") or {},
        # Breakers that are open now or have tripped before, per
        # process ("head" = the head process itself).
        "breakers": open_breakers,
    }


def list_crash_reports(*, filters=None, limit: int = 100) -> list[dict]:
    """Classified worker/node death reports from the head's bounded
    crash-forensics table (reference analogue: the GCS worker-death
    table with WorkerExitType + exit_detail). Summary rows — use
    get_crash_report() for the full evidence (stacks, log tail,
    beacon, flight-recorder cross-link)."""
    rows = _call("list_crash_reports", {"limit": limit})["reports"]
    return _filtered(rows, filters)[:limit]


def get_crash_report(worker_id: str) -> "dict | None":
    """One death's FULL post-mortem report: classification
    (exit_type/exit_detail), real exit code / terminating signal,
    faulthandler stack excerpt, log tail, the worker's last beacon
    (task, phase, rss, cpu at the instant of death), and its last
    flight-recorder events. Node deaths live under ``node:<node_id>``."""
    rows = _call("list_crash_reports", {"worker_id": worker_id})["reports"]
    return dict(rows[0]) if rows else None


def profile_worker(worker_id: str, duration_s: float = 5.0, *,
                   mode: str = "cpu", hz: int = 50,
                   include_idle: bool = False) -> dict:
    """Sample one live worker's threads for ``duration_s`` seconds and
    return folded collapsed stacks (``{"file:func;file:func": hits}``)
    — the Python API over the worker's sampling profiler that was
    previously reachable only through the dashboard's /api/profile
    endpoint. ``mode="memory"`` traces allocations (tracemalloc window)
    instead. Render with save_flamegraph() / save_speedscope()."""
    body = {"worker_id": worker_id, "sample_s": float(duration_s),
            "hz": int(hz), "mode": mode, "include_idle": bool(include_idle)}
    return global_runtime().conn.call("profile_worker", body,
                                      timeout=float(duration_s) + 20.0)


def cluster_profile(*, role: "str | None" = None,
                    node: "str | None" = None,
                    window: "int | None" = None) -> dict:
    """The continuous profiling plane's merged cluster table
    (`ray-tpu profile` backs onto this): every process samples its own
    threads on a duty cycle from boot (head, dispatch shards, node
    agents, workers, drivers — role-tagged), window summaries ride the
    amortized rpc_report/heartbeat casts, and the head merges them into
    bounded windows keyed (node, role, window index).

    Returns ``{"windows": [...], "gil_exemplars": [...], "stats": {...},
    "window_s": float}``. Each window carries ``folded`` collapsed
    stacks mergeable with profile_worker() output — render via
    save_flamegraph()/save_speedscope() after merging with
    profplane.merge_folded, or let the CLI do it."""
    body: dict = {}
    if role is not None:
        body["role"] = role
    if node is not None:
        body["node"] = node
    if window is not None:
        body["window"] = int(window)
    return _call("cluster_profile", body)


def query_metrics(name: str, labels: "dict | None" = None,
                  start: "float | None" = None,
                  end: "float | None" = None,
                  step: "float | None" = None) -> dict:
    """Range query against the head's embedded time-series store
    (`ray-tpu metrics query` and the dashboard Charts view back onto
    this). History is retained in two tiers — raw ~10s buckets for the
    last ~30min, 1min rollups for ~24h — and the store answers from
    whichever tier covers ``start`` (``step`` coarser than the tier
    resolution resamples).

    Returns ``{"series": [{"name", "labels", "kind", "resolution_s",
    "points"}], "enabled": bool}``; each point is a
    ``[ts, min, max, sum, count, last]`` aggregate bucket. Under a
    sharded head every shard's store is queried and same-keyed series
    merge. Empty when ``RAY_TPU_TSDB_ENABLED=0``."""
    body: dict = {"name": name}
    if labels:
        body["labels"] = dict(labels)
    if start is not None:
        body["start"] = float(start)
    if end is not None:
        body["end"] = float(end)
    if step is not None:
        body["step"] = float(step)
    return _call("query_metrics", body)


def list_alerts(*, history: bool = False) -> dict:
    """The SLO alert engine's table (`ray-tpu alerts` backs onto
    this): active records (pending + firing) and, with
    ``history=True``, the bounded resolved ring. Returns
    ``{"alerts": [...], "stats": {...}, "enabled": bool}``; a firing
    record pins its cross-plane evidence under ``context`` (trace
    exemplar ids, overlapping profile windows, crash reports)."""
    return _call("list_alerts", {"history": bool(history)})


def save_flamegraph(profile: dict, path: str) -> str:
    """Write a profile_worker() result as collapsed-stack lines — the
    input format of flamegraph.pl / inferno / speedscope's importer."""
    folded = profile.get("folded") or {}
    with open(path, "w") as f:
        for stack, hits in folded.items():
            f.write(f"{stack} {hits}\n")
    return path


def to_speedscope(profile: dict, name: str = "ray_tpu worker") -> dict:
    """Convert a profile_worker() result to the speedscope file format
    (https://www.speedscope.app) — paste/drag the saved JSON into the
    web UI for an interactive flamegraph."""
    folded = profile.get("folded") or {}
    frames: list[dict] = []
    index: dict[str, int] = {}
    samples, weights = [], []
    for stack, hits in folded.items():
        sample = []
        for frame in stack.split(";"):
            i = index.get(frame)
            if i is None:
                i = index[frame] = len(frames)
                frames.append({"name": frame})
            sample.append(i)
        samples.append(sample)
        weights.append(hits)
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": f"{name} ({profile.get('worker_id', '?')})",
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
    }


def save_speedscope(profile: dict, path: str,
                    name: str = "ray_tpu worker") -> str:
    import json

    with open(path, "w") as f:
        json.dump(to_speedscope(profile, name), f)
    return path


def get_task_events(limit: int = 10000,
                    task_ids: "list[str] | None" = None) -> list[dict]:
    body: dict = {"limit": limit}
    if task_ids is not None:
        body["task_ids"] = list(task_ids)
    return _call("get_task_events", body)["events"]


def get_timeline_data(limit: int = 10000) -> dict:
    """Raw flight-recorder feed: events PLUS the head's per-node clock
    offsets and node id — everything timeline() needs to align
    cross-node spans onto one clock."""
    reply = _call("get_task_events", {"limit": limit})
    return {"events": reply["events"],
            "clock_offsets": reply.get("clock_offsets") or {},
            "head_node_id": reply.get("head_node_id")}


def _aligned(ev: dict, data: dict) -> dict:
    from ray_tpu._private.events import align_phases

    return align_phases(ev, data["clock_offsets"], data["head_node_id"])


def timeline(filename: str | None = None) -> "list | str":
    """Chrome-trace export of the task flight recorder (reference:
    _private/profiling.py:124 `ray timeline`). Load the result in
    chrome://tracing or Perfetto (ui.perfetto.dev).

    Per task: the classic execution span (cat "task") on the executing
    node's track, one sub-span per lifecycle segment (cat "phase":
    submit/queue/dispatch/dequeue/exec/seal/resolve — owner- and
    head-side segments render on their own tracks), and flow arrows
    (cat "lifecycle") connecting submit → push/dispatch → exec → resolve
    across pids. Chaos-plane faults appear as instant events (cat
    "chaos") on the node that injected them; user tracing.span events
    keep their old rendering. Cross-node timestamps are aligned onto the
    head's clock via the heartbeat-estimated offsets."""
    from ray_tpu._private.events import PHASE_DOMAIN, PHASE_SEGMENTS

    data = get_timeline_data()
    trace: list = []
    track_index: dict = {}  # Chrome traces want integer pids

    def _pid(label) -> int:
        return track_index.setdefault(label or "?", len(track_index))

    for ev in data["events"]:
        if not isinstance(ev, dict):
            continue
        if ev.get("event") in ("worker_death", "oom_kill"):
            # Crash-forensics instants: classified worker deaths and
            # memory-monitor kills on the dead worker's node track.
            off = (data["clock_offsets"].get(ev.get("node_id"), 0.0)
                   if ev.get("node_id") else 0.0)
            reason = ev.get("reason") or "oom_kill"
            trace.append({
                "cat": "death", "ph": "i", "s": "p",
                "name": f"death:{reason}:{(ev.get('worker_id') or '')[:16]}",
                "ts": (ev["ts"] - off) * 1e6,
                "pid": _pid(ev.get("node_id")),
                "tid": int(ev.get("pid") or 0),
                "args": {k: ev.get(k) for k in
                         ("worker_id", "node_id", "reason", "detail",
                          "tasks") if ev.get(k) is not None},
            })
            continue
        if ev.get("event") == "overload":
            # Overload-protection instants: deadline sheds, admission
            # rejections, memory-pressure transitions — rendered on the
            # affected node's track (or a dedicated "overload" track).
            kind = ev.get("kind") or "shed"
            off = (data["clock_offsets"].get(ev.get("node_id"), 0.0)
                   if ev.get("node_id") else 0.0)
            trace.append({
                "cat": "overload", "ph": "i", "s": "p",
                "name": f"overload:{kind}"
                        + (f":{ev['where']}" if ev.get("where") else ""),
                "ts": (ev["ts"] - off) * 1e6,
                "pid": _pid(ev.get("node_id") or "overload"),
                "tid": 0,
                "args": {k: ev.get(k) for k in
                         ("kind", "where", "task_id", "name", "owner_id",
                          "scope", "pending", "limit", "node_id",
                          "used_bytes", "total_bytes")
                         if ev.get(k) is not None},
            })
            continue
        if ev.get("event") == "chaos":
            trace.append({
                "cat": "chaos", "ph": "i", "s": "p",
                "name": f"fault:{ev.get('action')}:{ev.get('kind')}",
                "ts": ev["ts"] * 1e6,
                "pid": _pid("chaos"), "tid": int(ev.get("pid") or 0),
                "args": {k: ev.get(k) for k in
                         ("action", "direction", "peer", "kind",
                          "delay_s") if ev.get(k) is not None},
            })
            continue
        phases = _aligned(ev, data) if "phases" in ev else {}
        worker_pid = _pid(ev.get("node_id"))
        worker_tid = int(ev.get("pid") or 0)
        name = ev.get("name")
        args = {"task_id": ev.get("task_id"),
                "node_id": ev.get("node_id"),
                "failed": ev.get("failed", False)}
        if ev.get("start") is not None and ev.get("end") is not None:
            # The classic execution / user-span complete event (kept
            # verbatim: existing tooling and tests key on it).
            off = (data["clock_offsets"].get(ev.get("node_id"), 0.0)
                   if ev.get("node_id") else 0.0)
            trace.append({
                "cat": "span" if ev.get("event") == "span" else "task",
                "name": name, "ph": "X",
                "ts": (ev["start"] - off) * 1e6,
                "dur": (ev["end"] - ev["start"]) * 1e6,
                "pid": worker_pid, "tid": worker_tid,
                "args": {**args, **(
                    {"parent": ev.get("parent"),
                     **(ev.get("attributes") or {})}
                    if ev.get("event") == "span" else {})},
            })
        if not phases:
            continue
        owner_pid = _pid(ev.get("owner_node_id") or "owner")
        head_pid = _pid(data.get("head_node_id") or "head")
        track_for = {"owner": (owner_pid, 0), "head": (head_pid, 0),
                     "worker": (worker_pid, worker_tid)}
        for a, b, label in PHASE_SEGMENTS:
            ta, tb = phases.get(a), phases.get(b)
            if ta is None or tb is None:
                continue
            pid_, tid_ = track_for[PHASE_DOMAIN.get(a, "worker")]
            trace.append({
                "cat": "phase", "name": label, "ph": "X",
                "ts": ta * 1e6, "dur": max(0.0, tb - ta) * 1e6,
                "pid": pid_, "tid": tid_,
                "args": {**args, "from": a, "to": b},
            })
        # Flow arrows: submit (owner) → recv (worker) → resolve (owner)
        # connect the per-task story across pids. A lone point would
        # render as a dangling arrow, so fewer than two emit nothing.
        flow_points = [(p, *track_for[PHASE_DOMAIN[p]])
                       for p in ("submit", "recv", "resolve")
                       if p in phases]
        if len(flow_points) >= 2:
            for i, (p, pid_, tid_) in enumerate(flow_points):
                ph = "s" if i == 0 else ("f" if i == len(flow_points) - 1
                                         else "t")
                step = {"cat": "lifecycle", "name": "task-flow",
                        "ph": ph, "id": ev.get("task_id"),
                        "ts": phases[p] * 1e6, "pid": pid_, "tid": tid_}
                if ph == "f":
                    step["bp"] = "e"
                trace.append(step)
    if filename is None:
        return trace
    import json

    with open(filename, "w") as f:
        json.dump(trace, f)
    return filename
