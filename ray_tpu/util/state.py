"""State API: cluster introspection.

Counterpart of the reference's ray.util.state (util/state/api.py —
list_actors :784, list_tasks :1011, summarize_tasks :1368), backed by the
head's task/actor/object/worker tables instead of GCS task events."""

from __future__ import annotations

import time
from collections import Counter

from ray_tpu._private.worker_context import global_runtime


def _call(method: str, body: dict | None = None) -> dict:
    return global_runtime().conn.call(method, body or {})


def _filtered(rows: list[dict], filters) -> list[dict]:
    """filters: list of (key, predicate '=' or '!=', value) tuples."""
    if not filters:
        return rows
    out = []
    for r in rows:
        ok = True
        for key, op, value in filters:
            have = r.get(key)
            if op == "=":
                ok = ok and str(have) == str(value)
            elif op == "!=":
                ok = ok and str(have) != str(value)
            else:
                raise ValueError(f"unsupported filter op {op!r}")
        if ok:
            out.append(r)
    return out


def list_tasks(*, filters=None, limit: int = 1000) -> list[dict]:
    # A state equality filter is pushed down to the head (hot path for
    # autoscaler/dashboard polls); remaining filters apply client-side
    # over the full table window so matches outside the last `limit`
    # rows aren't silently missed.
    filters = list(filters or [])
    body: dict = {}
    for f in list(filters):
        # Equality filters on indexed/point keys push down to the head
        # (hot path for autoscaler/dashboard polls and drill-downs).
        if f[1] == "=" and f[0] in ("state", "task_id", "worker_id"):
            body[f[0]] = f[2]
            filters.remove(f)
    # Only filters that remain CLIENT-side force a full-table fetch.
    body["limit"] = limit if not filters else 1_000_000
    rows = _call("list_tasks", body)["tasks"]
    return _filtered([dict(r) for r in rows], filters)[:limit]


def list_actors(*, filters=None, limit: int = 1000) -> list[dict]:
    # An actor_id equality filter is a point lookup — pushed down to the
    # head (mirrors the task_id pushdown in list_tasks) so drill-downs
    # never ship the whole actor table.
    filters = list(filters or [])
    body: dict = {}
    for f in list(filters):
        if f[1] == "=" and f[0] == "actor_id":
            body["actor_id"] = f[2]
            filters.remove(f)
    rows = _call("list_actors", body)["actors"]
    return _filtered(rows, filters)[:limit]


def list_objects(*, filters=None, limit: int = 1000) -> list[dict]:
    rows = _call("list_objects")["objects"]
    return _filtered(rows, filters)[:limit]


def list_workers(*, filters=None, limit: int = 1000) -> list[dict]:
    rows = _call("list_workers")["workers"]
    return _filtered(rows, filters)[:limit]


def list_nodes(*, filters=None, limit: int = 1000) -> list[dict]:
    rows = _call("get_nodes")["nodes"]
    return _filtered(rows, filters)[:limit]


def list_placement_groups(*, filters=None, limit: int = 1000) -> list[dict]:
    """Reference: util/state list_placement_groups."""
    rows = _call("list_placement_groups")["placement_groups"]
    return _filtered(rows, filters)[:limit]


def list_jobs(*, filters=None, limit: int = 1000) -> list[dict]:
    """Submitted jobs (reference: util/state list_jobs / JobSubmissionClient
    list_jobs)."""
    from ray_tpu import job_submission

    rows = [dict(j) for j in job_submission.list_jobs()]
    return _filtered(rows, filters)[:limit]


def get_task(task_id: str) -> "dict | None":
    """One task's record (reference: util/state/api.py get_task).
    Point lookup pushed down to the head — never ships the table."""
    rows = _call("list_tasks", {"task_id": task_id, "limit": 1})["tasks"]
    return dict(rows[0]) if rows else None


def get_actor(actor_id: str) -> "dict | None":
    """One actor's record (reference: util/state/api.py get_actor).
    Point lookup pushed down to the head — never ships the table."""
    rows = _call("list_actors", {"actor_id": actor_id})["actors"]
    return dict(rows[0]) if rows else None


def summarize_tasks() -> dict:
    """Counts by (name, state) — reference: util/state/api.py:1368."""
    by_name: dict[str, Counter] = {}
    for t in list_tasks(limit=100000):
        by_name.setdefault(t["name"], Counter())[t["state"]] += 1
    return {
        name: {"state_counts": dict(states), "total": sum(states.values())}
        for name, states in by_name.items()
    }


def summarize_actors() -> dict:
    states = Counter(a["state"] for a in list_actors(limit=100000))
    return {"state_counts": dict(states), "total": sum(states.values())}


def summarize_objects() -> dict:
    """Counts + bytes by state (reference: util/state summarize_objects)."""
    objs = list_objects(limit=100000)
    states = Counter(o["state"] for o in objs)
    size_by_state: dict[str, int] = Counter()
    for o in objs:
        size_by_state[o["state"]] += int(o.get("size", 0) or 0)
    return {
        "state_counts": dict(states),
        "bytes_by_state": dict(size_by_state),
        "total": len(objs),
        "total_bytes": sum(size_by_state.values()),
    }


def object_store_stats() -> dict:
    return _call("store_stats")


def list_logs() -> list[dict]:
    """Worker log index on the head (reference: util/state list_logs)."""
    return _call("log_index")["logs"]


def get_log(name: str, *, tail: int = 500,
            max_bytes: int = 64 * 1024) -> list[str]:
    """Tail one worker log (reference: util/state get_log)."""
    reply = _call("log_tail", {"name": name, "max_bytes": max_bytes})
    return reply["lines"][-tail:] if tail > 0 else []


def get_task_events(limit: int = 10000,
                    task_ids: "list[str] | None" = None) -> list[dict]:
    body: dict = {"limit": limit}
    if task_ids is not None:
        body["task_ids"] = list(task_ids)
    return _call("get_task_events", body)["events"]


def timeline(filename: str | None = None) -> "list | str":
    """Chrome-trace export of task profile events (reference:
    _private/profiling.py:124 `ray timeline`). Load the result in
    chrome://tracing or Perfetto."""
    events = get_task_events()
    trace = []
    node_index: dict[str, int] = {}  # Chrome traces want integer pids
    for ev in events:
        pid = node_index.setdefault(ev["node_id"], len(node_index))
        trace.append(
            {
                "cat": "task",
                "name": ev["name"],
                "ph": "X",  # complete event
                "ts": ev["start"] * 1e6,
                "dur": (ev["end"] - ev["start"]) * 1e6,
                "pid": pid,
                "tid": int(ev["pid"]),
                "args": {
                    "task_id": ev["task_id"],
                    "node_id": ev["node_id"],
                    "failed": ev.get("failed", False),
                },
            }
        )
    if filename is None:
        return trace
    import json

    with open(filename, "w") as f:
        json.dump(trace, f)
    return filename
