"""User-level tracing spans, merged into the cluster timeline.

Counterpart of the reference's tracing/profiling helpers
(reference: python/ray/util/tracing/tracing_helper.py:34-127 — opt-in
OpenTelemetry spans around task/actor calls — and _private/profiling.py:84
``profile`` events buffered through TaskEventBuffer into `ray timeline`).
Here spans are lightweight dicts buffered into the traceplane's bounded
span buffer and flushed on the next amortized ``rpc_report`` cast — a
``span()`` inside a hot loop never produces per-span frames to the head.
At the head they land in both the task-event buffer (so
``ray_tpu.util.state.timeline()`` renders user spans alongside task
execution spans) and, when a request-trace context is ambient, in the
trace table as causal children of the enclosing request. OpenTelemetry
export is attached on top when the package is importable.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import threading
import time
from typing import Any

_local = threading.local()


def _emit(event: dict) -> None:
    """Buffer a span for the next amortized rpc_report flush (never a
    per-span cast — see traceplane.buffer_span). Spans emitted before
    the runtime exists are dropped, same as the old cast path."""
    from ray_tpu._private import traceplane
    from ray_tpu._private.worker_context import try_runtime

    if try_runtime() is None:
        return
    traceplane.buffer_span(event)


@contextlib.contextmanager
def span(name: str, **attributes: Any):
    """Record a named span:

        with tracing.span("preprocess", rows=123):
            ...

    Nesting is tracked per-thread; child spans carry their parent's name
    in ``parent`` so trace viewers can reconstruct the hierarchy. When a
    request-trace context is ambient (inside a traced task, or under an
    outer span that minted one) the span also joins that causal trace —
    it gets its own span id, parents to the enclosing span, and any
    ``.remote()`` submitted inside the block chains under it."""
    from ray_tpu._private import traceplane, worker_context

    parent = getattr(_local, "span_name", None)
    _local.span_name = name
    start = time.time()
    error = None
    # Request-trace linkage: take a span id in the ambient trace (if
    # any) and make this span the parent for the duration of the block.
    tc = worker_context.get_trace_context()
    span_id = traceplane.new_span_id() if tc else None
    tc_token = (worker_context.push_trace_context((tc[0], span_id, tc[2]))
                if tc else None)
    # Optional OpenTelemetry bridge.
    otel_cm = None
    try:
        from opentelemetry import trace as otel_trace  # type: ignore

        otel_cm = otel_trace.get_tracer("ray_tpu").start_as_current_span(name)
        otel_cm.__enter__()
    except Exception:
        otel_cm = None
    try:
        yield
    except BaseException as e:
        error = repr(e)
        raise
    finally:
        if otel_cm is not None:
            try:
                otel_cm.__exit__(None, None, None)
            except Exception:
                pass
        _local.span_name = parent
        if tc_token is not None:
            worker_context.pop_trace_context(tc_token)
        end = time.time()
        ctx = worker_context.get_task_context()
        # Worker/actor identity from the runtime context (a worker
        # runtime's client id IS its worker id) — without it user spans
        # emitted from tasks carried "worker_id": None and refused to
        # group with their task's lifecycle spans in the timeline.
        rt = worker_context.try_runtime()
        worker_id = (rt.client_id if rt is not None
                     and rt.client_type == "worker" else None)
        ev = {
            "event": "span",
            "name": name,
            "parent": parent,
            "task_id": getattr(ctx, "task_id", None),
            "worker_id": worker_id,
            "actor_id": getattr(ctx, "actor_id", None),
            "node_id": (getattr(ctx, "node_id", None)
                        or (rt.node_id if rt is not None else None)),
            "pid": os.getpid(),
            "start": start,
            "end": end,
            "failed": error is not None,
            "attributes": {**attributes, **({"error": error} if error else {})},
        }
        if tc and int(tc[2] or 0):
            ev["trace_id"] = tc[0]
            ev["span_id"] = span_id
            ev["parent_span_id"] = tc[1]
        _emit(ev)


def trace(fn=None, *, name: str | None = None):
    """Decorator form of span()."""
    def wrap(f):
        @functools.wraps(f)
        def inner(*args, **kwargs):
            with span(name or f.__qualname__):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap


# ---------------------------------------------- trace-correlated logs


class TraceIdFilter(logging.Filter):
    """Stamps ``[trace=<id>]`` into log records made while a traced task
    (or span) executes. A filter rather than a formatter so it composes
    with whatever format the handler already has — worker stderr is
    plain-formatted into ``{worker_id}.log`` and the prefix makes those
    lines greppable by ``ray-tpu logs --trace <id>``."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from ray_tpu._private import worker_context

            tc = worker_context.get_trace_context()
            if tc and not str(record.msg).startswith("[trace="):
                record.msg = f"[trace={tc[0]}] {record.msg}"
        except Exception:
            pass
        return True


def install_log_correlation() -> None:
    """Attach the trace-id filter where every record passes: the root
    logger's handlers (logger-level filters don't see records propagated
    from child loggers; handler-level ones do) plus the lastResort
    handler that catches unconfigured logging. Idempotent. Installed by
    worker main() when the trace plane is enabled; drivers embedding a
    serve proxy can call it too."""
    filt = TraceIdFilter()
    root = logging.getLogger()
    targets = [root, *root.handlers]
    if logging.lastResort is not None:
        targets.append(logging.lastResort)
    for t in targets:
        if not any(isinstance(f, TraceIdFilter) for f in t.filters):
            t.addFilter(filt)
