"""User-level tracing spans, merged into the cluster timeline.

Counterpart of the reference's tracing/profiling helpers
(reference: python/ray/util/tracing/tracing_helper.py:34-127 — opt-in
OpenTelemetry spans around task/actor calls — and _private/profiling.py:84
``profile`` events buffered through TaskEventBuffer into `ray timeline`).
Here spans are lightweight dicts cast to the head's task-event buffer, so
``ray_tpu.util.state.timeline()`` renders user spans alongside task
execution spans in the same Chrome trace. OpenTelemetry export is
attached on top when the package is importable.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from typing import Any

_local = threading.local()


def _emit(event: dict) -> None:
    from ray_tpu._private.worker_context import try_runtime

    rt = try_runtime()
    if rt is None:
        return
    try:
        rt.conn.cast("task_events", {"events": [event]})
    except Exception:
        pass


@contextlib.contextmanager
def span(name: str, **attributes: Any):
    """Record a named span:

        with tracing.span("preprocess", rows=123):
            ...

    Nesting is tracked per-thread; child spans carry their parent's name
    in ``parent`` so trace viewers can reconstruct the hierarchy."""
    parent = getattr(_local, "span_name", None)
    _local.span_name = name
    start = time.time()
    error = None
    # Optional OpenTelemetry bridge.
    otel_cm = None
    try:
        from opentelemetry import trace as otel_trace  # type: ignore

        otel_cm = otel_trace.get_tracer("ray_tpu").start_as_current_span(name)
        otel_cm.__enter__()
    except Exception:
        otel_cm = None
    try:
        yield
    except BaseException as e:
        error = repr(e)
        raise
    finally:
        if otel_cm is not None:
            try:
                otel_cm.__exit__(None, None, None)
            except Exception:
                pass
        _local.span_name = parent
        end = time.time()
        from ray_tpu._private import worker_context

        ctx = worker_context.get_task_context()
        # Worker/actor identity from the runtime context (a worker
        # runtime's client id IS its worker id) — without it user spans
        # emitted from tasks carried "worker_id": None and refused to
        # group with their task's lifecycle spans in the timeline.
        rt = worker_context.try_runtime()
        worker_id = (rt.client_id if rt is not None
                     and rt.client_type == "worker" else None)
        _emit({
            "event": "span",
            "name": name,
            "parent": parent,
            "task_id": getattr(ctx, "task_id", None),
            "worker_id": worker_id,
            "actor_id": getattr(ctx, "actor_id", None),
            "node_id": (getattr(ctx, "node_id", None)
                        or (rt.node_id if rt is not None else None)),
            "pid": os.getpid(),
            "start": start,
            "end": end,
            "failed": error is not None,
            "attributes": {**attributes, **({"error": error} if error else {})},
        })


def trace(fn=None, *, name: str | None = None):
    """Decorator form of span()."""
    def wrap(f):
        @functools.wraps(f)
        def inner(*args, **kwargs):
            with span(name or f.__qualname__):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap
