"""Durable workflows: checkpointed DAG execution with resume.

Counterpart of the reference's python/ray/workflow (api.py `workflow.run`
/ `run_async` / `resume` / `get_output` / `list_all`; step results
durably logged to storage so a crashed driver resumes where it left off;
dynamic workflows via `workflow.continuation`). Implementation here:

  - A DAG built with ``fn.bind(...)`` is *frozen* into a JSON-safe spec
    (functions cloudpickled, upstream edges by step id) and persisted, so
    resume does not need the original driver process.
  - Steps execute as cluster tasks (``fn.remote``) level-by-level
    (independent steps run in parallel); each result is written to
    storage before any dependent is submitted — the workflow is
    re-entrant at step granularity.
  - A step returning ``workflow.continuation(dag)`` splices the new
    sub-DAG in durably (dynamic workflows).

Storage layout (filesystem; base dir from RAY_TPU_WORKFLOW_DIR):
    {base}/{workflow_id}/dag.pkl         frozen spec (grows with continuations)
    {base}/{workflow_id}/meta.json       status: RUNNING | SUCCESS | FAILED
    {base}/{workflow_id}/steps/{sid}.pkl durable step results
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import Future
from typing import Any

import cloudpickle

from ray_tpu._private.serialization import dumps_scoped

import ray_tpu
from ray_tpu.dag.nodes import DAGNode, FunctionNode

__all__ = [
    "run", "run_async", "resume", "resume_async", "get_output",
    "get_status", "list_all", "delete", "continuation", "Continuation",
    "EventListener", "KVEventListener", "TimerListener", "trigger_event",
    "wait_for_event", "sleep",
]


def _base_dir() -> str:
    d = os.environ.get("RAY_TPU_WORKFLOW_DIR", "/tmp/ray_tpu/workflows")
    os.makedirs(d, exist_ok=True)
    return d


class Continuation:
    """Marker returned by a step to splice a sub-DAG into the workflow."""

    def __init__(self, dag: DAGNode):
        if not isinstance(dag, DAGNode):
            raise TypeError("continuation() takes a DAG node (fn.bind(...))")
        self.dag = dag

    def __reduce__(self):
        # Travels worker→driver as its frozen spec (DAGNodes themselves
        # are not serializable).
        return (_rebuild_continuation, (_freeze(self.dag),))


def _rebuild_continuation(spec):
    c = Continuation.__new__(Continuation)
    c.dag = None
    c.spec = spec
    return c


def continuation(dag: DAGNode) -> Continuation:
    """Dynamic workflows (reference: workflow.continuation)."""
    return Continuation(dag)


# -- freezing ---------------------------------------------------------------

def _freeze(root: DAGNode) -> dict:
    """DAG → durable spec {steps: {sid: {fn, args, kwargs, deps}}, output}.

    Only FunctionNode graphs are durable (actor methods hold process
    state that cannot be replayed from storage — same restriction as the
    reference's workflow steps being task-based).
    """
    steps: dict[str, dict] = {}
    ids: dict[str, str] = {}  # node uuid -> step id
    counter = [0]

    def visit(node: DAGNode) -> str:
        if node._uuid in ids:
            return ids[node._uuid]
        if not isinstance(node, FunctionNode):
            raise TypeError(
                f"workflows support function steps only (fn.bind); got "
                f"{type(node).__name__}"
            )
        for up in node._upstream():
            visit(up)
        fn = node._remote_fn
        sid = f"{counter[0]:04d}_{getattr(fn, '__name__', 'step')}"
        counter[0] += 1
        ids[node._uuid] = sid

        def enc(v):
            if isinstance(v, DAGNode):
                return {"__step__": ids[v._uuid]}
            return {"__val__": dumps_scoped(v).hex()}

        steps[sid] = {
            "fn": dumps_scoped(fn._fn).hex(),
            "opts": fn._opts,
            "args": [enc(a) for a in node._bound_args],
            "kwargs": {k: enc(v) for k, v in node._bound_kwargs.items()},
            "deps": sorted({ids[u._uuid] for u in node._upstream()}),
        }
        return sid

    out = visit(root)
    return {"steps": steps, "output": out}


# -- storage ----------------------------------------------------------------

class _Store:
    def __init__(self, workflow_id: str):
        self.dir = os.path.join(_base_dir(), workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)

    def save_spec(self, spec: dict) -> None:
        _atomic_write(os.path.join(self.dir, "dag.pkl"),
                      dumps_scoped(spec))

    def load_spec(self) -> dict:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return cloudpickle.loads(f.read())

    def save_meta(self, **kw) -> None:
        meta = self.load_meta()
        meta.update(kw)
        _atomic_write(os.path.join(self.dir, "meta.json"),
                      json.dumps(meta).encode())

    def load_meta(self) -> dict:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    def step_path(self, sid: str) -> str:
        return os.path.join(self.steps_dir, f"{sid}.pkl")

    def has_step(self, sid: str) -> bool:
        return os.path.exists(self.step_path(sid))

    def save_step(self, sid: str, value: Any) -> None:
        _atomic_write(self.step_path(sid), dumps_scoped(value))

    def load_step(self, sid: str) -> Any:
        with open(self.step_path(sid), "rb") as f:
            return cloudpickle.loads(f.read())


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


# -- execution --------------------------------------------------------------

def _reachable(steps: dict, target: str) -> set[str]:
    needed: set[str] = set()
    frontier = [target]
    while frontier:
        s = frontier.pop()
        if s in needed:
            continue
        needed.add(s)
        frontier.extend(steps[s]["deps"])
    return needed


def _execute(spec: dict, store: _Store) -> Any:
    """Run the steps reachable from spec['output'] that are not yet in
    storage, deps-first, parallel within a level. Returns the output
    step's value.

    Only the reachable subgraph runs here: spliced continuation steps
    are NOT dependency-linked to the outer output and execute exclusively
    through their parent step's continuation marker (below) — running
    them at this level too would double-execute them on resume."""
    import ray_tpu.remote_function as rf

    steps = spec["steps"]
    done: dict[str, Any] = {}
    pending = _reachable(steps, spec["output"])

    for sid in list(pending):
        if store.has_step(sid):
            value = store.load_step(sid)
            if isinstance(value, dict) and "__continuation__" in value:
                # The step durably resolved to a continuation before the
                # crash: finish (or load) its subgraph instead of
                # re-running the step.
                value = _execute(
                    {"steps": steps, "output": value["__continuation__"]},
                    store,
                )
                store.save_step(sid, value)
            done[sid] = value
            pending.discard(sid)

    while pending:
        ready = [s for s in pending
                 if all(d in done for d in steps[s]["deps"])]
        if not ready:
            raise RuntimeError(
                f"workflow deadlock: pending={sorted(pending)} with no "
                f"satisfiable dependencies"
            )
        refs = {}
        for sid in ready:
            st = steps[sid]
            fn = cloudpickle.loads(bytes.fromhex(st["fn"]))

            def dec(v):
                if "__step__" in v:
                    return done[v["__step__"]]
                return cloudpickle.loads(bytes.fromhex(v["__val__"]))

            args = [dec(a) for a in st["args"]]
            kwargs = {k: dec(v) for k, v in st["kwargs"].items()}
            remote = rf.RemoteFunction(fn, **(st.get("opts") or {}))
            refs[sid] = remote.remote(*args, **kwargs)
        first_error: BaseException | None = None
        for sid, ref in refs.items():
            # Persist every successful sibling even when another step in
            # the same level fails — resume must never replay a step that
            # already ran (side effects would double-fire).
            try:
                value = ray_tpu.get(ref)
                if isinstance(value, Continuation):
                    value = _splice_continuation(spec, store, sid, value)
            except BaseException as e:  # noqa: BLE001
                first_error = first_error or e
                continue
            store.save_step(sid, value)
            done[sid] = value
            pending.discard(sid)
        if first_error is not None:
            raise first_error
    return done[spec["output"]]


def _splice_continuation(spec: dict, store: _Store, sid: str,
                         cont: Continuation) -> Any:
    """Execute a continuation sub-DAG durably; its output becomes the
    step's value. The merged spec is persisted so resume replays it."""
    sub = getattr(cont, "spec", None) or _freeze(cont.dag)
    prefixed = {}
    for ssid, st in sub["steps"].items():
        st = dict(st)
        st["deps"] = [f"{sid}.{d}" for d in st["deps"]]
        st["args"] = [_prefix_ref(a, sid) for a in st["args"]]
        st["kwargs"] = {k: _prefix_ref(v, sid) for k, v in st["kwargs"].items()}
        prefixed[f"{sid}.{ssid}"] = st
    spec["steps"].update(prefixed)
    # Persist the FULL merged graph, not the (possibly truncated) spec
    # this nested _execute is running — a crash between here and the
    # final save must leave dag.pkl resumable to the real output.
    full = store.load_spec()
    full["steps"].update(spec["steps"])
    store.save_spec(full)
    # Durably mark the step as resolved-to-a-continuation BEFORE running
    # the subgraph: a crash mid-subgraph then resumes INTO the subgraph
    # instead of re-running this step (whose side effects already fired).
    target = f"{sid}.{sub['output']}"
    store.save_step(sid, {"__continuation__": target})
    return _execute({"steps": spec["steps"], "output": target}, store)


def _prefix_ref(v: dict, prefix: str) -> dict:
    if "__step__" in v:
        return {"__step__": f"{prefix}.{v['__step__']}"}
    return v


# -- public API -------------------------------------------------------------

def run(dag: DAGNode, *, workflow_id: str | None = None) -> Any:
    """Execute a DAG durably; blocks until the result is available.

    Re-running a SUCCESS id returns the stored result. Re-running a
    FAILED/RUNNING id with the *same* DAG resumes it; with a *different*
    DAG it raises (stale step results from the old graph must not leak
    into the new one — delete() or pick a fresh id)."""
    workflow_id = workflow_id or f"workflow-{_uuid_hex()}"
    store = _Store(workflow_id)
    meta = store.load_meta()
    spec = _freeze(dag)
    fp = _fingerprint(spec)
    # Fingerprint check FIRST: a SUCCESS entry for a different DAG must
    # raise, not silently return the other DAG's output.
    if meta and meta.get("fingerprint") not in (None, fp):
        raise ValueError(
            f"workflow id {workflow_id!r} already exists with a different "
            f"DAG (status={meta.get('status')}); workflow.delete() it or "
            f"use a new id (same-DAG reruns resume; workflow.resume() "
            f"skips this check)"
        )
    if meta.get("status") == "SUCCESS":
        return store.load_step(meta["output"])
    if meta.get("fingerprint") == fp:
        # Same-DAG rerun of a FAILED/RUNNING workflow: resume from the
        # STORED spec — it may contain continuation splices the freshly
        # frozen dag doesn't; overwriting it would orphan stored
        # continuation markers (their targets live only in the merged
        # graph).
        spec = store.load_spec()
    else:
        store.save_spec(spec)
    store.save_meta(status="RUNNING", output=spec["output"],
                    fingerprint=fp, created_at=time.time())
    return _finish(store, spec)


def _fingerprint(spec: dict) -> str:
    import hashlib

    # Hash graph structure + bound argument values but NOT the function
    # bytecode: cloudpickle bytes are not guaranteed stable across driver
    # restarts, and a re-run after a code fix SHOULD resume (same
    # semantics as resume()). Changed args/structure are the hazard.
    # Caveat: args whose pickling is order-unstable (sets under a new
    # PYTHONHASHSEED) can fingerprint differently across processes —
    # resume(workflow_id) bypasses this check for exactly that case.
    h = hashlib.sha256()
    for sid in sorted(spec["steps"]):
        st = spec["steps"][sid]
        h.update(sid.encode())
        for a in st["args"]:
            h.update(json.dumps(a, sort_keys=True).encode())
        for k in sorted(st["kwargs"]):
            h.update(k.encode())
            h.update(json.dumps(st["kwargs"][k], sort_keys=True).encode())
    return h.hexdigest()[:32]


def _finish(store: _Store, spec: dict) -> Any:
    try:
        result = _execute(spec, store)
    except Exception as e:  # noqa: BLE001
        store.save_meta(status="FAILED", error=repr(e))
        raise
    store.save_meta(status="SUCCESS", output=spec["output"])
    return result


def _uuid_hex() -> str:
    import uuid

    return uuid.uuid4().hex[:16]


def run_async(dag: DAGNode, *, workflow_id: str | None = None) -> Future:
    workflow_id = workflow_id or f"workflow-{_uuid_hex()}"
    fut: Future = Future()

    def target():
        try:
            fut.set_result(run(dag, workflow_id=workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    t = threading.Thread(target=target, daemon=True,
                         name=f"workflow-{workflow_id}")
    t.start()
    fut.workflow_id = workflow_id  # type: ignore[attr-defined]
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a FAILED/RUNNING workflow from its durable state: completed
    steps load from storage, the rest re-execute."""
    store = _Store(workflow_id)
    meta = store.load_meta()
    if not meta:
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    spec = store.load_spec()
    store.save_meta(status="RUNNING")
    return _finish(store, spec)


def resume_async(workflow_id: str) -> Future:
    fut: Future = Future()

    def target():
        try:
            fut.set_result(resume(workflow_id))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=target, daemon=True).start()
    return fut


def get_output(workflow_id: str) -> Any:
    store = _Store(workflow_id)
    meta = store.load_meta()
    if meta.get("status") != "SUCCESS":
        raise ValueError(
            f"workflow {workflow_id!r} status={meta.get('status')}; "
            f"output only available after SUCCESS (use resume())"
        )
    return store.load_step(meta["output"])


def get_status(workflow_id: str) -> str | None:
    return _Store(workflow_id).load_meta().get("status")


def list_all(status_filter: str | None = None) -> list[tuple[str, str]]:
    out = []
    base = _base_dir()
    for wid in sorted(os.listdir(base)):
        meta_path = os.path.join(base, wid, "meta.json")
        if not os.path.exists(meta_path):
            continue
        with open(meta_path) as f:
            status = json.load(f).get("status", "UNKNOWN")
        if status_filter is None or status == status_filter:
            out.append((wid, status))
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(os.path.join(_base_dir(), workflow_id), ignore_errors=True)


# -- events (reference: python/ray/workflow/api.py wait_for_event +
#    workflow/event_listener.py EventListener; events are delivered
#    exactly-once because the receiving step's result is checkpointed) ----


class EventListener:
    """Poll-based event provider (reference: event_listener.py — the
    reference's is coroutine-based; polling maps better onto checkpointed
    task steps). Subclass and implement ``poll_for_event``."""

    def poll_for_event(self) -> Any:
        """Block until the event arrives; return its payload."""
        raise NotImplementedError


class KVEventListener(EventListener):
    """Default listener: waits for a key in the cluster KV (events are
    posted with ``workflow.trigger_event``)."""

    def __init__(self, event_key: str, poll_interval_s: float = 0.1,
                 timeout_s: float | None = None):
        self.event_key = event_key
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s

    def poll_for_event(self) -> Any:
        import time as _time

        from ray_tpu._private.worker_context import global_runtime
        from ray_tpu._private import serialization

        rt = global_runtime()
        deadline = (_time.time() + self.timeout_s) if self.timeout_s is not None else None
        while True:
            raw = rt.kv_get(self.event_key, ns="__wf_events__")
            if raw:
                # Consume-once: the receiving step checkpoints the payload,
                # so the KV copy is deleted — a later workflow reusing the
                # key waits for a FRESH event instead of reading a stale
                # one (and the namespace doesn't grow unboundedly).
                # kv_del is atomic head-side: with concurrent waiters on
                # one key, exactly the deleting winner delivers; losers
                # keep waiting for the next event.
                if rt.kv_del(self.event_key, ns="__wf_events__"):
                    return serialization.loads(raw)
            if deadline is not None and _time.time() > deadline:
                raise TimeoutError(
                    f"no event {self.event_key!r} within {self.timeout_s}s")
            _time.sleep(self.poll_interval_s)


def trigger_event(event_key: str, payload: Any = True) -> None:
    """Post an event for KVEventListener waiters (works from any driver
    or task in the cluster)."""
    from ray_tpu._private.worker_context import global_runtime
    from ray_tpu._private import serialization

    global_runtime().kv_put(event_key, serialization.dumps(payload),
                            ns="__wf_events__")


class TimerListener(EventListener):
    """Event at a wall-clock timestamp (reference: event_listener.py
    TimerListener)."""

    def __init__(self, timestamp: float):
        self.timestamp = float(timestamp)

    def poll_for_event(self) -> float:
        time.sleep(max(0.0, self.timestamp - time.time()))
        return self.timestamp


def sleep(duration: float) -> DAGNode:
    """A workflow step resolving ``duration`` seconds after it first
    runs (reference: workflow/api.py sleep). The deadline is computed in
    its own checkpointed step, so a crash/resume waits out the ORIGINAL
    deadline instead of restarting the clock."""
    import ray_tpu as _rt

    def _end_time(d):
        return time.time() + d

    return wait_for_event(TimerListener, _rt.remote(_end_time).bind(duration))


def _poll_listener(listener_cls, *args, **kwargs):
    return listener_cls(*args, **kwargs).poll_for_event()


def wait_for_event(listener_cls_or_key, *args, **kwargs) -> DAGNode:
    """A workflow step that completes when the event arrives (reference:
    workflow/api.py wait_for_event). Pass an EventListener subclass plus
    its constructor args, or just a string key for the KV listener:

        gate = workflow.wait_for_event("deploy-approved", timeout_s=60)
        dag = finalize.bind(gate)

    Exactly-once: after the event is first received, the step's
    checkpointed result replays on resume without re-waiting."""
    import ray_tpu as _rt

    if isinstance(listener_cls_or_key, str):
        return _rt.remote(_poll_listener).bind(
            KVEventListener, listener_cls_or_key, *args, **kwargs)
    if not (isinstance(listener_cls_or_key, type)
            and issubclass(listener_cls_or_key, EventListener)):
        raise TypeError(
            "wait_for_event takes an event key string or an EventListener "
            f"subclass, got {listener_cls_or_key!r}")
    return _rt.remote(_poll_listener).bind(listener_cls_or_key, *args, **kwargs)
