"""Wheel build hook: compile the native C++ components (src/ ->
ray_tpu/_native/*.so) before packaging, so wheels ship binaries built
from the checked-in sources rather than committed artifacts (which are
gitignored — ADVICE r3). Source dists carry src/ via MANIFEST.in and
rebuild on demand at first use (ray_tpu/_private/native_build.py)."""

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildPyWithNative(build_py):
    def run(self):
        import subprocess
        import os

        src = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
        if os.path.isdir(src):
            subprocess.run(["make", "-C", src, "-j4"], check=True)
        super().run()


setup(cmdclass={"build_py": BuildPyWithNative})
