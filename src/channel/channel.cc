// Mutable shared-memory ring channels for compiled-DAG actor pipelines.
//
// Counterpart of the reference's mutable-object channel machinery
// (reference: src/ray/core_worker/experimental_mutable_object_manager.h:44
// — WriteAcquire/WriteRelease + ReadAcquire/ReadRelease over a reusable
// plasma buffer; python/ray/experimental/channel/shared_memory_channel.py).
//
// One channel = one POSIX shm region holding a fixed header plus
// `num_slots` payload slots REUSED round-robin: no allocation, no
// object-store bookkeeping, no RPC on the per-message path. Single
// writer, fixed num_readers; each reader consumes every message exactly
// once, in order. Multiple slots let the producer run ahead, which
// amortizes context switches — decisive when producer and consumer
// share a core.
//
// Protocol (32-bit atomics in process-shared memory, futex-waitable):
//   writer (message s, slot = s % num_slots):
//     wait acks[slot] == num_readers   (slot s-num_slots fully consumed)
//     fill payload[slot], len[slot] = n
//     acks[slot] = 0, seq = s+1 (release), futex_wake(seq)
//   reader (cursor r, slot = r % num_slots):
//     wait (int32)(seq - r) > 0
//     use payload[slot] ... acks[slot] += 1 (release), futex_wake(acks[slot])
//
// Waiting spins briefly on multi-core (sub-microsecond handoff when the
// peer runs elsewhere), and parks on a futex immediately on single-core
// boxes (spinning would burn exactly the cycles the peer needs).
// close() wakes every word so teardown never deadlocks.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <linux/futex.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#define RTPU_PAUSE() _mm_pause()
#else
#define RTPU_PAUSE() ((void)0)
#endif

namespace {

constexpr uint64_t kMagic = 0x5254505543484134ULL;  // "RTPUCHA4"
constexpr size_t kHeaderSize = 512;
constexpr uint32_t kMaxSlots = 16;

int spin_budget() {
  static int budget = [] {
    long n = sysconf(_SC_NPROCESSORS_ONLN);
    return n > 1 ? 6000 : 1;
  }();
  return budget;
}

struct Header {
  uint64_t magic;
  uint64_t capacity;            // per-slot payload bytes
  uint32_t num_readers;
  uint32_t num_slots;
  std::atomic<uint32_t> seq;    // messages published (futex word)
  std::atomic<uint32_t> closed;
  std::atomic<uint32_t> attach; // live handles across all processes; the
                                // LAST detacher unlinks the shm name, so a
                                // creator GC'd early can't yank the region
                                // from readers still holding it
  std::atomic<uint32_t> acks[kMaxSlots];  // futex words
  std::atomic<uint64_t> len[kMaxSlots];
};
static_assert(sizeof(Header) <= kHeaderSize, "header grew past its slot");

struct Chan {
  Header* hdr = nullptr;
  uint8_t* payload = nullptr;   // num_slots * capacity
  size_t map_size = 0;
  std::string name;
  uint32_t cursor = 0;          // reader-side next message index
  int acquired_read_slot = -1;
  int acquired_write_slot = -1;
};

std::mutex g_lock;
std::vector<Chan*> g_chans;

int64_t put_handle(Chan* c) {
  std::lock_guard<std::mutex> g(g_lock);
  for (size_t i = 0; i < g_chans.size(); i++) {
    if (g_chans[i] == nullptr) {
      g_chans[i] = c;
      return static_cast<int64_t>(i);
    }
  }
  g_chans.push_back(c);
  return static_cast<int64_t>(g_chans.size() - 1);
}

Chan* get_handle(int64_t h) {
  std::lock_guard<std::mutex> g(g_lock);
  if (h < 0 || static_cast<size_t>(h) >= g_chans.size()) return nullptr;
  return g_chans[h];
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

int futex_wait(std::atomic<uint32_t>* word, uint32_t expected,
               double timeout_s) {
  struct timespec ts;
  ts.tv_sec = static_cast<time_t>(timeout_s);
  ts.tv_nsec = static_cast<long>((timeout_s - ts.tv_sec) * 1e9);
  return static_cast<int>(syscall(SYS_futex,
                                  reinterpret_cast<uint32_t*>(word),
                                  FUTEX_WAIT, expected, &ts, nullptr, 0));
}

void futex_wake_all(std::atomic<uint32_t>* word) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
}

// Wait until pred(load()) or closed/timeout. The futex word must change
// whenever pred can flip. Sets *status: 0 ok, -1 timeout, -2 closed.
template <typename P>
uint32_t wait_word(std::atomic<uint32_t>* word, P pred,
                   const std::atomic<uint32_t>& closed, double timeout_s,
                   int* status) {
  int spins = spin_budget();
  for (int i = 0; i < spins; i++) {
    uint32_t v = word->load(std::memory_order_acquire);
    if (pred(v)) { *status = 0; return v; }
    if (closed.load(std::memory_order_relaxed)) { *status = -2; return v; }
    RTPU_PAUSE();
  }
  const double deadline = now_s() + timeout_s;
  while (true) {
    uint32_t v = word->load(std::memory_order_acquire);
    if (pred(v)) { *status = 0; return v; }
    if (closed.load(std::memory_order_relaxed)) { *status = -2; return v; }
    double left = deadline - now_s();
    if (left <= 0) { *status = -1; return v; }
    // Bounded slice so a missed wake (peer raced between load and wait)
    // still re-checks promptly.
    futex_wait(word, v, left < 0.2 ? left : 0.2);
  }
}

int64_t open_impl(const char* name, uint64_t capacity, uint32_t num_readers,
                  uint32_t num_slots, bool create) {
  if (create && (num_slots == 0 || num_slots > kMaxSlots)) return -EINVAL;
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return -errno;
  size_t map_size;
  if (create) {
    map_size = kHeaderSize + capacity * num_slots;
    if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
      int e = errno;
      close(fd);
      shm_unlink(name);
      return -e;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(kHeaderSize)) {
      close(fd);
      return -EINVAL;
    }
    map_size = static_cast<size_t>(st.st_size);
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -errno;
  auto* hdr = reinterpret_cast<Header*>(mem);
  if (create) {
    hdr->capacity = capacity;
    hdr->num_readers = num_readers;
    hdr->num_slots = num_slots;
    hdr->seq.store(0, std::memory_order_relaxed);
    hdr->closed.store(0, std::memory_order_relaxed);
    hdr->attach.store(1, std::memory_order_relaxed);
    for (uint32_t i = 0; i < kMaxSlots; i++) {
      // Every slot starts fully acked: the first num_slots writes
      // proceed immediately.
      hdr->acks[i].store(num_readers, std::memory_order_relaxed);
      hdr->len[i].store(0, std::memory_order_relaxed);
    }
    hdr->magic = kMagic;  // last: openers validate it
  } else if (hdr->magic != kMagic) {
    munmap(mem, map_size);
    return -EINVAL;
  } else {
    hdr->attach.fetch_add(1, std::memory_order_acq_rel);
  }
  auto* c = new Chan();
  c->hdr = hdr;
  c->payload = reinterpret_cast<uint8_t*>(mem) + kHeaderSize;
  c->map_size = map_size;
  c->name = name;
  return put_handle(c);
}

}  // namespace

extern "C" {

int64_t rtpu_chan_create(const char* name, uint64_t capacity,
                         uint32_t num_readers, uint32_t num_slots) {
  return open_impl(name, capacity, num_readers, num_slots, true);
}

int64_t rtpu_chan_open(const char* name) {
  return open_impl(name, 0, 0, 1, false);
}

uint64_t rtpu_chan_capacity(int64_t h) {
  Chan* c = get_handle(h);
  return c ? c->hdr->capacity : 0;
}

// Wait until the next slot's previous occupant is fully consumed;
// returns the slot payload pointer for zero-copy serialization, or NULL
// (timeout/closed).
uint8_t* rtpu_chan_write_acquire(int64_t h, double timeout_s) {
  Chan* c = get_handle(h);
  if (!c || c->acquired_write_slot >= 0) return nullptr;
  Header* hd = c->hdr;
  uint32_t slot = hd->seq.load(std::memory_order_relaxed) % hd->num_slots;
  int status;
  uint32_t readers = hd->num_readers;
  wait_word(&hd->acks[slot], [readers](uint32_t v) { return v >= readers; },
            hd->closed, timeout_s, &status);
  if (status != 0) return nullptr;
  c->acquired_write_slot = static_cast<int>(slot);
  return c->payload + static_cast<size_t>(slot) * hd->capacity;
}

// Publish a message of `len` bytes written into the acquired slot.
int rtpu_chan_write_commit(int64_t h, uint64_t len) {
  Chan* c = get_handle(h);
  if (!c || c->acquired_write_slot < 0 || len > c->hdr->capacity) return -1;
  uint32_t slot = static_cast<uint32_t>(c->acquired_write_slot);
  c->acquired_write_slot = -1;
  c->hdr->len[slot].store(len, std::memory_order_relaxed);
  c->hdr->acks[slot].store(0, std::memory_order_relaxed);
  c->hdr->seq.fetch_add(1, std::memory_order_release);
  futex_wake_all(&c->hdr->seq);
  return 0;
}

// Abandon an acquired-but-uncommitted write slot (e.g. serialization into
// the mapped region raised mid-way). Nothing is published; the next
// write_acquire starts fresh on the same slot.
int rtpu_chan_write_abort(int64_t h) {
  Chan* c = get_handle(h);
  if (!c || c->acquired_write_slot < 0) return -1;
  c->acquired_write_slot = -1;
  return 0;
}

// Convenience: acquire + memcpy + commit.
int rtpu_chan_write(int64_t h, const uint8_t* buf, uint64_t len,
                    double timeout_s) {
  Chan* c = get_handle(h);
  if (!c || len > c->hdr->capacity) return -1;
  uint8_t* dst = rtpu_chan_write_acquire(h, timeout_s);
  if (!dst) return -2;
  memcpy(dst, buf, len);
  return rtpu_chan_write_commit(h, len);
}

// Wait for the next unseen message. On success returns its length and
// sets *out_ptr to the slot payload (valid until read_release). Returns
// -1 timeout, -2 closed, -3 bad handle / double acquire.
int64_t rtpu_chan_read_acquire(int64_t h, const uint8_t** out_ptr,
                               double timeout_s) {
  Chan* c = get_handle(h);
  if (!c || c->acquired_read_slot >= 0) return -3;
  Header* hd = c->hdr;
  uint32_t cur = c->cursor;
  int status;
  wait_word(&hd->seq,
            [cur](uint32_t v) {
              return static_cast<int32_t>(v - cur) > 0;  // wrap-safe
            },
            hd->closed, timeout_s, &status);
  if (status != 0) return status == -2 ? -2 : -1;
  uint32_t slot = cur % hd->num_slots;
  c->cursor = cur + 1;
  c->acquired_read_slot = static_cast<int>(slot);
  *out_ptr = c->payload + static_cast<size_t>(slot) * hd->capacity;
  return static_cast<int64_t>(hd->len[slot].load(std::memory_order_relaxed));
}

int rtpu_chan_read_release(int64_t h) {
  Chan* c = get_handle(h);
  if (!c || c->acquired_read_slot < 0) return -1;
  uint32_t slot = static_cast<uint32_t>(c->acquired_read_slot);
  c->acquired_read_slot = -1;
  c->hdr->acks[slot].fetch_add(1, std::memory_order_release);
  futex_wake_all(&c->hdr->acks[slot]);
  return 0;
}

// Mark closed (wakes all waiters with the closed error).
int rtpu_chan_close(int64_t h) {
  Chan* c = get_handle(h);
  if (!c) return -1;
  c->hdr->closed.store(1, std::memory_order_release);
  futex_wake_all(&c->hdr->seq);
  for (uint32_t i = 0; i < c->hdr->num_slots; i++) {
    futex_wake_all(&c->hdr->acks[i]);
  }
  return 0;
}

int rtpu_chan_is_closed(int64_t h) {
  Chan* c = get_handle(h);
  return (c && c->hdr->closed.load(std::memory_order_acquire)) ? 1 : 0;
}

// Detach this handle. The shm name is unlinked only when the LAST
// attached handle (across all processes) detaches — a creator handle
// GC'd while a reader still drains cannot yank the region (the old
// creator-unlinks rule did exactly that). `force_unlink` (=2) unlinks
// unconditionally.
int rtpu_chan_destroy(int64_t h, int force_unlink) {
  Chan* c = get_handle(h);
  if (!c) return -1;
  {
    std::lock_guard<std::mutex> g(g_lock);
    g_chans[h] = nullptr;
  }
  uint32_t prev = c->hdr->attach.fetch_sub(1, std::memory_order_acq_rel);
  bool last = (prev <= 1);
  munmap(reinterpret_cast<void*>(c->hdr), c->map_size);
  // A crashed peer never decrements its attach count; compiled-DAG
  // teardown force-unlinks every channel name it created
  // (rtpu_chan_force_unlink) so those regions are reclaimed once the
  // surviving mappings close. Ad-hoc channels whose holders all crash
  // leak the name until reboot — standard POSIX shm semantics.
  if (last || force_unlink == 2) shm_unlink(c->name.c_str());
  delete c;
  return 0;
}

// Remove the shm NAME regardless of attach count (existing mappings
// stay valid; the memory is reclaimed when they unmap or die). Used by
// compiled-DAG teardown, which knows every reader has been woken by
// close() and no new opens are coming.
int rtpu_chan_force_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

}  // extern "C"
