// Minimal pickle writer/reader for the ray_tpu control-plane protocol.
//
// The wire frames carry pickled (kind, msg_id, body) tuples
// (ray_tpu/_private/rpc.py). A native client needs just enough pickle:
//   write: protocol 3 — None/bool/int/float/str/bytes/list/dict/tuple,
//          plus GLOBAL+NEWOBJ+BUILD for dataclass instances (TaskSpec).
//   read:  the opcodes CPython's default protocol (5) emits for plain
//          data (FRAME/MEMOIZE/SHORT_BINUNICODE/...), with a memo table.
// Anything outside that vocabulary raises — the replies this client
// consumes are dicts of scalars/containers by protocol design.
//
// Counterpart of the reference's cross-language serialization surface
// (reference: cpp/ frontend + java msgpack bridge); ours speaks the
// Python control plane natively so no interpreter is embedded.

#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace rtpu {

struct PVal;
using PList = std::vector<PVal>;
using PItems = std::vector<std::pair<PVal, PVal>>;

struct PVal {
  enum class Kind { None, Bool, Int, Float, Str, Bytes, List, Tuple, Dict,
                    Instance };
  Kind kind = Kind::None;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;        // Str (utf-8) or Bytes
  std::shared_ptr<PList> seq;    // List / Tuple
  std::shared_ptr<PItems> items; // Dict

  PVal() = default;
  static PVal none() { return PVal(); }
  static PVal boolean(bool v) { PVal p; p.kind = Kind::Bool; p.b = v; return p; }
  static PVal integer(int64_t v) { PVal p; p.kind = Kind::Int; p.i = v; return p; }
  static PVal real(double v) { PVal p; p.kind = Kind::Float; p.f = v; return p; }
  static PVal str(std::string v) { PVal p; p.kind = Kind::Str; p.s = std::move(v); return p; }
  static PVal bytes(std::string v) { PVal p; p.kind = Kind::Bytes; p.s = std::move(v); return p; }
  static PVal list(PList v = {}) { PVal p; p.kind = Kind::List; p.seq = std::make_shared<PList>(std::move(v)); return p; }
  static PVal tuple(PList v = {}) { PVal p; p.kind = Kind::Tuple; p.seq = std::make_shared<PList>(std::move(v)); return p; }
  static PVal dict(PItems v = {}) { PVal p; p.kind = Kind::Dict; p.items = std::make_shared<PItems>(std::move(v)); return p; }
  // A Python class instance: GLOBAL module\ncls + NEWOBJ() + BUILD state.
  // `s` holds "module\ncls"; `items` the state dict.
  static PVal instance(const std::string& module, const std::string& cls,
                       PItems state) {
    PVal p; p.kind = Kind::Instance; p.s = module + "\n" + cls;
    p.items = std::make_shared<PItems>(std::move(state));
    return p;
  }

  bool is_none() const { return kind == Kind::None; }
  // Dict lookup by string key (linear; bodies are small).
  const PVal* find(const std::string& key) const {
    if (kind != Kind::Dict) return nullptr;
    for (const auto& kv : *items)
      if (kv.first.kind == Kind::Str && kv.first.s == key) return &kv.second;
    return nullptr;
  }
  const PVal& at(const std::string& key) const {
    const PVal* v = find(key);
    if (!v) throw std::runtime_error("minipickle: missing key " + key);
    return *v;
  }
};

// ---------------------------------------------------------------- writer

class Pickler {
 public:
  // One complete pickle stream for `v`.
  static std::string dumps(const PVal& v) {
    Pickler p;
    p.out_ += "\x80\x03";  // PROTO 3
    p.write(v);
    p.out_ += '.';
    return std::move(p.out_);
  }

  void write(const PVal& v) {
    if (v.kind == PVal::Kind::Instance) {
      out_ += 'c';
      out_ += v.s;   // "module\ncls"
      out_ += '\n';
      out_ += ')';   // EMPTY_TUPLE (no __new__ args)
      out_ += '\x81';  // NEWOBJ
      write_dict_items(*v.items);
      out_ += 'b';   // BUILD (sets __dict__)
      return;
    }
    switch (v.kind) {
      case PVal::Kind::None: out_ += 'N'; break;
      case PVal::Kind::Bool: out_ += (v.b ? '\x88' : '\x89'); break;
      case PVal::Kind::Int: write_int(v.i); break;
      case PVal::Kind::Float: write_float(v.f); break;
      case PVal::Kind::Str: {
        out_ += 'X';
        put_le32(static_cast<uint32_t>(v.s.size()));
        out_ += v.s;
        break;
      }
      case PVal::Kind::Bytes: {
        out_ += 'B';  // BINBYTES (proto 3)
        put_le32(static_cast<uint32_t>(v.s.size()));
        out_ += v.s;
        break;
      }
      case PVal::Kind::List: {
        out_ += ']';
        if (!v.seq->empty()) {
          out_ += '(';
          for (const auto& e : *v.seq) write(e);
          out_ += 'e';  // APPENDS
        }
        break;
      }
      case PVal::Kind::Tuple: {
        const auto& seq = *v.seq;
        if (seq.empty()) { out_ += ')'; break; }
        if (seq.size() <= 3) {
          for (const auto& e : seq) write(e);
          out_ += static_cast<char>(seq.size() == 1   ? '\x85'
                                    : seq.size() == 2 ? '\x86'
                                                      : '\x87');
        } else {
          out_ += '(';
          for (const auto& e : seq) write(e);
          out_ += 't';
        }
        break;
      }
      case PVal::Kind::Dict:
        write_dict_items(*v.items);
        break;
      case PVal::Kind::Instance:
        break;  // handled above
    }
  }

 private:
  void write_dict_items(const PItems& items) {
    out_ += '}';
    if (!items.empty()) {
      out_ += '(';
      for (const auto& kv : items) { write(kv.first); write(kv.second); }
      out_ += 'u';  // SETITEMS
    }
  }
  void put_le32(uint32_t n) {
    char b[4];
    std::memcpy(b, &n, 4);  // little-endian hosts only (x86/arm64)
    out_.append(b, 4);
  }
  void write_int(int64_t n) {
    if (n >= 0 && n < 256) {
      out_ += 'K';
      out_ += static_cast<char>(n);
    } else if (n >= INT32_MIN && n <= INT32_MAX) {
      out_ += 'J';
      int32_t v = static_cast<int32_t>(n);
      char b[4];
      std::memcpy(b, &v, 4);
      out_.append(b, 4);
    } else {
      out_ += '\x8a';  // LONG1
      out_ += static_cast<char>(8);
      char b[8];
      std::memcpy(b, &n, 8);
      out_.append(b, 8);
    }
  }
  void write_float(double d) {
    out_ += 'G';  // BINFLOAT: big-endian IEEE 754
    uint64_t bits;
    std::memcpy(&bits, &d, 8);
    for (int i = 7; i >= 0; --i)
      out_ += static_cast<char>((bits >> (8 * i)) & 0xFF);
  }
  std::string out_;
};

// ---------------------------------------------------------------- reader

class Unpickler {
 public:
  static PVal loads(const std::string& data) {
    Unpickler u(data);
    return u.run();
  }

 private:
  explicit Unpickler(const std::string& d) : d_(d) {}

  const std::string& d_;
  size_t pos_ = 0;
  std::vector<PVal> stack_;
  std::vector<size_t> marks_;
  std::vector<PVal> memo_;

  uint8_t u8() { need(1); return static_cast<uint8_t>(d_[pos_++]); }
  uint16_t u16() { need(2); uint16_t v; std::memcpy(&v, d_.data() + pos_, 2); pos_ += 2; return v; }
  uint32_t u32() { need(4); uint32_t v; std::memcpy(&v, d_.data() + pos_, 4); pos_ += 4; return v; }
  uint64_t u64() { need(8); uint64_t v; std::memcpy(&v, d_.data() + pos_, 8); pos_ += 8; return v; }
  std::string take(size_t n) { need(n); std::string s = d_.substr(pos_, n); pos_ += n; return s; }
  void need(size_t n) {
    if (pos_ + n > d_.size()) throw std::runtime_error("minipickle: truncated");
  }
  PVal pop() {
    if (stack_.empty()) throw std::runtime_error("minipickle: stack underflow");
    PVal v = std::move(stack_.back());
    stack_.pop_back();
    return v;
  }

  PVal run() {
    while (pos_ < d_.size()) {
      uint8_t op = u8();
      switch (op) {
        case 0x80: u8(); break;                    // PROTO n
        case 0x95: u64(); break;                   // FRAME len
        case '.': return pop();                    // STOP
        case 'N': stack_.push_back(PVal::none()); break;
        case 0x88: stack_.push_back(PVal::boolean(true)); break;
        case 0x89: stack_.push_back(PVal::boolean(false)); break;
        case 'K': stack_.push_back(PVal::integer(u8())); break;
        case 'M': stack_.push_back(PVal::integer(u16())); break;
        case 'J': {
          uint32_t v = u32();
          int32_t sv;
          std::memcpy(&sv, &v, 4);
          stack_.push_back(PVal::integer(sv));
          break;
        }
        case 0x8a: {  // LONG1
          uint8_t n = u8();
          if (n > 8) throw std::runtime_error("minipickle: LONG1 > 8 bytes");
          std::string raw = take(n);
          int64_t v = 0;
          if (n) {
            uint64_t uv = 0;
            std::memcpy(&uv, raw.data(), n);
            // sign-extend from byte n
            if (n < 8 && (raw[n - 1] & 0x80)) uv |= ~0ULL << (8 * n);
            std::memcpy(&v, &uv, 8);
          }
          stack_.push_back(PVal::integer(v));
          break;
        }
        case 'G': {  // BINFLOAT big-endian
          std::string raw = take(8);
          uint64_t bits = 0;
          for (int i = 0; i < 8; ++i)
            bits = (bits << 8) | static_cast<uint8_t>(raw[i]);
          double dv;
          std::memcpy(&dv, &bits, 8);
          stack_.push_back(PVal::real(dv));
          break;
        }
        case 0x8c: { size_t n = u8(); stack_.push_back(PVal::str(take(n))); break; }   // SHORT_BINUNICODE
        case 'X': { size_t n = u32(); stack_.push_back(PVal::str(take(n))); break; }   // BINUNICODE
        case 0x8d: { size_t n = u64(); stack_.push_back(PVal::str(take(n))); break; }  // BINUNICODE8
        case 'C': { size_t n = u8(); stack_.push_back(PVal::bytes(take(n))); break; }  // SHORT_BINBYTES
        case 'B': { size_t n = u32(); stack_.push_back(PVal::bytes(take(n))); break; } // BINBYTES
        case 0x8e: { size_t n = u64(); stack_.push_back(PVal::bytes(take(n))); break; }// BINBYTES8
        case 0x94: memo_.push_back(stack_.back()); break;                              // MEMOIZE
        case 'q': { u8(); memo_.push_back(stack_.back()); break; }                     // BINPUT
        case 'r': { u32(); memo_.push_back(stack_.back()); break; }                    // LONG_BINPUT
        case 'h': { stack_.push_back(memo_at(u8())); break; }                          // BINGET
        case 'j': { stack_.push_back(memo_at(u32())); break; }                         // LONG_BINGET
        case '(': marks_.push_back(stack_.size()); break;                              // MARK
        case ')': stack_.push_back(PVal::tuple()); break;
        case 0x85: { PVal a = pop(); stack_.push_back(PVal::tuple({std::move(a)})); break; }
        case 0x86: { PVal b2 = pop(), a = pop(); stack_.push_back(PVal::tuple({std::move(a), std::move(b2)})); break; }
        case 0x87: { PVal c = pop(), b2 = pop(), a = pop(); stack_.push_back(PVal::tuple({std::move(a), std::move(b2), std::move(c)})); break; }
        case 't': { stack_.push_back(PVal::tuple(pop_to_mark())); break; }
        case ']': stack_.push_back(PVal::list()); break;
        case 'a': { PVal v = pop(); stack_.back().seq->push_back(std::move(v)); break; }  // APPEND
        case 'e': {  // APPENDS
          PList items = pop_to_mark();
          auto& target = *stack_.back().seq;
          for (auto& it : items) target.push_back(std::move(it));
          break;
        }
        case '}': stack_.push_back(PVal::dict()); break;
        case 's': {  // SETITEM
          PVal v = pop(), k = pop();
          stack_.back().items->emplace_back(std::move(k), std::move(v));
          break;
        }
        case 'u': {  // SETITEMS
          PList kv = pop_to_mark();
          auto& target = *stack_.back().items;
          for (size_t i = 0; i + 1 < kv.size(); i += 2)
            target.emplace_back(std::move(kv[i]), std::move(kv[i + 1]));
          break;
        }
        case 0x8f: stack_.push_back(PVal::list()); break;  // EMPTY_SET -> list
        case 0x90: {  // ADDITEMS (set)
          PList items = pop_to_mark();
          auto& target = *stack_.back().seq;
          for (auto& it : items) target.push_back(std::move(it));
          break;
        }
        default:
          throw std::runtime_error(
              "minipickle: unsupported opcode 0x" + hex2(op) + " at " +
              std::to_string(pos_ - 1));
      }
    }
    throw std::runtime_error("minipickle: no STOP");
  }

  PList pop_to_mark() {
    if (marks_.empty()) throw std::runtime_error("minipickle: no MARK");
    size_t m = marks_.back();
    marks_.pop_back();
    PList out(std::make_move_iterator(stack_.begin() + m),
              std::make_move_iterator(stack_.end()));
    stack_.resize(m);
    return out;
  }
  const PVal& memo_at(size_t i) {
    if (i >= memo_.size()) throw std::runtime_error("minipickle: bad memo ref");
    return memo_[i];
  }
  static std::string hex2(uint8_t v) {
    const char* h = "0123456789abcdef";
    return std::string(1, h[v >> 4]) + h[v & 0xF];
  }
};

}  // namespace rtpu
