// Native C++ client for the ray_tpu control plane.
//
// Speaks the framed-TCP protocol directly (no embedded interpreter; cf.
// cpp/src/api.cc which embeds CPython): register as a remote driver,
// put/get inline objects, submit tasks that invoke Python functions by
// import path ("path:module:attr" — the cross-language convention, see
// runtime.get_function), and free objects. Counterpart of the
// reference's native C++ frontend (reference: cpp/src/ray/runtime/,
// ~9k LoC over the C++ core worker; here the wire protocol IS the
// contract, so the client is ~500 lines).
//
// Build: make -C src  ->  ray_tpu/_native/rtpu_client_demo
// Demo:  rtpu_client_demo <host> <port>   (exercised by
//        tests/test_cpp_client.py against a live head)

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "minipickle.h"

namespace rtpu {

namespace {

std::string hex_id() {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  const char* h = "0123456789abcdef";
  std::string out(32, '0');
  for (int i = 0; i < 32; ++i) out[i] = h[rng() & 0xF];
  return out;
}

// Object wire format (ray_tpu/_private/serialization.py write_to):
// [MAGIC u32][hlen u64][header pickle][nbuf u64][(off,len) x nbuf][bufs]
constexpr uint32_t kMagic = 0x52545055;  // 'RTPU'
constexpr size_t kAlign = 64;

// ((args...), {kwargs}) — the worker's cloudpickle.loads consumes this.
std::string pack_args(const PList& args, const PItems& kwargs) {
  return Pickler::dumps(PVal::tuple({PVal::tuple(args), PVal::dict(kwargs)}));
}

std::string wrap_object(const PVal& value) {
  std::string header = Pickler::dumps(value);
  std::string out;
  size_t hlen = header.size();
  size_t index_pos = 12 + hlen;
  size_t total = (index_pos + 8 + kAlign - 1) & ~(kAlign - 1);
  out.resize(total, '\0');
  std::memcpy(&out[0], &kMagic, 4);
  uint64_t h64 = hlen;
  std::memcpy(&out[4], &h64, 8);
  std::memcpy(&out[12], header.data(), hlen);
  uint64_t nbuf = 0;
  std::memcpy(&out[index_pos], &nbuf, 8);
  return out;
}

PVal unwrap_object(const std::string& payload) {
  if (payload.size() < 12) throw std::runtime_error("object: truncated");
  uint32_t magic;
  std::memcpy(&magic, payload.data(), 4);
  if (magic != kMagic) throw std::runtime_error("object: bad magic");
  uint64_t hlen;
  std::memcpy(&hlen, payload.data() + 4, 8);
  if (12 + hlen > payload.size()) throw std::runtime_error("object: bad hlen");
  uint64_t nbuf = 0;
  if (12 + hlen + 8 <= payload.size())
    std::memcpy(&nbuf, payload.data() + 12 + hlen, 8);
  if (nbuf != 0)
    throw std::runtime_error(
        "object: out-of-band buffers (tensors) need the Python client");
  return Unpickler::loads(payload.substr(12, hlen));
}

}  // namespace

class RayTpuClient {
 public:
  RayTpuClient(const std::string& host, int port) {
    sock_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (sock_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    ::setsockopt(sock_, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    hostent* he = ::gethostbyname(host.c_str());
    if (!he) throw std::runtime_error("unknown host " + host);
    std::memcpy(&addr.sin_addr, he->h_addr, he->h_length);
    if (::connect(sock_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)))
      throw std::runtime_error("connect() failed");
    reader_ = std::thread([this] { read_loop(); });
    // Remote driver registration (can_shm=false: payloads ride inline).
    PVal reply = call("register", PVal::dict({
        {PVal::str("client_type"), PVal::str("driver")},
        {PVal::str("worker_id"), PVal::none()},
        {PVal::str("pid"), PVal::integer(::getpid())},
        {PVal::str("can_shm"), PVal::boolean(false)},
    }));
    client_id_ = reply.at("client_id").s;
  }

  ~RayTpuClient() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      closed_ = true;
      cv_.notify_all();
    }
    ::shutdown(sock_, SHUT_RDWR);
    ::close(sock_);
    if (reader_.joinable()) reader_.join();
  }

  const std::string& client_id() const { return client_id_; }

  // ---- objects ----

  std::string put(const PVal& value) {
    std::string oid = hex_id();
    call("put_inline", PVal::dict({
        {PVal::str("object_id"), PVal::str(oid)},
        {PVal::str("payload"), PVal::bytes(wrap_object(value))},
        {PVal::str("owner_id"), PVal::str(client_id_)},
        {PVal::str("is_error"), PVal::boolean(false)},
        {PVal::str("contained_ids"), PVal::list()},
    }));
    return oid;
  }

  PVal get(const std::string& object_id, double timeout_s = 30.0) {
    std::string waiter = "cwtr-" + hex_id().substr(0, 12);
    {
      std::unique_lock<std::mutex> lk(mu_);
      waiters_[waiter] = PVal();
      waiter_done_[waiter] = false;
    }
    cast("get_meta", PVal::dict({
        {PVal::str("waiter_id"), PVal::str(waiter)},
        {PVal::str("ids"), PVal::list({PVal::str(object_id)})},
    }));
    PVal body;
    {
      std::unique_lock<std::mutex> lk(mu_);
      bool ok = cv_.wait_for(lk, std::chrono::duration<double>(timeout_s),
                             [&] { return waiter_done_[waiter] || closed_; });
      bool lost = closed_;
      if (ok && !lost) body = waiters_[waiter];
      waiters_.erase(waiter);
      waiter_done_.erase(waiter);
      if (!ok) throw std::runtime_error("get timed out");
      if (lost) throw std::runtime_error("connection lost");
    }
    const PVal& meta = body.at("metas").at(object_id);
    const PList& m = *meta.seq;  // ("inline", payload, is_error)
    if (m.at(0).s != "inline")
      throw std::runtime_error("non-inline object (kind=" + m.at(0).s + ")");
    if (m.at(2).b) {
      // Stored errors are cloudpickled TaskError instances — outside
      // the mini-unpickler's vocabulary — so check the flag BEFORE
      // unwrapping and surface a typed failure.
      throw std::runtime_error("task failed (stored error object for " +
                               object_id.substr(0, 8) + ")");
    }
    return unwrap_object(m.at(1).s);
  }

  void free_object(const std::string& object_id) {
    cast("free_objects", PVal::dict({
        {PVal::str("ids"), PVal::list({PVal::str(object_id)})},
        {PVal::str("force"), PVal::boolean(false)},
    }));
  }

  // ---- tasks ----

  // Submit a Python function by import path; returns the result object id.
  std::string submit(const std::string& func_path, const PList& args,
                     const PItems& kwargs = {}, double num_cpus = 1.0) {
    std::string task_id = hex_id();
    std::string ret_id = hex_id();
    std::string packed = pack_args(args, kwargs);
    PVal spec = PVal::instance(
        "ray_tpu._private.task_spec", "TaskSpec", {
            {PVal::str("task_id"), PVal::str(task_id)},
            {PVal::str("name"), PVal::str(func_path)},
            {PVal::str("func_id"), PVal::str("path:" + func_path)},
            {PVal::str("args"), PVal::bytes(packed)},
            {PVal::str("deps"), PVal::list()},
            {PVal::str("return_ids"), PVal::list({PVal::str(ret_id)})},
            {PVal::str("resources"), PVal::dict({
                {PVal::str("CPU"), PVal::real(num_cpus)}})},
            {PVal::str("owner_id"), PVal::str(client_id_)},
            {PVal::str("max_retries"), PVal::integer(0)},
            {PVal::str("retries_used"), PVal::integer(0)},
            {PVal::str("streaming"), PVal::boolean(false)},
            {PVal::str("scheduling_strategy"), PVal::none()},
            {PVal::str("runtime_env"), PVal::none()},
            {PVal::str("actor_id"), PVal::none()},
            {PVal::str("actor_creation"), PVal::boolean(false)},
            {PVal::str("method_name"), PVal::str("")},
            {PVal::str("seq_no"), PVal::integer(0)},
            {PVal::str("concurrency_group"), PVal::none()},
            {PVal::str("borrowed_ids"), PVal::list()},
        });
    cast("submit_task", PVal::dict({{PVal::str("spec"), spec}}));
    return ret_id;
  }

  // ---- actors ----

  // Create a Python actor by class import path; methods are then
  // invoked with call_actor. Head-side registration is synchronous;
  // the instance itself constructs asynchronously (calls queue).
  std::string create_actor(const std::string& class_path, const PList& args,
                           const PItems& kwargs = {},
                           double num_cpus = 0.0) {
    std::string actor_id = "actor-" + hex_id().substr(0, 12);
    std::string packed = pack_args(args, kwargs);
    PVal spec = PVal::instance(
        "ray_tpu._private.task_spec", "ActorSpec", {
            {PVal::str("actor_id"), PVal::str(actor_id)},
            {PVal::str("name"), PVal::none()},
            {PVal::str("namespace"), PVal::str("default")},
            {PVal::str("cls_func_id"), PVal::str("path:" + class_path)},
            {PVal::str("init_args"), PVal::bytes(packed)},
            {PVal::str("deps"), PVal::list()},
            {PVal::str("resources"), PVal::dict({
                {PVal::str("CPU"), PVal::real(num_cpus)}})},
            {PVal::str("max_restarts"), PVal::integer(0)},
            {PVal::str("max_concurrency"), PVal::integer(0)},
            {PVal::str("owner_id"), PVal::str(client_id_)},
            {PVal::str("max_task_retries"), PVal::integer(0)},
            {PVal::str("scheduling_strategy"), PVal::none()},
            {PVal::str("runtime_env"), PVal::none()},
            {PVal::str("lifetime"), PVal::none()},
            {PVal::str("concurrency_groups"), PVal::none()},
            {PVal::str("borrowed_ids"), PVal::list()},
            {PVal::str("allow_out_of_order"), PVal::boolean(false)},
        });
    call("create_actor", PVal::dict({{PVal::str("spec"), spec}}));
    return actor_id;
  }

  // Invoke a method on a created actor; returns the result object id.
  std::string call_actor(const std::string& actor_id,
                         const std::string& method, const PList& args,
                         const PItems& kwargs = {}) {
    std::string task_id = "task-" + hex_id().substr(0, 12);
    std::string ret_id = hex_id();
    std::string packed = pack_args(args, kwargs);
    int64_t seq;
    {
      std::unique_lock<std::mutex> lk(mu_);
      seq = ++actor_seq_[actor_id];
    }
    PVal spec = PVal::instance(
        "ray_tpu._private.task_spec", "TaskSpec", {
            {PVal::str("task_id"), PVal::str(task_id)},
            {PVal::str("name"), PVal::str("actor." + method)},
            {PVal::str("func_id"), PVal::str("")},
            {PVal::str("args"), PVal::bytes(packed)},
            {PVal::str("deps"), PVal::list()},
            {PVal::str("return_ids"), PVal::list({PVal::str(ret_id)})},
            {PVal::str("resources"), PVal::dict()},
            {PVal::str("owner_id"), PVal::str(client_id_)},
            {PVal::str("max_retries"), PVal::integer(0)},
            {PVal::str("retries_used"), PVal::integer(0)},
            {PVal::str("streaming"), PVal::boolean(false)},
            {PVal::str("scheduling_strategy"), PVal::none()},
            {PVal::str("runtime_env"), PVal::none()},
            {PVal::str("actor_id"), PVal::str(actor_id)},
            {PVal::str("actor_creation"), PVal::boolean(false)},
            {PVal::str("method_name"), PVal::str(method)},
            {PVal::str("seq_no"), PVal::integer(seq)},
            {PVal::str("concurrency_group"), PVal::none()},
            {PVal::str("borrowed_ids"), PVal::list()},
        });
    cast("submit_actor_task", PVal::dict({{PVal::str("spec"), spec}}));
    return ret_id;
  }

  void kill_actor(const std::string& actor_id) {
    call("kill_actor", PVal::dict({
        {PVal::str("actor_id"), PVal::str(actor_id)},
        {PVal::str("no_restart"), PVal::boolean(true)},
    }));
  }

  // ---- kv ----

  PVal kv_get(const std::string& key, const std::string& ns = "") {
    PVal r = call("kv_get", PVal::dict({
        {PVal::str("ns"), PVal::str(ns)}, {PVal::str("key"), PVal::str(key)}}));
    return r.at("value");
  }

  // ---- rpc primitives ----

  PVal call(const std::string& kind, const PVal& body, double timeout_s = 30.0) {
    int64_t msg_id;
    {
      std::unique_lock<std::mutex> lk(mu_);
      msg_id = next_id_++;
      pending_[msg_id] = PVal();
      pending_done_[msg_id] = false;
    }
    send_msg(kind, PVal::integer(msg_id), body);
    std::unique_lock<std::mutex> lk(mu_);
    bool ok = cv_.wait_for(lk, std::chrono::duration<double>(timeout_s),
                           [&] { return pending_done_[msg_id] || closed_; });
    bool lost = closed_;
    PVal reply;
    if (ok && !lost) reply = pending_[msg_id];
    pending_.erase(msg_id);
    pending_done_.erase(msg_id);
    if (!ok) throw std::runtime_error("call " + kind + " timed out");
    if (lost) throw std::runtime_error("connection lost");
    if (reply.kind == PVal::Kind::Dict) {
      const PVal* err = reply.find("__rpc_error__");
      if (err) throw std::runtime_error("rpc error: " + err->s);
    }
    return reply;
  }

  void cast(const std::string& kind, const PVal& body) {
    send_msg(kind, PVal::none(), body);
  }

 private:
  void send_msg(const std::string& kind, const PVal& msg_id, const PVal& body) {
    std::string payload = Pickler::dumps(PVal::tuple({
        PVal::str(kind), msg_id, body}));
    uint32_t len = static_cast<uint32_t>(payload.size());
    std::string frame(4, '\0');
    std::memcpy(&frame[0], &len, 4);
    frame += payload;
    std::unique_lock<std::mutex> lk(wmu_);
    size_t off = 0;
    while (off < frame.size()) {
      ssize_t n = ::send(sock_, frame.data() + off, frame.size() - off, 0);
      if (n <= 0) throw std::runtime_error("send failed");
      off += static_cast<size_t>(n);
    }
  }

  bool recv_exact(char* buf, size_t n) {
    size_t off = 0;
    while (off < n) {
      ssize_t r = ::recv(sock_, buf + off, n - off, 0);
      if (r <= 0) return false;
      off += static_cast<size_t>(r);
    }
    return true;
  }

  void read_loop() {
    for (;;) {
      char hdr[4];
      if (!recv_exact(hdr, 4)) break;
      uint32_t len;
      std::memcpy(&len, hdr, 4);
      std::string payload(len, '\0');
      if (!recv_exact(&payload[0], len)) break;
      try {
        PVal msg = Unpickler::loads(payload);
        const PList& t = *msg.seq;  // (kind, msg_id, body)
        const std::string& kind = t.at(0).s;
        if (kind == "__reply__" || kind == "__error__") {
          int64_t mid = t.at(1).i;
          std::unique_lock<std::mutex> lk(mu_);
          auto it = pending_.find(mid);
          if (it != pending_.end()) {
            if (kind == "__error__") {
              // Error payload is the remote traceback STRING.
              it->second = PVal::dict({{PVal::str("__rpc_error__"),
                                        PVal::str(t.at(2).s)}});
            } else {
              it->second = t.at(2);
            }
            pending_done_[mid] = true;
            cv_.notify_all();
          }
        } else if (kind == "objects_ready") {
          const PVal& body = t.at(2);
          std::string wid = body.at("waiter_id").s;
          std::unique_lock<std::mutex> lk(mu_);
          auto it = waiters_.find(wid);
          if (it != waiters_.end()) {
            it->second = body;
            waiter_done_[wid] = true;
            cv_.notify_all();
          }
        }
        // other pushes (log records, pubsub) are ignored
      } catch (const std::exception& e) {
        std::fprintf(stderr, "rtpu-client: bad frame: %s\n", e.what());
      }
    }
    std::unique_lock<std::mutex> lk(mu_);
    closed_ = true;
    cv_.notify_all();
  }

  int sock_ = -1;
  std::thread reader_;
  std::string client_id_;
  std::mutex mu_, wmu_;
  std::condition_variable cv_;
  int64_t next_id_ = 1;  // the server's reply check is `if msg_id:` — 0
                         // reads as a cast and would never get a reply
  std::map<std::string, int64_t> actor_seq_;  // per-actor call ordering
  std::map<int64_t, PVal> pending_;
  std::map<int64_t, bool> pending_done_;
  std::map<std::string, PVal> waiters_;
  std::map<std::string, bool> waiter_done_;
  bool closed_ = false;
};

}  // namespace rtpu

// ---------------------------------------------------------------- demo

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <host> <port>\n", argv[0]);
    return 2;
  }
  try {
    rtpu::RayTpuClient client(argv[1], std::atoi(argv[2]));
    std::printf("registered: %s\n", client.client_id().c_str());

    // put/get roundtrip of a structured value
    rtpu::PVal v = rtpu::PVal::dict({
        {rtpu::PVal::str("nums"), rtpu::PVal::list({
            rtpu::PVal::integer(1), rtpu::PVal::integer(2),
            rtpu::PVal::integer(3)})},
        {rtpu::PVal::str("pi"), rtpu::PVal::real(3.25)},
        {rtpu::PVal::str("tag"), rtpu::PVal::str("native")},
    });
    std::string oid = client.put(v);
    rtpu::PVal back = client.get(oid);
    if (back.at("tag").s != "native" || back.at("nums").seq->size() != 3 ||
        back.at("pi").f != 3.25) {
      std::fprintf(stderr, "put/get mismatch\n");
      return 1;
    }
    std::printf("put/get ok: %s\n", oid.substr(0, 8).c_str());
    client.free_object(oid);

    // cross-language task: Python function by import path, with kwargs
    std::string rid = client.submit(
        "tests.cross_lang_helpers:add_scaled",
        {rtpu::PVal::integer(20), rtpu::PVal::integer(11)},
        {{rtpu::PVal::str("scale"), rtpu::PVal::integer(2)}});
    rtpu::PVal result = client.get(rid, 60.0);
    if (result.i != 62) {
      std::fprintf(stderr, "task result %lld != 62\n",
                   static_cast<long long>(result.i));
      return 1;
    }
    std::printf("task ok: add_scaled(20, 11, scale=2) = %lld\n",
                static_cast<long long>(result.i));

    // cross-language actor: Python class by import path
    std::string actor = client.create_actor(
        "tests.cross_lang_helpers:Accumulator", {rtpu::PVal::integer(100)});
    std::string r1 = client.call_actor(actor, "add", {rtpu::PVal::integer(7)});
    std::string r2 = client.call_actor(actor, "add", {rtpu::PVal::integer(5)});
    if (client.get(r1, 60.0).i != 107 || client.get(r2, 60.0).i != 112) {
      std::fprintf(stderr, "actor results wrong\n");
      return 1;
    }
    client.kill_actor(actor);
    std::printf("actor ok: 100 +7 +5 = 112\nNATIVE_CLIENT_OK\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "rtpu-client: %s\n", e.what());
    return 1;
  }
}
