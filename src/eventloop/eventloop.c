/* Native event-loop + dispatch core for the control plane's hot lane.
 *
 * Counterpart of the reference's C++ core-worker event loop (reference:
 * src/ray/core_worker/ + rpc/ — the inner recv/demux/dispatch loop that
 * Python only observes): rpc.py keeps the protocol and the slow path,
 * but a Connection that arms the native lane moves
 *
 *   - the writer thread  (frame ring, coalesced writev, high-water
 *     backpressure) and
 *   - the reader thread  (bulk recv, [u32 len] reassembly, 0xA9 binary
 *     demux, tagged-value decode, BATCHED GIL delivery to one Python
 *     callback; pickle/exotic frames pass through as raw bytes) and
 *   - the cast flusher   (process-wide ~1 ms pass, adjacent same-kind
 *     record merging per wirefmt.coalesce_casts semantics, CAST_BATCH
 *     assembly — all without the GIL) and
 *   - the direct_ack sink (owner side: delivery acks parsed and
 *     retained in C, drained in bulk by the direct plane's watchdog)
 *
 * into pthreads that touch Python exactly once per BATCH of inbound
 * frames. Fault injection stays at the Python/native boundary: send
 * faults are applied in rpc.Connection._send before bytes reach the
 * ring, recv faults in the Python delivery callback, and rpc.py routes
 * casts back through the pure-Python buffer whenever the chaos plane
 * is armed — so the native lane never hides a frame from the fault
 * matrix.
 *
 * Decoder/encoder fragments mirror src/specenc/specenc.c and the
 * pure-Python half in wirefmt.py BYTE-FOR-BYTE; any C-side parse
 * failure downgrades that one frame to raw-bytes passthrough, so the
 * Python decoder (and its typed WireDecodeError close-the-connection
 * contract) remains the single source of error semantics.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

/* ------------------------------------------------------------------ */
/* wire kind table — MUST mirror wirefmt.KIND_CODES (codes are wire
 * protocol: never renumber, only append). tools/rtlint's RT-W pass
 * cross-checks this enum against the Python table so the two can
 * never drift; evloop.py additionally refuses to load a module whose
 * kind_codes() disagree at runtime. */

enum rt_kind {
    RT_KIND_DIRECT_PUSH = 1,
    RT_KIND_DIRECT_ACK = 2,
    RT_KIND_DIRECT_REJ = 3,
    RT_KIND_OWNER_SEALED = 4,
    RT_KIND_TASK_STARTED = 5,
    RT_KIND_TASK_FINISHED = 6,
    RT_KIND_SEAL_OBJECTS = 7,
    RT_KIND_PUSH_TASK = 8,
    RT_KIND_SUBMIT_TASK = 9,
    RT_KIND_SUBMIT_ACTOR_TASK = 10,
    RT_KIND_CAST_BATCH = 11,
    RT_KIND_CANCEL_DIRECT = 12,
    RT_KIND_PUT_INLINE = 13,
    RT_KIND_DEL_REF = 14,
    RT_KIND_DEL_BORROW = 15,
    RT_KIND_ADD_BORROW = 16,
};

#define RT_KIND_MAX 16

static const char *rt_kind_names[RT_KIND_MAX + 1] = {
    NULL,
    "direct_push",
    "direct_ack",
    "direct_rej",
    "owner_sealed",
    "task_started",
    "task_finished",
    "seal_objects",
    "push_task",
    "submit_task",
    "submit_actor_task",
    "__cast_batch__",
    "cancel_direct",
    "put_inline",
    "del_ref",
    "del_borrow",
    "add_borrow",
};

#define WIRE_MAGIC 0xA9
#define WIRE_VERSION 1

/* tagged-value codec tags (mirror wirefmt.py / specenc.c) */
#define T_NONE 0
#define T_STR 1
#define T_BYTES 2
#define T_INT 3
#define T_FLOAT 4
#define T_TRUE 5
#define T_FALSE 6
#define T_LSTR 7
#define T_DSF 8
#define T_PAIR_SI 9
#define T_LIST 10
#define T_MAP 11
#define T_TUPLE 12

#define MAX_DEPTH 64

/* ------------------------------------------------------------------ */
/* varint / parse helpers (no GIL needed) */

static int rd_varint(const uint8_t *b, size_t n, size_t *off, uint64_t *out)
{
    uint64_t v = 0;
    int shift = 0;
    while (1) {
        if (*off >= n)
            return -1;
        uint8_t c = b[(*off)++];
        v |= (uint64_t)(c & 0x7F) << shift;
        if (!(c & 0x80)) {
            *out = v;
            return 0;
        }
        shift += 7;
        if (shift > 63)
            return -1;
    }
}

static void wr_varint(uint8_t *b, size_t *off, uint64_t v)
{
    while (v >= 0x80) {
        b[(*off)++] = (uint8_t)((v & 0x7F) | 0x80);
        v >>= 7;
    }
    b[(*off)++] = (uint8_t)v;
}

static size_t varint_len(uint64_t v)
{
    size_t n = 1;
    while (v >= 0x80) {
        v >>= 7;
        n++;
    }
    return n;
}

/* skip one length-prefixed string/bytes run */
static int skip_lp(const uint8_t *b, size_t n, size_t *off)
{
    uint64_t len;
    if (rd_varint(b, n, off, &len))
        return -1;
    if (len > n - *off)
        return -1;
    *off += (size_t)len;
    return 0;
}

/* skip one tagged value; returns 0 ok, -1 corrupt */
static int skip_value(const uint8_t *b, size_t n, size_t *off, int depth)
{
    if (depth > MAX_DEPTH || *off >= n)
        return -1;
    uint8_t tag = b[(*off)++];
    uint64_t cnt, i;
    switch (tag) {
    case T_NONE:
    case T_TRUE:
    case T_FALSE:
        return 0;
    case T_STR:
    case T_BYTES:
        return skip_lp(b, n, off);
    case T_INT:
        return rd_varint(b, n, off, &cnt);
    case T_FLOAT:
        if (n - *off < 8)
            return -1;
        *off += 8;
        return 0;
    case T_LSTR:
        if (rd_varint(b, n, off, &cnt) || cnt > n - *off)
            return -1;
        for (i = 0; i < cnt; i++)
            if (skip_lp(b, n, off))
                return -1;
        return 0;
    case T_LIST:
    case T_TUPLE:
        if (rd_varint(b, n, off, &cnt) || cnt > n - *off)
            return -1;
        for (i = 0; i < cnt; i++)
            if (skip_value(b, n, off, depth + 1))
                return -1;
        return 0;
    case T_DSF:
        if (rd_varint(b, n, off, &cnt) || cnt > (n - *off) / 9)
            return -1;
        for (i = 0; i < cnt; i++) {
            if (skip_lp(b, n, off) || n - *off < 8)
                return -1;
            *off += 8;
        }
        return 0;
    case T_MAP:
        if (rd_varint(b, n, off, &cnt) || cnt > (n - *off) / 2)
            return -1;
        for (i = 0; i < cnt; i++)
            if (skip_lp(b, n, off) || skip_value(b, n, off, depth + 1))
                return -1;
        return 0;
    case T_PAIR_SI:
        if (skip_lp(b, n, off))
            return -1;
        return rd_varint(b, n, off, &cnt);
    default:
        return -1;
    }
}

/* ------------------------------------------------------------------ */
/* tagged-value -> PyObject decoder (GIL held). Mirrors wirefmt._dec;
 * any failure returns NULL with no Python exception set — the caller
 * downgrades the frame to raw passthrough and Python replays the
 * decode (keeping ONE source of error semantics). */

static PyObject *dec_value(const uint8_t *b, size_t n, size_t *off,
                           int depth)
{
    if (depth > MAX_DEPTH || *off >= n)
        return NULL;
    uint8_t tag = b[(*off)++];
    uint64_t cnt, i;
    switch (tag) {
    case T_NONE:
        Py_RETURN_NONE;
    case T_TRUE:
        Py_RETURN_TRUE;
    case T_FALSE:
        Py_RETURN_FALSE;
    case T_STR: {
        if (rd_varint(b, n, off, &cnt) || cnt > n - *off)
            return NULL;
        PyObject *s = PyUnicode_DecodeUTF8((const char *)b + *off,
                                           (Py_ssize_t)cnt, NULL);
        if (s == NULL) {
            PyErr_Clear();
            return NULL;
        }
        *off += (size_t)cnt;
        return s;
    }
    case T_BYTES: {
        if (rd_varint(b, n, off, &cnt) || cnt > n - *off)
            return NULL;
        PyObject *s = PyBytes_FromStringAndSize((const char *)b + *off,
                                                (Py_ssize_t)cnt);
        if (s == NULL) {
            PyErr_Clear();
            return NULL;
        }
        *off += (size_t)cnt;
        return s;
    }
    case T_INT: {
        if (rd_varint(b, n, off, &cnt))
            return NULL;
        /* zigzag */
        int64_t v = (int64_t)(cnt >> 1) ^ -(int64_t)(cnt & 1);
        return PyLong_FromLongLong(v);
    }
    case T_FLOAT: {
        double d;
        if (n - *off < 8)
            return NULL;
        memcpy(&d, b + *off, 8);
        *off += 8;
        return PyFloat_FromDouble(d);
    }
    case T_LSTR:
    case T_LIST:
    case T_TUPLE: {
        if (rd_varint(b, n, off, &cnt) || cnt > n - *off)
            return NULL;
        PyObject *lst = (tag == T_TUPLE)
                            ? PyTuple_New((Py_ssize_t)cnt)
                            : PyList_New((Py_ssize_t)cnt);
        if (lst == NULL) {
            PyErr_Clear();
            return NULL;
        }
        for (i = 0; i < cnt; i++) {
            PyObject *it;
            if (tag == T_LSTR) {
                uint64_t sl;
                if (rd_varint(b, n, off, &sl) || sl > n - *off) {
                    Py_DECREF(lst);
                    return NULL;
                }
                it = PyUnicode_DecodeUTF8((const char *)b + *off,
                                          (Py_ssize_t)sl, NULL);
                if (it == NULL)
                    PyErr_Clear();
                else
                    *off += (size_t)sl;
            } else {
                it = dec_value(b, n, off, depth + 1);
            }
            if (it == NULL) {
                Py_DECREF(lst);
                return NULL;
            }
            if (tag == T_TUPLE)
                PyTuple_SET_ITEM(lst, (Py_ssize_t)i, it);
            else
                PyList_SET_ITEM(lst, (Py_ssize_t)i, it);
        }
        return lst;
    }
    case T_DSF: {
        if (rd_varint(b, n, off, &cnt) || cnt > (n - *off) / 9)
            return NULL;
        PyObject *d = PyDict_New();
        if (d == NULL) {
            PyErr_Clear();
            return NULL;
        }
        for (i = 0; i < cnt; i++) {
            uint64_t sl;
            double fv;
            if (rd_varint(b, n, off, &sl) || sl > n - *off)
                goto dsf_fail;
            PyObject *k = PyUnicode_DecodeUTF8((const char *)b + *off,
                                               (Py_ssize_t)sl, NULL);
            if (k == NULL) {
                PyErr_Clear();
                goto dsf_fail;
            }
            *off += (size_t)sl;
            if (n - *off < 8) {
                Py_DECREF(k);
                goto dsf_fail;
            }
            memcpy(&fv, b + *off, 8);
            *off += 8;
            PyObject *v = PyFloat_FromDouble(fv);
            if (v == NULL || PyDict_SetItem(d, k, v) < 0) {
                PyErr_Clear();
                Py_DECREF(k);
                Py_XDECREF(v);
                goto dsf_fail;
            }
            Py_DECREF(k);
            Py_DECREF(v);
        }
        return d;
    dsf_fail:
        Py_DECREF(d);
        return NULL;
    }
    case T_MAP: {
        if (rd_varint(b, n, off, &cnt) || cnt > (n - *off) / 2)
            return NULL;
        PyObject *d = PyDict_New();
        if (d == NULL) {
            PyErr_Clear();
            return NULL;
        }
        for (i = 0; i < cnt; i++) {
            uint64_t sl;
            if (rd_varint(b, n, off, &sl) || sl > n - *off)
                goto map_fail;
            PyObject *k = PyUnicode_DecodeUTF8((const char *)b + *off,
                                               (Py_ssize_t)sl, NULL);
            if (k == NULL) {
                PyErr_Clear();
                goto map_fail;
            }
            *off += (size_t)sl;
            PyObject *v = dec_value(b, n, off, depth + 1);
            if (v == NULL || PyDict_SetItem(d, k, v) < 0) {
                PyErr_Clear();
                Py_DECREF(k);
                Py_XDECREF(v);
                goto map_fail;
            }
            Py_DECREF(k);
            Py_DECREF(v);
        }
        return d;
    map_fail:
        Py_DECREF(d);
        return NULL;
    }
    case T_PAIR_SI: {
        uint64_t sl;
        if (rd_varint(b, n, off, &sl) || sl > n - *off)
            return NULL;
        PyObject *s = PyUnicode_DecodeUTF8((const char *)b + *off,
                                           (Py_ssize_t)sl, NULL);
        if (s == NULL) {
            PyErr_Clear();
            return NULL;
        }
        *off += (size_t)sl;
        if (rd_varint(b, n, off, &cnt)) {
            Py_DECREF(s);
            return NULL;
        }
        int64_t v = (int64_t)(cnt >> 1) ^ -(int64_t)(cnt & 1);
        PyObject *iv = PyLong_FromLongLong(v);
        if (iv == NULL) {
            PyErr_Clear();
            Py_DECREF(s);
            return NULL;
        }
        PyObject *t = PyTuple_Pack(2, s, iv);
        Py_DECREF(s);
        Py_DECREF(iv);
        if (t == NULL)
            PyErr_Clear();
        return t;
    }
    default:
        return NULL;
    }
}

/* ------------------------------------------------------------------ */
/* connection object */

typedef struct frame {
    struct frame *next;
    uint32_t len; /* full wire bytes incl. 4-byte length prefix */
    uint8_t data[];
} frame_t;

typedef struct castrec {
    struct castrec *next;
    uint8_t kind;
    uint32_t len;
    uint8_t data[];
} castrec_t;

typedef struct conn {
    int fd;     /* our dup(); C owns it */
    int closed; /* under mu */
    int freed_bufs;
    int threads_live; /* under g_mu */
    pthread_mutex_t mu;
    pthread_cond_t cv; /* writer wakeup + drain/highwater waiters */
    pthread_mutex_t fl_mu; /* serializes cast flushes (order!) */

    /* send ring */
    frame_t *q_head, *q_tail;
    size_t q_bytes;
    size_t high_water;
    int writer_idle;

    /* cast buffer */
    castrec_t *cb_head, *cb_tail;
    int cb_count;

    /* counters for flusher-built frames (Python folds them in) */
    unsigned long long fl_frames, fl_bytes;

    /* direct_ack sink (owner side) */
    int ack_sink; /* under mu */
    uint8_t *acks;
    size_t acks_len, acks_cap;
    unsigned long long acks_sunk;

    PyObject *callback; /* owned; reader thread uses under GIL */

    struct conn *next_all; /* global registry (flusher walk) */
} conn_t;

static pthread_mutex_t g_mu = PTHREAD_MUTEX_INITIALIZER;
static conn_t *g_conns = NULL;
static int g_flusher_running = 0;
static int g_live_conns = 0;

#define CAST_BATCH_MAX 512
#define READ_CHUNK (256 * 1024)
#define WRITE_IOV_MAX 64

/* ------------------------------------------------------------------ */
/* ring helpers (conn->mu held unless noted) */

static void ring_append(conn_t *c, frame_t *f)
{
    f->next = NULL;
    if (c->q_tail)
        c->q_tail->next = f;
    else
        c->q_head = f;
    c->q_tail = f;
    c->q_bytes += f->len;
}

static void ring_clear(conn_t *c)
{
    frame_t *f = c->q_head;
    while (f) {
        frame_t *n = f->next;
        free(f);
        f = n;
    }
    c->q_head = c->q_tail = NULL;
    c->q_bytes = 0;
}

static void casts_clear(conn_t *c)
{
    castrec_t *r = c->cb_head;
    while (r) {
        castrec_t *n = r->next;
        free(r);
        r = n;
    }
    c->cb_head = c->cb_tail = NULL;
    c->cb_count = 0;
}

/* ------------------------------------------------------------------ */
/* cast flush: adjacent same-kind merge (mirrors wirefmt.coalesce_casts
 * + wirefmt._MERGERS) and frame assembly. No GIL required. */

typedef struct merged {
    uint8_t kind;
    uint8_t *payload; /* owned iff owns */
    size_t len;
    int owns;
} merged_t;

/* Parse a single-key map payload {key: container}; on success fills
 * the container's element-count and element-bytes span. Accepts only
 * the exact shape the runtime emits, so merged output is byte-
 * identical to the Python merger's re-encode. */
static int parse_keyed_container(const uint8_t *p, size_t n,
                                 const char *key, uint8_t want_tag,
                                 uint64_t *count, size_t *span_off)
{
    size_t off = 0;
    uint64_t cnt, klen;
    if (n < 2 || p[off++] != T_MAP)
        return -1;
    if (rd_varint(p, n, &off, &cnt) || cnt != 1)
        return -1;
    if (rd_varint(p, n, &off, &klen) || klen != strlen(key)
        || klen > n - off || memcmp(p + off, key, (size_t)klen) != 0)
        return -1;
    off += (size_t)klen;
    if (off >= n || p[off++] != want_tag)
        return -1;
    if (rd_varint(p, n, &off, count))
        return -1;
    *span_off = off;
    /* validate: elements must consume the payload exactly */
    uint64_t i;
    for (i = 0; i < *count; i++) {
        if (want_tag == T_LSTR) {
            if (skip_lp(p, n, &off))
                return -1;
        } else {
            if (skip_value(p, n, &off, 1))
                return -1;
        }
    }
    return off == n ? 0 : -1;
}

/* owner_sealed payload: {"objects": [..], "t_resolve": f}? (key order
 * free, t_resolve optional, no other keys). */
static int parse_owner_sealed(const uint8_t *p, size_t n, uint8_t *obj_tag,
                              uint64_t *count, size_t *span_off,
                              size_t *span_len, int *has_t, double *t)
{
    size_t off = 0;
    uint64_t cnt, i, klen;
    int saw_obj = 0;
    *has_t = 0;
    if (n < 2 || p[off++] != T_MAP)
        return -1;
    if (rd_varint(p, n, &off, &cnt) || cnt < 1 || cnt > 2)
        return -1;
    for (i = 0; i < cnt; i++) {
        if (rd_varint(p, n, &off, &klen) || klen > n - off)
            return -1;
        const char *k = (const char *)p + off;
        off += (size_t)klen;
        if (klen == 7 && memcmp(k, "objects", 7) == 0) {
            if (off >= n)
                return -1;
            uint8_t tag = p[off++];
            if (tag != T_LIST && tag != T_LSTR)
                return -1;
            uint64_t oc;
            if (rd_varint(p, n, &off, &oc))
                return -1;
            size_t start = off;
            uint64_t j;
            for (j = 0; j < oc; j++) {
                if (tag == T_LSTR ? skip_lp(p, n, &off)
                                  : skip_value(p, n, &off, 1))
                    return -1;
            }
            *obj_tag = tag;
            *count = oc;
            *span_off = start;
            *span_len = off - start;
            saw_obj = 1;
        } else if (klen == 9 && memcmp(k, "t_resolve", 9) == 0) {
            if (off >= n || p[off++] != T_FLOAT || n - off < 8)
                return -1;
            memcpy(t, p + off, 8);
            off += 8;
            *has_t = 1;
        } else {
            return -1;
        }
    }
    return (saw_obj && off == n) ? 0 : -1;
}

/* Try to merge a run[0..k) of same-kind casts. Returns a malloc'd
 * payload (caller owns) or NULL (emit individually). */
static uint8_t *merge_run(castrec_t **run, int k, uint8_t kind,
                          size_t *out_len)
{
    int i;
    if (kind == RT_KIND_DIRECT_ACK || kind == RT_KIND_SEAL_OBJECTS) {
        const char *key =
            (kind == RT_KIND_DIRECT_ACK) ? "task_ids" : "objects";
        uint8_t want = (kind == RT_KIND_DIRECT_ACK) ? T_LSTR : T_LIST;
        uint64_t total = 0;
        size_t bytes = 0;
        size_t offs[512];
        uint64_t cnts[512];
        if (k > 512)
            return NULL;
        for (i = 0; i < k; i++) {
            if (parse_keyed_container(run[i]->data, run[i]->len, key,
                                      want, &cnts[i], &offs[i]))
                return NULL;
            total += cnts[i];
            bytes += run[i]->len - offs[i];
        }
        size_t klen = strlen(key);
        size_t cap = 1 + 1 + 1 + klen + 1 + varint_len(total) + bytes;
        uint8_t *out = malloc(cap);
        if (out == NULL)
            return NULL;
        size_t o = 0;
        out[o++] = T_MAP;
        wr_varint(out, &o, 1);
        wr_varint(out, &o, klen);
        memcpy(out + o, key, klen);
        o += klen;
        out[o++] = want;
        wr_varint(out, &o, total);
        for (i = 0; i < k; i++) {
            size_t sl = run[i]->len - offs[i];
            memcpy(out + o, run[i]->data + offs[i], sl);
            o += sl;
        }
        *out_len = o;
        return out;
    }
    if (kind == RT_KIND_OWNER_SEALED) {
        uint64_t total = 0;
        size_t bytes = 0;
        int any_t = 0;
        double tmax = 0.0;
        uint8_t tag0 = 0;
        size_t offs[512], lens[512];
        if (k > 512)
            return NULL;
        for (i = 0; i < k; i++) {
            uint8_t tag = 0;
            uint64_t cnt;
            int has_t;
            double t;
            if (parse_owner_sealed(run[i]->data, run[i]->len, &tag, &cnt,
                                   &offs[i], &lens[i], &has_t, &t))
                return NULL;
            if (i == 0)
                tag0 = tag;
            else if (tag != tag0)
                return NULL;
            total += cnt;
            bytes += lens[i];
            /* mirror _merge_owner_sealed: max over TRUTHY stamps */
            if (has_t && t != 0.0) {
                if (!any_t || t > tmax)
                    tmax = t;
                any_t = 1;
            }
        }
        size_t cap = 1 + 1 + 1 + 7 + 1 + varint_len(total) + bytes + 1
                     + 9 + 1 + 8;
        uint8_t *out = malloc(cap);
        if (out == NULL)
            return NULL;
        size_t o = 0;
        out[o++] = T_MAP;
        wr_varint(out, &o, any_t ? 2 : 1);
        wr_varint(out, &o, 7);
        memcpy(out + o, "objects", 7);
        o += 7;
        out[o++] = tag0;
        wr_varint(out, &o, total);
        for (i = 0; i < k; i++) {
            memcpy(out + o, run[i]->data + offs[i], lens[i]);
            o += lens[i];
        }
        if (any_t) {
            wr_varint(out, &o, 9);
            memcpy(out + o, "t_resolve", 9);
            o += 9;
            out[o++] = T_FLOAT;
            memcpy(out + o, &tmax, 8);
            o += 8;
        }
        *out_len = o;
        return out;
    }
    return NULL;
}

static int kind_mergeable(uint8_t kind)
{
    return kind == RT_KIND_DIRECT_ACK || kind == RT_KIND_SEAL_OBJECTS
           || kind == RT_KIND_OWNER_SEALED;
}

static frame_t *frame_for_payload(uint8_t kind, const uint8_t *payload,
                                  size_t plen)
{
    /* [u32 le len][A9][ver][kind][flags=0][msg_id varint = 0][payload] */
    size_t body = 5 + plen;
    frame_t *f = malloc(sizeof(frame_t) + 4 + body);
    if (f == NULL)
        return NULL;
    f->len = (uint32_t)(4 + body);
    uint8_t *d = f->data;
    d[0] = (uint8_t)(body & 0xFF);
    d[1] = (uint8_t)((body >> 8) & 0xFF);
    d[2] = (uint8_t)((body >> 16) & 0xFF);
    d[3] = (uint8_t)((body >> 24) & 0xFF);
    d[4] = WIRE_MAGIC;
    d[5] = WIRE_VERSION;
    d[6] = kind;
    d[7] = 0;
    d[8] = 0;
    memcpy(d + 9, payload, plen);
    return f;
}

/* Flush the cast buffer of one conn: detach, merge, frame, append to
 * ring. Caller must NOT hold mu; takes fl_mu for ordering (a Python
 * flush_casts and the background flusher must not interleave their
 * detach->append windows, or a later call frame could overtake
 * buffered casts). */
static void conn_flush_casts(conn_t *c)
{
    pthread_mutex_lock(&c->fl_mu);
    pthread_mutex_lock(&c->mu);
    castrec_t *head = c->cb_head;
    int count = c->cb_count;
    c->cb_head = c->cb_tail = NULL;
    c->cb_count = 0;
    int closed = c->closed;
    pthread_mutex_unlock(&c->mu);
    if (head == NULL || closed) {
        castrec_t *r = head;
        while (r) {
            castrec_t *n = r->next;
            free(r);
            r = n;
        }
        pthread_mutex_unlock(&c->fl_mu);
        return;
    }

    /* collect into an array for run detection */
    castrec_t *arr[CAST_BATCH_MAX + 64];
    int n = 0;
    castrec_t *r = head;
    while (r && n < CAST_BATCH_MAX + 64) {
        arr[n++] = r;
        r = r->next;
    }
    /* overflow defensively: flush the tail separately afterwards */
    castrec_t *tail_rest = r;

    merged_t out[CAST_BATCH_MAX + 64];
    int m = 0;
    int i = 0;
    (void)count;
    while (i < n) {
        int j = i;
        while (j + 1 < n && arr[j + 1]->kind == arr[i]->kind)
            j++;
        int runlen = j - i + 1;
        if (runlen > 1 && kind_mergeable(arr[i]->kind)) {
            size_t ml = 0;
            uint8_t *mp = merge_run(&arr[i], runlen, arr[i]->kind, &ml);
            if (mp != NULL) {
                out[m].kind = arr[i]->kind;
                out[m].payload = mp;
                out[m].len = ml;
                out[m].owns = 1;
                m++;
                i = j + 1;
                continue;
            }
        }
        /* unmerged: one entry per record */
        int k2;
        for (k2 = i; k2 <= j; k2++) {
            out[m].kind = arr[k2]->kind;
            out[m].payload = arr[k2]->data;
            out[m].len = arr[k2]->len;
            out[m].owns = 0;
            m++;
        }
        i = j + 1;
    }

    frame_t *fr = NULL;
    if (m == 1) {
        fr = frame_for_payload(out[0].kind, out[0].payload, out[0].len);
    } else if (m > 1) {
        /* CAST_BATCH body: T_LIST n of T_TUPLE(2)[T_STR kind, body] —
         * each body span is already a tagged value, so splicing the
         * buffered bytes verbatim reproduces
         * wirefmt.encode(CAST_BATCH, 0, [(kind, body_dict)]) exactly */
        size_t plen = 1 + varint_len((uint64_t)m);
        for (i = 0; i < m; i++) {
            const char *kn = rt_kind_names[out[i].kind];
            size_t kl = strlen(kn);
            plen += 1 + 1 + 1 + varint_len(kl) + kl + out[i].len;
        }
        uint8_t *p = malloc(plen);
        if (p != NULL) {
            size_t o = 0;
            p[o++] = T_LIST;
            wr_varint(p, &o, (uint64_t)m);
            for (i = 0; i < m; i++) {
                const char *kn = rt_kind_names[out[i].kind];
                size_t kl = strlen(kn);
                p[o++] = T_TUPLE;
                wr_varint(p, &o, 2);
                p[o++] = T_STR;
                wr_varint(p, &o, kl);
                memcpy(p + o, kn, kl);
                o += kl;
                memcpy(p + o, out[i].payload, out[i].len);
                o += out[i].len;
            }
            fr = frame_for_payload(RT_KIND_CAST_BATCH, p, o);
            free(p);
        }
    }

    for (i = 0; i < m; i++)
        if (out[i].owns)
            free(out[i].payload);
    r = head;
    while (r) {
        castrec_t *nx = r->next;
        free(r);
        r = nx;
    }

    if (fr != NULL) {
        pthread_mutex_lock(&c->mu);
        if (c->closed) {
            free(fr);
        } else {
            ring_append(c, fr);
            c->fl_frames += 1;
            c->fl_bytes += fr->len;
            pthread_cond_broadcast(&c->cv);
        }
        pthread_mutex_unlock(&c->mu);
    }
    pthread_mutex_unlock(&c->fl_mu);
    if (tail_rest != NULL) {
        /* re-attach overflow and flush again */
        pthread_mutex_lock(&c->mu);
        castrec_t *t = tail_rest;
        int cnt2 = 0;
        castrec_t *last = t;
        while (last->next) {
            last = last->next;
            cnt2++;
        }
        cnt2++;
        last->next = c->cb_head;
        c->cb_head = t;
        if (c->cb_tail == NULL)
            c->cb_tail = last;
        c->cb_count += cnt2;
        pthread_mutex_unlock(&c->mu);
        conn_flush_casts(c);
    }
}

/* ------------------------------------------------------------------ */
/* global flusher thread: ~1 ms pass over all conns (the native
 * counterpart of rpc._CastFlusher — bounds the latency of a lone
 * buffered cast without a timer thread per connection). */

static void *flusher_main(void *arg)
{
    (void)arg;
    struct timespec ts = {0, 1000000}; /* 1 ms */
    while (1) {
        nanosleep(&ts, NULL);
        pthread_mutex_lock(&g_mu);
        conn_t *c = g_conns;
        pthread_mutex_unlock(&g_mu);
        /* conn structs are never freed (only their buffers), so the
         * unlocked walk is safe: next_all links are write-once. */
        while (c) {
            int want = 0;
            pthread_mutex_lock(&c->mu);
            want = (!c->closed && c->cb_count > 0);
            pthread_mutex_unlock(&c->mu);
            if (want)
                conn_flush_casts(c);
            c = c->next_all;
        }
    }
    return NULL;
}

/* ------------------------------------------------------------------ */
/* writer thread */

static void *writer_main(void *arg)
{
    conn_t *c = arg;
    for (;;) {
        pthread_mutex_lock(&c->mu);
        while (!c->closed && c->q_head == NULL) {
            c->writer_idle = 1;
            pthread_cond_broadcast(&c->cv); /* drain waiters */
            pthread_cond_wait(&c->cv, &c->mu);
        }
        if (c->closed && c->q_head == NULL) {
            c->writer_idle = 1;
            pthread_cond_broadcast(&c->cv);
            pthread_mutex_unlock(&c->mu);
            break;
        }
        /* pop a batch */
        frame_t *batch[WRITE_IOV_MAX];
        struct iovec iov[WRITE_IOV_MAX];
        int n = 0;
        size_t bytes = 0;
        while (c->q_head && n < WRITE_IOV_MAX) {
            frame_t *f = c->q_head;
            c->q_head = f->next;
            if (c->q_head == NULL)
                c->q_tail = NULL;
            batch[n] = f;
            iov[n].iov_base = f->data;
            iov[n].iov_len = f->len;
            bytes += f->len;
            n++;
        }
        c->writer_idle = 0;
        pthread_mutex_unlock(&c->mu);

        /* send it all (handle partial writev) */
        int err = 0;
        int idx = 0;
        while (idx < n) {
            ssize_t w = writev(c->fd, &iov[idx], n - idx);
            if (w < 0) {
                if (errno == EINTR)
                    continue;
                err = 1;
                break;
            }
            size_t left = (size_t)w;
            while (idx < n && left >= iov[idx].iov_len) {
                left -= iov[idx].iov_len;
                idx++;
            }
            if (idx < n && left > 0) {
                iov[idx].iov_base = (uint8_t *)iov[idx].iov_base + left;
                iov[idx].iov_len -= left;
            }
        }
        int i;
        for (i = 0; i < n; i++)
            free(batch[i]);
        pthread_mutex_lock(&c->mu);
        c->q_bytes -= bytes;
        pthread_cond_broadcast(&c->cv); /* highwater + drain waiters */
        if (err) {
            /* peer gone on the SEND side: mirror rpc._write_loop —
             * drop the queue and force the reader's EOF path (which
             * runs the Python _shutdown teardown) via shutdown(2). */
            ring_clear(c);
            c->closed = 1;
            pthread_cond_broadcast(&c->cv);
            pthread_mutex_unlock(&c->mu);
            shutdown(c->fd, SHUT_RDWR);
            break;
        }
        pthread_mutex_unlock(&c->mu);
    }

    /* last-thread cleanup */
    pthread_mutex_lock(&g_mu);
    int last = (--c->threads_live == 0);
    pthread_mutex_unlock(&g_mu);
    if (last) {
        pthread_mutex_lock(&c->mu);
        ring_clear(c);
        casts_clear(c);
        free(c->acks);
        c->acks = NULL;
        c->acks_len = c->acks_cap = 0;
        c->freed_bufs = 1;
        pthread_mutex_unlock(&c->mu);
        close(c->fd);
    }
    return NULL;
}

/* ------------------------------------------------------------------ */
/* direct_ack sink: parse {"task_ids": [str,...]} casts entirely in C.
 * Returns 0 when consumed, -1 when the frame must go to Python. */

static int sink_ack_frame(conn_t *c, const uint8_t *p, size_t n)
{
    /* p points at the frame body (past the length prefix):
     * [A9][01][kind=2][flags][msgid=0][payload...] */
    if (n < 6 || p[0] != WIRE_MAGIC || p[1] != WIRE_VERSION
        || p[2] != RT_KIND_DIRECT_ACK || p[4] != 0)
        return -1;
    const uint8_t *b = p + 5;
    size_t bn = n - 5;
    uint64_t cnt;
    size_t span;
    if (parse_keyed_container(b, bn, "task_ids", T_LSTR, &cnt, &span))
        return -1;
    /* append each id as [u32 len][bytes] */
    size_t off = span;
    uint64_t i;
    pthread_mutex_lock(&c->mu);
    if (!c->ack_sink) {
        pthread_mutex_unlock(&c->mu);
        return -1;
    }
    for (i = 0; i < cnt; i++) {
        uint64_t sl;
        if (rd_varint(b, bn, &off, &sl) || sl > bn - off)
            break; /* validated already; defensive */
        size_t need = c->acks_len + 4 + (size_t)sl;
        if (need > c->acks_cap) {
            size_t ncap = c->acks_cap ? c->acks_cap * 2 : 4096;
            while (ncap < need)
                ncap *= 2;
            uint8_t *na = realloc(c->acks, ncap);
            if (na == NULL)
                break;
            c->acks = na;
            c->acks_cap = ncap;
        }
        uint8_t *d = c->acks + c->acks_len;
        d[0] = (uint8_t)(sl & 0xFF);
        d[1] = (uint8_t)((sl >> 8) & 0xFF);
        d[2] = (uint8_t)((sl >> 16) & 0xFF);
        d[3] = (uint8_t)((sl >> 24) & 0xFF);
        memcpy(d + 4, b + off, (size_t)sl);
        c->acks_len += 4 + (size_t)sl;
        c->acks_sunk++;
        off += (size_t)sl;
    }
    pthread_mutex_unlock(&c->mu);
    return 0;
}

/* ------------------------------------------------------------------ */
/* reader thread: bulk recv + reassembly + batched GIL delivery */

typedef struct span {
    size_t off;
    size_t len; /* frame body length (without the 4-byte prefix) */
} span_t;

static PyObject *decode_frame_obj(const uint8_t *p, size_t n)
{
    /* Full native decode of a binary hot frame; NULL (no exception) ->
     * caller passes raw bytes through to Python. */
    if (n < 5 || p[0] != WIRE_MAGIC || p[1] != WIRE_VERSION)
        return NULL;
    uint8_t kc = p[2];
    if (kc < 1 || kc > RT_KIND_MAX)
        return NULL;
    size_t off = 4;
    uint64_t msg_id = 0;
    if (p[4] == 0) {
        off = 5;
    } else {
        if (rd_varint(p, n, &off, &msg_id))
            return NULL;
    }
    PyObject *body = dec_value(p, n, &off, 0);
    if (body == NULL)
        return NULL;
    if (off != n) {
        Py_DECREF(body);
        return NULL;
    }
    PyObject *kind = PyUnicode_FromString(rt_kind_names[kc]);
    PyObject *mid = PyLong_FromUnsignedLongLong(msg_id);
    if (kind == NULL || mid == NULL) {
        PyErr_Clear();
        Py_XDECREF(kind);
        Py_XDECREF(mid);
        Py_DECREF(body);
        return NULL;
    }
    PyObject *t = PyTuple_Pack(3, kind, mid, body);
    Py_DECREF(kind);
    Py_DECREF(mid);
    Py_DECREF(body);
    if (t == NULL)
        PyErr_Clear();
    return t;
}

/* deliver a batch of frame spans to the Python callback.
 * Returns 0 to continue, -1 to stop the reader. */
static int deliver_batch(conn_t *c, const uint8_t *buf, span_t *spans,
                         int nspans)
{
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject *list = PyList_New(nspans);
    int stop = 0;
    if (list == NULL) {
        PyErr_Clear();
        PyGILState_Release(g);
        return -1;
    }
    int i;
    for (i = 0; i < nspans; i++) {
        const uint8_t *p = buf + spans[i].off;
        size_t n = spans[i].len;
        PyObject *it = NULL;
        if (n > 0 && p[0] == WIRE_MAGIC)
            it = decode_frame_obj(p, n);
        if (it == NULL) {
            /* pickle frame / exotic / corrupt: raw passthrough — the
             * Python side replays the decode and owns error handling */
            it = PyBytes_FromStringAndSize((const char *)p,
                                           (Py_ssize_t)n);
            if (it == NULL) {
                PyErr_Clear();
                stop = 1;
                Py_DECREF(list);
                PyGILState_Release(g);
                return -1;
            }
        }
        PyList_SET_ITEM(list, i, it);
    }
    PyObject *res = PyObject_CallFunctionObjArgs(c->callback, list, NULL);
    Py_DECREF(list);
    if (res == NULL) {
        PyErr_Print();
        stop = 1;
    } else {
        stop = !PyObject_IsTrue(res);
        Py_DECREF(res);
    }
    PyGILState_Release(g);
    return stop ? -1 : 0;
}

static void deliver_eof(conn_t *c)
{
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject *res =
        PyObject_CallFunctionObjArgs(c->callback, Py_None, NULL);
    if (res == NULL)
        PyErr_Print();
    else
        Py_DECREF(res);
    Py_CLEAR(c->callback);
    PyGILState_Release(g);
}

static void *reader_main(void *arg)
{
    conn_t *c = arg;
    size_t cap = READ_CHUNK;
    uint8_t *buf = malloc(cap);
    size_t have = 0, pos = 0;
    span_t spans[1024];

    if (buf == NULL)
        goto out;
    for (;;) {
        pthread_mutex_lock(&c->mu);
        int closed = c->closed;
        pthread_mutex_unlock(&c->mu);
        if (closed)
            break;
        /* compact + ensure space */
        if (pos > 0) {
            memmove(buf, buf + pos, have - pos);
            have -= pos;
            pos = 0;
        }
        if (have == cap) {
            size_t ncap = cap * 2;
            uint8_t *nb = realloc(buf, ncap);
            if (nb == NULL)
                break;
            buf = nb;
            cap = ncap;
        }
        ssize_t r = recv(c->fd, buf + have, cap - have, 0);
        if (r < 0 && errno == EINTR)
            continue;
        if (r <= 0)
            break;
        have += (size_t)r;

        /* demux complete frames */
        int ns = 0;
        while (have - pos >= 4) {
            uint32_t flen = (uint32_t)buf[pos]
                            | ((uint32_t)buf[pos + 1] << 8)
                            | ((uint32_t)buf[pos + 2] << 16)
                            | ((uint32_t)buf[pos + 3] << 24);
            if ((size_t)flen + 4 > have - pos) {
                /* grow eagerly for oversized frames so the next recv
                 * can complete them in one pass */
                if ((size_t)flen + 4 > cap) {
                    size_t ncap = cap;
                    while (ncap < (size_t)flen + 4)
                        ncap *= 2;
                    /* compact first so pos==0 */
                    if (pos > 0) {
                        memmove(buf, buf + pos, have - pos);
                        have -= pos;
                        pos = 0;
                    }
                    uint8_t *nb = realloc(buf, ncap);
                    if (nb == NULL)
                        goto out_free;
                    buf = nb;
                    cap = ncap;
                }
                break;
            }
            size_t body = pos + 4;
            int sink;
            pthread_mutex_lock(&c->mu);
            sink = c->ack_sink;
            pthread_mutex_unlock(&c->mu);
            if (sink && flen >= 6 && buf[body] == WIRE_MAGIC
                && buf[body + 2] == RT_KIND_DIRECT_ACK
                && sink_ack_frame(c, buf + body, flen) == 0) {
                pos = body + flen;
                continue;
            }
            spans[ns].off = body;
            spans[ns].len = flen;
            ns++;
            pos = body + flen;
            if (ns == 1024) {
                if (deliver_batch(c, buf, spans, ns))
                    goto out_free;
                ns = 0;
            }
        }
        if (ns > 0 && deliver_batch(c, buf, spans, ns))
            goto out_free;
    }
out_free:
    free(buf);
    buf = NULL;
out:
    /* EOF/teardown: tell Python (it runs _shutdown), then close our
     * half. */
    pthread_mutex_lock(&c->mu);
    c->closed = 1;
    ring_clear(c);
    pthread_cond_broadcast(&c->cv);
    pthread_mutex_unlock(&c->mu);
    shutdown(c->fd, SHUT_RDWR);
    if (c->callback)
        deliver_eof(c);

    pthread_mutex_lock(&g_mu);
    int last = (--c->threads_live == 0);
    pthread_mutex_unlock(&g_mu);
    if (last) {
        pthread_mutex_lock(&c->mu);
        ring_clear(c);
        casts_clear(c);
        free(c->acks);
        c->acks = NULL;
        c->acks_len = c->acks_cap = 0;
        c->freed_bufs = 1;
        pthread_mutex_unlock(&c->mu);
        close(c->fd);
    }
    return NULL;
}

/* ------------------------------------------------------------------ */
/* module functions */

static conn_t *conn_from_handle(PyObject *h)
{
    void *p = PyLong_AsVoidPtr(h);
    if (p == NULL && PyErr_Occurred())
        return NULL;
    return (conn_t *)p;
}

static PyObject *py_attach(PyObject *self, PyObject *args)
{
    int fd;
    PyObject *cb;
    unsigned long long high_water = 64ULL << 20;
    (void)self;
    if (!PyArg_ParseTuple(args, "iO|K", &fd, &cb, &high_water))
        return NULL;
    if (!PyCallable_Check(cb)) {
        PyErr_SetString(PyExc_TypeError, "callback must be callable");
        return NULL;
    }
    int dupfd = dup(fd);
    if (dupfd < 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    conn_t *c = calloc(1, sizeof(conn_t));
    if (c == NULL) {
        close(dupfd);
        return PyErr_NoMemory();
    }
    c->fd = dupfd;
    c->high_water = (size_t)high_water;
    c->writer_idle = 1;
    pthread_mutex_init(&c->mu, NULL);
    pthread_mutex_init(&c->fl_mu, NULL);
    pthread_cond_init(&c->cv, NULL);
    Py_INCREF(cb);
    c->callback = cb;
    c->threads_live = 2;

    pthread_mutex_lock(&g_mu);
    c->next_all = g_conns;
    g_conns = c;
    g_live_conns++;
    if (!g_flusher_running) {
        pthread_t ft;
        pthread_attr_t at;
        pthread_attr_init(&at);
        pthread_attr_setdetachstate(&at, PTHREAD_CREATE_DETACHED);
        if (pthread_create(&ft, &at, flusher_main, NULL) == 0)
            g_flusher_running = 1;
        pthread_attr_destroy(&at);
    }
    pthread_mutex_unlock(&g_mu);

    pthread_attr_t at;
    pthread_attr_init(&at);
    pthread_attr_setdetachstate(&at, PTHREAD_CREATE_DETACHED);
    pthread_t wt, rt;
    int e1 = pthread_create(&wt, &at, writer_main, c);
    int e2 = e1 ? e1 : pthread_create(&rt, &at, reader_main, c);
    pthread_attr_destroy(&at);
    if (e1 || e2) {
        pthread_mutex_lock(&c->mu);
        c->closed = 1;
        pthread_cond_broadcast(&c->cv);
        pthread_mutex_unlock(&c->mu);
        if (e1) { /* neither thread exists: free here */
            pthread_mutex_lock(&g_mu);
            c->threads_live = 0;
            pthread_mutex_unlock(&g_mu);
            close(c->fd);
            Py_CLEAR(c->callback);
        }
        PyErr_SetString(PyExc_OSError, "evloop: thread create failed");
        return NULL;
    }
    return PyLong_FromVoidPtr(c);
}

static PyObject *py_send(PyObject *self, PyObject *args)
{
    PyObject *h;
    Py_buffer view;
    (void)self;
    if (!PyArg_ParseTuple(args, "Oy*", &h, &view))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    frame_t *f = malloc(sizeof(frame_t) + view.len);
    if (f == NULL) {
        PyBuffer_Release(&view);
        return PyErr_NoMemory();
    }
    f->len = (uint32_t)view.len;
    memcpy(f->data, view.buf, (size_t)view.len);
    PyBuffer_Release(&view);

    int ok = 1;
    Py_BEGIN_ALLOW_THREADS;
    pthread_mutex_lock(&c->mu);
    while (!c->closed && c->q_bytes > c->high_water) {
        struct timespec ts;
        clock_gettime(CLOCK_REALTIME, &ts);
        ts.tv_sec += 1;
        pthread_cond_timedwait(&c->cv, &c->mu, &ts);
    }
    if (c->closed) {
        ok = 0;
        free(f);
    } else {
        ring_append(c, f);
        pthread_cond_broadcast(&c->cv);
    }
    pthread_mutex_unlock(&c->mu);
    Py_END_ALLOW_THREADS;
    return PyBool_FromLong(ok);
}

static PyObject *py_cast(PyObject *self, PyObject *args)
{
    PyObject *h;
    int kind;
    Py_buffer view;
    (void)self;
    if (!PyArg_ParseTuple(args, "Oiy*", &h, &kind, &view))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL || kind < 1 || kind > RT_KIND_MAX) {
        PyBuffer_Release(&view);
        if (c != NULL)
            PyErr_SetString(PyExc_ValueError, "bad kind code");
        return NULL;
    }
    castrec_t *r = malloc(sizeof(castrec_t) + view.len);
    if (r == NULL) {
        PyBuffer_Release(&view);
        return PyErr_NoMemory();
    }
    r->kind = (uint8_t)kind;
    r->len = (uint32_t)view.len;
    r->next = NULL;
    memcpy(r->data, view.buf, (size_t)view.len);
    PyBuffer_Release(&view);

    int full = 0, ok = 1;
    pthread_mutex_lock(&c->mu);
    if (c->closed) {
        ok = 0;
        free(r);
    } else {
        if (c->cb_tail)
            c->cb_tail->next = r;
        else
            c->cb_head = r;
        c->cb_tail = r;
        c->cb_count++;
        full = (c->cb_count >= CAST_BATCH_MAX);
    }
    pthread_mutex_unlock(&c->mu);
    if (full) {
        Py_BEGIN_ALLOW_THREADS;
        conn_flush_casts(c);
        Py_END_ALLOW_THREADS;
    }
    return PyBool_FromLong(ok);
}

static PyObject *py_flush(PyObject *self, PyObject *args)
{
    PyObject *h;
    (void)self;
    if (!PyArg_ParseTuple(args, "O", &h))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    Py_BEGIN_ALLOW_THREADS;
    conn_flush_casts(c);
    Py_END_ALLOW_THREADS;
    Py_RETURN_NONE;
}

static PyObject *py_drain(PyObject *self, PyObject *args)
{
    PyObject *h;
    double timeout_s = 2.0;
    (void)self;
    if (!PyArg_ParseTuple(args, "O|d", &h, &timeout_s))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    int drained = 0;
    Py_BEGIN_ALLOW_THREADS;
    conn_flush_casts(c);
    struct timespec dl;
    clock_gettime(CLOCK_REALTIME, &dl);
    dl.tv_sec += (time_t)timeout_s;
    dl.tv_nsec += (long)((timeout_s - (time_t)timeout_s) * 1e9);
    if (dl.tv_nsec >= 1000000000L) {
        dl.tv_sec += 1;
        dl.tv_nsec -= 1000000000L;
    }
    pthread_mutex_lock(&c->mu);
    while (!c->closed && (c->q_head != NULL || !c->writer_idle)) {
        if (pthread_cond_timedwait(&c->cv, &c->mu, &dl) == ETIMEDOUT)
            break;
    }
    drained = (c->q_head == NULL && c->writer_idle);
    pthread_mutex_unlock(&c->mu);
    Py_END_ALLOW_THREADS;
    return PyBool_FromLong(drained);
}

static void conn_close(conn_t *c)
{
    pthread_mutex_lock(&c->mu);
    if (c->closed) {
        pthread_mutex_unlock(&c->mu);
        return;
    }
    c->closed = 1;
    pthread_cond_broadcast(&c->cv);
    pthread_mutex_unlock(&c->mu);
    /* wake the reader out of recv; writer wakes via cv */
    shutdown(c->fd, SHUT_RDWR);
}

static PyObject *py_close(PyObject *self, PyObject *args)
{
    PyObject *h;
    (void)self;
    if (!PyArg_ParseTuple(args, "O", &h))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    conn_close(c);
    Py_RETURN_NONE;
}

static PyObject *py_take_counters(PyObject *self, PyObject *args)
{
    PyObject *h;
    (void)self;
    if (!PyArg_ParseTuple(args, "O", &h))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    unsigned long long fr, by;
    pthread_mutex_lock(&c->mu);
    fr = c->fl_frames;
    by = c->fl_bytes;
    c->fl_frames = 0;
    c->fl_bytes = 0;
    pthread_mutex_unlock(&c->mu);
    return Py_BuildValue("(KK)", fr, by);
}

static PyObject *py_set_ack_sink(PyObject *self, PyObject *args)
{
    PyObject *h;
    int on;
    (void)self;
    if (!PyArg_ParseTuple(args, "Op", &h, &on))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    pthread_mutex_lock(&c->mu);
    c->ack_sink = on;
    pthread_mutex_unlock(&c->mu);
    Py_RETURN_NONE;
}

static PyObject *py_take_acks(PyObject *self, PyObject *args)
{
    PyObject *h;
    (void)self;
    if (!PyArg_ParseTuple(args, "O", &h))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    uint8_t *data = NULL;
    size_t len = 0;
    pthread_mutex_lock(&c->mu);
    if (c->acks_len > 0 && !c->freed_bufs) {
        data = c->acks;
        len = c->acks_len;
        c->acks = NULL;
        c->acks_len = c->acks_cap = 0;
    }
    pthread_mutex_unlock(&c->mu);
    PyObject *out = PyList_New(0);
    if (out == NULL) {
        free(data);
        return NULL;
    }
    size_t off = 0;
    while (data != NULL && off + 4 <= len) {
        uint32_t sl = (uint32_t)data[off] | ((uint32_t)data[off + 1] << 8)
                      | ((uint32_t)data[off + 2] << 16)
                      | ((uint32_t)data[off + 3] << 24);
        off += 4;
        if (sl > len - off)
            break;
        PyObject *s = PyUnicode_DecodeUTF8((const char *)data + off,
                                           (Py_ssize_t)sl, NULL);
        if (s == NULL) {
            PyErr_Clear();
            off += sl;
            continue;
        }
        if (PyList_Append(out, s) < 0) {
            Py_DECREF(s);
            break;
        }
        Py_DECREF(s);
        off += sl;
    }
    free(data);
    return out;
}

static PyObject *py_queued(PyObject *self, PyObject *args)
{
    PyObject *h;
    (void)self;
    if (!PyArg_ParseTuple(args, "O", &h))
        return NULL;
    conn_t *c = conn_from_handle(h);
    if (c == NULL)
        return NULL;
    size_t q;
    int cb;
    pthread_mutex_lock(&c->mu);
    q = c->q_bytes;
    cb = c->cb_count;
    pthread_mutex_unlock(&c->mu);
    return Py_BuildValue("(ni)", (Py_ssize_t)q, cb);
}

static PyObject *py_kind_codes(PyObject *self, PyObject *args)
{
    (void)self;
    (void)args;
    PyObject *d = PyDict_New();
    if (d == NULL)
        return NULL;
    int i;
    for (i = 1; i <= RT_KIND_MAX; i++) {
        PyObject *v = PyLong_FromLong(i);
        if (v == NULL || PyDict_SetItemString(d, rt_kind_names[i], v) < 0) {
            Py_XDECREF(v);
            Py_DECREF(d);
            return NULL;
        }
        Py_DECREF(v);
    }
    return d;
}

static PyObject *py_shutdown_all(PyObject *self, PyObject *args)
{
    (void)self;
    (void)args;
    pthread_mutex_lock(&g_mu);
    conn_t *c = g_conns;
    pthread_mutex_unlock(&g_mu);
    while (c) {
        conn_close(c);
        c = c->next_all;
    }
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"attach", py_attach, METH_VARARGS,
     "attach(fd, callback, high_water=64MiB) -> handle: dup the fd and "
     "start the native reader/writer threads"},
    {"send", py_send, METH_VARARGS,
     "send(handle, frame_bytes) -> bool: enqueue one complete wire "
     "frame (blocks GIL-free past the high-water mark)"},
    {"cast", py_cast, METH_VARARGS,
     "cast(handle, kind_code, payload) -> bool: buffer one hot cast "
     "for the native coalescing flusher"},
    {"flush", py_flush, METH_VARARGS,
     "flush(handle): synchronously merge+frame the cast buffer into "
     "the send ring (ordering barrier before calls)"},
    {"drain", py_drain, METH_VARARGS,
     "drain(handle, timeout_s=2.0) -> bool: wait until the ring is "
     "empty and the writer idle"},
    {"close", py_close, METH_VARARGS,
     "close(handle): shut the lane down (threads exit, dup'd fd "
     "closes)"},
    {"take_counters", py_take_counters, METH_VARARGS,
     "take_counters(handle) -> (frames, bytes) delta of flusher-built "
     "frames since the last take"},
    {"set_ack_sink", py_set_ack_sink, METH_VARARGS,
     "set_ack_sink(handle, on): consume direct_ack casts natively"},
    {"take_acks", py_take_acks, METH_VARARGS,
     "take_acks(handle) -> list[str] of task ids acked since last take"},
    {"queued", py_queued, METH_VARARGS,
     "queued(handle) -> (ring_bytes, cast_count)"},
    {"kind_codes", py_kind_codes, METH_NOARGS,
     "kind_codes() -> {name: code} from the C enum (runtime cross-"
     "check against wirefmt.KIND_CODES)"},
    {"shutdown_all", py_shutdown_all, METH_NOARGS,
     "shutdown_all(): close every lane (atexit hook)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_evloop",
    "Native event-loop + dispatch core for the rpc hot lane.", -1,
    methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__evloop(void)
{
    PyObject *m = PyModule_Create(&moduledef);
    if (m == NULL)
        return NULL;
    PyModule_AddIntConstant(m, "WIRE_VERSION", WIRE_VERSION);
    PyModule_AddIntConstant(m, "CAST_BATCH_MAX", CAST_BATCH_MAX);
    return m;
}
