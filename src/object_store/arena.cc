// TPU-native object store arena: a shared-memory allocator used by the node's
// object store. Counterpart of the reference's plasma store arena
// (reference: src/ray/object_manager/plasma/store.h:55, dlmalloc.cc), redesigned:
// allocation policy lives in the head/store-owner process (single allocator,
// no cross-process locks on the hot path); the shm segment itself holds only
// object payloads, which workers map read-only and read zero-copy.
//
// Exposed as a C API consumed from Python via ctypes (ray_tpu/_private/shm_store.py).
//
// Design:
//  - best-fit free list with boundary-tag coalescing, 64-byte alignment
//    (64B keeps payloads cache-line aligned for memcpy and friendly to
//    jax.numpy zero-copy views)
//  - offsets (not pointers) returned, valid across processes mapping the
//    same segment
//  - O(log n) best-fit via std::map<size, offsets>

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <set>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;

struct Block {
  uint64_t offset;
  uint64_t size;
};

class Arena {
 public:
  Arena(uint64_t capacity) : capacity_(capacity) {
    free_by_size_.insert({capacity, 0});
    free_by_offset_[0] = capacity;
  }

  // Returns offset, or UINT64_MAX on OOM.
  uint64_t Alloc(uint64_t size) {
    if (size == 0) size = kAlign;
    size = (size + kAlign - 1) & ~(kAlign - 1);
    auto it = free_by_size_.lower_bound({size, 0});
    if (it == free_by_size_.end()) return UINT64_MAX;
    uint64_t blk_size = it->first, blk_off = it->second;
    free_by_size_.erase(it);
    free_by_offset_.erase(blk_off);
    if (blk_size > size) {
      uint64_t rem_off = blk_off + size, rem_size = blk_size - size;
      free_by_size_.insert({rem_size, rem_off});
      free_by_offset_[rem_off] = rem_size;
    }
    allocated_[blk_off] = size;
    in_use_ += size;
    return blk_off;
  }

  // Returns freed payload size, 0 if unknown offset.
  uint64_t Free(uint64_t offset) {
    auto it = allocated_.find(offset);
    if (it == allocated_.end()) return 0;
    uint64_t size = it->second;
    allocated_.erase(it);
    in_use_ -= size;
    // Coalesce with next free block.
    auto next = free_by_offset_.find(offset + size);
    if (next != free_by_offset_.end()) {
      size += next->second;
      free_by_size_.erase({next->second, next->first});
      free_by_offset_.erase(next);
    }
    // Coalesce with previous free block.
    if (!free_by_offset_.empty()) {
      auto prev = free_by_offset_.lower_bound(offset);
      if (prev != free_by_offset_.begin()) {
        --prev;
        if (prev->first + prev->second == offset) {
          offset = prev->first;
          size += prev->second;
          free_by_size_.erase({prev->second, prev->first});
          free_by_offset_.erase(prev);
        }
      }
    }
    free_by_size_.insert({size, offset});
    free_by_offset_[offset] = size;
    return size;
  }

  uint64_t InUse() const { return in_use_; }
  uint64_t Capacity() const { return capacity_; }
  uint64_t NumAllocated() const { return allocated_.size(); }
  // Largest contiguous free block (for fragmentation stats / spill decisions).
  uint64_t LargestFree() const {
    if (free_by_size_.empty()) return 0;
    return free_by_size_.rbegin()->first;
  }

 private:
  uint64_t capacity_;
  uint64_t in_use_ = 0;
  // {size, offset} ordered set → best-fit = lower_bound({size, 0}).
  std::set<std::pair<uint64_t, uint64_t>> free_by_size_;
  std::map<uint64_t, uint64_t> free_by_offset_;  // offset -> size
  std::unordered_map<uint64_t, uint64_t> allocated_;  // offset -> size
};

struct Store {
  Arena arena;
  void* base = nullptr;
  uint64_t capacity = 0;
  int fd = -1;
  std::string shm_name;
  Store(uint64_t cap) : arena(cap), capacity(cap) {}
};

}  // namespace

extern "C" {

// Create (owner) a shm segment of `capacity` bytes named `name` and an arena
// managing it. Returns opaque handle or nullptr.
void* store_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)capacity) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Store* s = new Store(capacity);
  s->base = base;
  s->fd = fd;
  s->shm_name = name;
  return s;
}

// Map an existing segment (worker side). The arena in this handle is unused.
void* store_attach(const char* name, uint64_t capacity) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store(capacity);
  s->base = base;
  s->fd = fd;
  return s;
}

void store_destroy(void* handle, int unlink) {
  Store* s = (Store*)handle;
  if (!s) return;
  munmap(s->base, s->capacity);
  close(s->fd);
  if (unlink && !s->shm_name.empty()) shm_unlink(s->shm_name.c_str());
  delete s;
}

uint64_t store_alloc(void* handle, uint64_t size) {
  return ((Store*)handle)->arena.Alloc(size);
}

uint64_t store_free(void* handle, uint64_t offset) {
  return ((Store*)handle)->arena.Free(offset);
}

void* store_base(void* handle) { return ((Store*)handle)->base; }
uint64_t store_in_use(void* handle) { return ((Store*)handle)->arena.InUse(); }
uint64_t store_capacity(void* handle) { return ((Store*)handle)->capacity; }
uint64_t store_num_objects(void* handle) { return ((Store*)handle)->arena.NumAllocated(); }
uint64_t store_largest_free(void* handle) { return ((Store*)handle)->arena.LargestFree(); }

}  // extern "C"
