// TPU-native cluster scheduler core: node feasibility + hybrid pack/spread
// node selection over fixed-point resource vectors.
//
// Counterpart of the reference's C++ scheduling stack
// (reference: src/ray/raylet/scheduling/cluster_resource_scheduler.h:46,
// policy/hybrid_scheduling_policy.h:50, common/scheduling/
// cluster_resource_data.h:36,290 with fixed_point.h arithmetic and interned
// resource ids, scheduling_ids.h). The head's Python ClusterScheduler mirrors
// membership/acquire/release into this core and delegates the per-task
// pick_node decision; semantics match the Python implementation exactly
// (max-over-resources utilization score, pack-below-threshold-else-spread,
// lexicographic node-id tie-break) so either side can serve as the oracle
// for the other in tests.
//
// Exposed as a C API consumed from Python via ctypes
// (ray_tpu/_private/native_sched.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

struct Node {
  std::string name;  // node id (tie-break key)
  bool alive = true;
  // resource id -> fixed-point amount
  std::map<uint32_t, int64_t> total;
  std::map<uint32_t, int64_t> avail;

  double Utilization() const {
    double best = 0.0;
    for (const auto& [rid, tot] : total) {
      if (tot <= 0) continue;
      auto it = avail.find(rid);
      int64_t av = (it == avail.end()) ? 0 : it->second;
      double used = static_cast<double>(tot - av);
      best = std::max(best, used / static_cast<double>(tot));
    }
    return best;
  }

  static bool Fits(const std::map<uint32_t, int64_t>& have, int n,
                   const uint32_t* ids, const int64_t* amts) {
    for (int i = 0; i < n; i++) {
      auto it = have.find(ids[i]);
      int64_t av = (it == have.end()) ? 0 : it->second;
      if (av < amts[i]) return false;
    }
    return true;
  }
};

struct Sched {
  double spread_threshold;
  std::map<int64_t, Node> nodes;  // key -> node
  uint64_t rr = 0;
};

// Deterministic 4-decimal utilization rounding, bit-identical to the
// Python oracle's _round4 (floor(x*1e4 + 0.5) over doubles).
int64_t Round4(double x) {
  return static_cast<int64_t>(std::floor(x * 10000.0 + 0.5));
}

// 64-bit FNV-1a, identical to scheduler._fnv1a (SPREAD tie-break).
uint64_t Fnv1a(const std::string& s) {
  uint64_t h = 0xCBF29CE484222325ULL;
  for (unsigned char c : s) {
    h ^= c;
    h *= 0x100000001B3ULL;
  }
  return h;
}

}  // namespace

extern "C" {

void* sched_create(double spread_threshold) {
  auto* s = new Sched();
  s->spread_threshold = spread_threshold;
  return s;
}

void sched_destroy(void* h) { delete static_cast<Sched*>(h); }

void sched_add_node(void* h, int64_t key, const char* name, int n,
                    const uint32_t* ids, const int64_t* totals,
                    const int64_t* avails) {
  auto* s = static_cast<Sched*>(h);
  Node node;
  node.name = name;
  for (int i = 0; i < n; i++) {
    node.total[ids[i]] = totals[i];
    node.avail[ids[i]] = avails[i];
  }
  s->nodes[key] = std::move(node);
}

void sched_remove_node(void* h, int64_t key) {
  static_cast<Sched*>(h)->nodes.erase(key);
}

void sched_set_alive(void* h, int64_t key, int alive) {
  auto* s = static_cast<Sched*>(h);
  auto it = s->nodes.find(key);
  if (it != s->nodes.end()) it->second.alive = alive != 0;
}

// 1 on success (resources deducted), 0 if they do not fit.
int sched_acquire(void* h, int64_t key, int n, const uint32_t* ids,
                  const int64_t* amts) {
  auto* s = static_cast<Sched*>(h);
  auto it = s->nodes.find(key);
  if (it == s->nodes.end()) return 0;
  if (!Node::Fits(it->second.avail, n, ids, amts)) return 0;
  for (int i = 0; i < n; i++) it->second.avail[ids[i]] -= amts[i];
  return 1;
}

void sched_release(void* h, int64_t key, int n, const uint32_t* ids,
                   const int64_t* amts) {
  auto* s = static_cast<Sched*>(h);
  auto it = s->nodes.find(key);
  if (it == s->nodes.end()) return;
  for (int i = 0; i < n; i++) it->second.avail[ids[i]] += amts[i];
}

// strategy: 0 = hybrid (default), 1 = SPREAD.
// Returns the chosen node key, or -1 if no node currently fits (the head
// queues the task either way, exactly like the Python policy).
int64_t sched_pick_node(void* h, int n, const uint32_t* ids,
                        const int64_t* amts, int strategy) {
  auto* s = static_cast<Sched*>(h);
  const Node* best = nullptr;
  int64_t best_key = -1;
  int64_t best_score = 0;

  std::vector<std::pair<int64_t, const Node*>> available;
  for (const auto& [key, node] : s->nodes) {
    if (!node.alive) continue;
    if (!Node::Fits(node.total, n, ids, amts)) continue;
    if (Node::Fits(node.avail, n, ids, amts)) available.emplace_back(key, &node);
  }
  if (available.empty()) return -1;

  if (strategy == 1) {  // SPREAD: least utilized, deterministic rr tie-break
    s->rr++;
    size_t m = available.size();
    uint64_t best_tb = 0;
    for (size_t i = 0; i < m; i++) {
      const auto& [key, node] = available[i];
      int64_t u = Round4(node->Utilization());
      uint64_t tb = (Fnv1a(node->name) + s->rr) % m;
      if (best == nullptr || u < best_score ||
          (u == best_score && tb < best_tb)) {
        best = node;
        best_key = key;
        best_score = u;
        best_tb = tb;
      }
    }
    return best_key;
  }

  // Hybrid: among nodes below threshold, PACK onto the most utilized
  // (lexicographically-largest name breaks ties); else SPREAD to least
  // utilized (lexicographically-smallest name breaks ties).
  std::vector<std::pair<int64_t, const Node*>> below;
  for (const auto& p : available)
    if (p.second->Utilization() < s->spread_threshold) below.push_back(p);

  if (!below.empty()) {
    for (const auto& [key, node] : below) {
      int64_t u = Round4(node->Utilization());
      if (best == nullptr || u > best_score ||
          (u == best_score && node->name > best->name)) {
        best = node;
        best_key = key;
        best_score = u;
      }
    }
    return best_key;
  }
  for (const auto& [key, node] : available) {
    int64_t u = Round4(node->Utilization());
    if (best == nullptr || u < best_score ||
        (u == best_score && node->name < best->name)) {
      best = node;
      best_key = key;
      best_score = u;
    }
  }
  return best_key;
}

double sched_utilization(void* h, int64_t key) {
  auto* s = static_cast<Sched*>(h);
  auto it = s->nodes.find(key);
  return it == s->nodes.end() ? -1.0 : it->second.Utilization();
}

int64_t sched_num_nodes(void* h) {
  return static_cast<int64_t>(static_cast<Sched*>(h)->nodes.size());
}

}  // extern "C"
