/* _specenc — compact binary codec for the control plane's hottest
 * payload, the TaskSpec (ray_tpu/_private/task_spec.py).
 *
 * Counterpart of the reference's compiled task-spec path: specs there
 * are protobufs built and parsed in C++ behind the Cython bridge
 * (reference: python/ray/_raylet.pyx:3709 submit_task building
 * TaskSpecification; src/ray/protobuf/common.proto TaskSpec). Here the
 * spec is a Python dataclass, and pickling it costs ~25-50 us per spec
 * across submit+dispatch — the dominant per-task head cost once result
 * payloads moved off the head. This module packs/unpacks the spec's
 * typed fields straight to bytes (tagged, varint-length, little
 * endian), leaving only the two arbitrary-object fields
 * (scheduling_strategy, runtime_env) to pickle — and those are None on
 * the hot path.
 *
 * Interface (see task_spec.pack_spec / unpack_spec wrappers):
 *   pack(tuple) -> bytes     tuple of tagged-codable values
 *   unpack(bytes) -> tuple
 *   pack_value(obj) -> bytes   one tagged value, no header (the binary
 *   unpack_value(bytes) -> obj wire frames supply their own header —
 *                              ray_tpu/_private/wirefmt.py)
 * Supported values: None, bool, int (64-bit signed), float, str,
 * bytes, list, tuple, dict with str keys, (str,int) pair. Anything
 * else raises TypeError — the wrapper falls back to pickle for the
 * whole spec/frame, so foreign producers (the C++ minipickle client)
 * and exotic field values keep working.
 *
 * The generic container tags (T_LIST/T_MAP/T_TUPLE) are ADDITIVE to
 * the v1 spec layout: pack() of a spec tuple emits exactly the same
 * bytes as before (all-numeric dicts keep the compact T_DSF form the
 * resources field always used), so packed specs stay byte-compatible
 * across the upgrade. ray_tpu/_private/wirefmt.py carries a pure-
 * Python codec for the identical byte format — mandatory fallback
 * where this extension can't build.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define MAGIC 0xA7u
#define VERSION 1u

/* Containers nest in practice <= ~6 deep (a frame body holding a list
 * of record dicts); the cap exists so a corrupt/hostile buffer cannot
 * recurse the C stack away. */
#define MAX_DEPTH 64

enum {
  T_NONE = 0,
  T_STR = 1,
  T_BYTES = 2,
  T_INT = 3,
  T_FLOAT = 4,
  T_TRUE = 5,
  T_FALSE = 6,
  T_LSTR = 7,    /* list of str */
  T_DSF = 8,     /* dict str -> float (all-numeric values) */
  T_PAIR_SI = 9, /* (str, int) — owner_addr */
  T_LIST = 10,   /* generic list: varint n, then n values */
  T_MAP = 11,    /* dict str -> any: varint n, then n (key, value) */
  T_TUPLE = 12,  /* generic tuple: varint n, then n values */
};

/* ---- growable output buffer ---- */

typedef struct {
  char *buf;
  Py_ssize_t len;
  Py_ssize_t cap;
} Out;

static int out_reserve(Out *o, Py_ssize_t extra) {
  if (o->len + extra <= o->cap) return 0;
  Py_ssize_t ncap = o->cap ? o->cap * 2 : 256;
  while (ncap < o->len + extra) ncap *= 2;
  char *nb = PyMem_Realloc(o->buf, ncap);
  if (!nb) {
    PyErr_NoMemory();
    return -1;
  }
  o->buf = nb;
  o->cap = ncap;
  return 0;
}

static int out_u8(Out *o, uint8_t v) {
  if (out_reserve(o, 1) < 0) return -1;
  o->buf[o->len++] = (char)v;
  return 0;
}

static int out_varint(Out *o, uint64_t v) {
  if (out_reserve(o, 10) < 0) return -1;
  while (v >= 0x80) {
    o->buf[o->len++] = (char)(v | 0x80);
    v >>= 7;
  }
  o->buf[o->len++] = (char)v;
  return 0;
}

static int out_bytes(Out *o, const char *p, Py_ssize_t n) {
  if (out_reserve(o, n) < 0) return -1;
  memcpy(o->buf + o->len, p, n);
  o->len += n;
  return 0;
}

static uint64_t zigzag(int64_t v) {
  return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
}

static int64_t unzigzag(uint64_t v) {
  return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
}

/* ---- encode one value ---- */

static int enc_str_body(Out *o, PyObject *s) {
  Py_ssize_t n;
  const char *p = PyUnicode_AsUTF8AndSize(s, &n);
  if (!p) return -1;
  if (out_varint(o, (uint64_t)n) < 0) return -1;
  return out_bytes(o, p, n);
}

static int enc_value(Out *o, PyObject *v, int depth) {
  if (depth > MAX_DEPTH) {
    PyErr_SetString(PyExc_TypeError, "specenc: nesting too deep");
    return -1;
  }
  if (v == Py_None) return out_u8(o, T_NONE);
  if (v == Py_True) return out_u8(o, T_TRUE);
  if (v == Py_False) return out_u8(o, T_FALSE);
  if (PyUnicode_Check(v)) {
    if (out_u8(o, T_STR) < 0) return -1;
    return enc_str_body(o, v);
  }
  if (PyBytes_Check(v)) {
    if (out_u8(o, T_BYTES) < 0) return -1;
    if (out_varint(o, (uint64_t)PyBytes_GET_SIZE(v)) < 0) return -1;
    return out_bytes(o, PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v));
  }
  if (PyLong_Check(v)) {
    int overflow = 0;
    int64_t i = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow || (i == -1 && PyErr_Occurred())) {
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "int out of 64-bit range");
      return -1;
    }
    if (out_u8(o, T_INT) < 0) return -1;
    return out_varint(o, zigzag(i));
  }
  if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    if (out_u8(o, T_FLOAT) < 0) return -1;
    return out_bytes(o, (const char *)&d, 8);
  }
  if (PyList_Check(v)) {
    Py_ssize_t n = PyList_GET_SIZE(v);
    /* All-str lists keep the compact T_LSTR tag (the v1 spec layout
     * for deps/return_ids); anything else takes the generic tag. */
    int all_str = 1;
    for (Py_ssize_t k = 0; k < n; k++)
      if (!PyUnicode_Check(PyList_GET_ITEM(v, k))) {
        all_str = 0;
        break;
      }
    if (out_u8(o, all_str ? T_LSTR : T_LIST) < 0) return -1;
    if (out_varint(o, (uint64_t)n) < 0) return -1;
    for (Py_ssize_t k = 0; k < n; k++) {
      PyObject *it = PyList_GET_ITEM(v, k);
      if (all_str ? enc_str_body(o, it) < 0
                  : enc_value(o, it, depth + 1) < 0)
        return -1;
    }
    return 0;
  }
  if (PyDict_Check(v)) {
    /* All-numeric (non-bool) values keep the compact T_DSF float map
     * (the v1 layout for the resources field — ints become floats,
     * exactly as before); mixed values take the generic map, which
     * preserves each value's type. */
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    int all_num = 1;
    while (PyDict_Next(v, &pos, &key, &val)) {
      if (!PyUnicode_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "dict keys must be str");
        return -1;
      }
      if (!PyFloat_Check(val) && !(PyLong_Check(val) && !PyBool_Check(val)))
        all_num = 0;
    }
    if (out_u8(o, all_num ? T_DSF : T_MAP) < 0) return -1;
    if (out_varint(o, (uint64_t)PyDict_GET_SIZE(v)) < 0) return -1;
    pos = 0;
    while (PyDict_Next(v, &pos, &key, &val)) {
      if (enc_str_body(o, key) < 0) return -1;
      if (all_num) {
        double d;
        if (PyFloat_Check(val))
          d = PyFloat_AS_DOUBLE(val);
        else {
          d = PyLong_AsDouble(val);
          if (d == -1.0 && PyErr_Occurred()) return -1;
        }
        if (out_bytes(o, (const char *)&d, 8) < 0) return -1;
      } else {
        if (enc_value(o, val, depth + 1) < 0) return -1;
      }
    }
    return 0;
  }
  if (PyTuple_Check(v)) {
    if (PyTuple_GET_SIZE(v) == 2 && PyUnicode_Check(PyTuple_GET_ITEM(v, 0)) &&
        PyLong_Check(PyTuple_GET_ITEM(v, 1)) &&
        !PyBool_Check(PyTuple_GET_ITEM(v, 1))) {
      int64_t i = PyLong_AsLongLong(PyTuple_GET_ITEM(v, 1));
      if (i == -1 && PyErr_Occurred()) return -1;
      if (out_u8(o, T_PAIR_SI) < 0) return -1;
      if (enc_str_body(o, PyTuple_GET_ITEM(v, 0)) < 0) return -1;
      return out_varint(o, zigzag(i));
    }
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    if (out_u8(o, T_TUPLE) < 0) return -1;
    if (out_varint(o, (uint64_t)n) < 0) return -1;
    for (Py_ssize_t k = 0; k < n; k++)
      if (enc_value(o, PyTuple_GET_ITEM(v, k), depth + 1) < 0) return -1;
    return 0;
  }
  PyErr_Format(PyExc_TypeError, "specenc: unsupported value type %s",
               Py_TYPE(v)->tp_name);
  return -1;
}

/* ---- decode ---- */

typedef struct {
  const char *p;
  const char *end;
} In;

static int in_u8(In *in, uint8_t *out) {
  if (in->p >= in->end) {
    PyErr_SetString(PyExc_ValueError, "specenc: truncated");
    return -1;
  }
  *out = (uint8_t)*in->p++;
  return 0;
}

static int in_varint(In *in, uint64_t *out) {
  uint64_t v = 0;
  int shift = 0;
  for (;;) {
    uint8_t b;
    if (in_u8(in, &b) < 0) return -1;
    v |= ((uint64_t)(b & 0x7F)) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 63) {
      PyErr_SetString(PyExc_ValueError, "specenc: varint overflow");
      return -1;
    }
  }
  *out = v;
  return 0;
}

static int in_span(In *in, uint64_t n, const char **out) {
  if ((uint64_t)(in->end - in->p) < n) {
    PyErr_SetString(PyExc_ValueError, "specenc: truncated");
    return -1;
  }
  *out = in->p;
  in->p += n;
  return 0;
}

static PyObject *dec_str(In *in) {
  uint64_t n;
  const char *p;
  if (in_varint(in, &n) < 0 || in_span(in, n, &p) < 0) return NULL;
  return PyUnicode_DecodeUTF8(p, (Py_ssize_t)n, "strict");
}

/* Preallocating containers from a length prefix lets a corrupt frame
 * demand petabytes; every element costs >= min_per bytes, so a count
 * exceeding the remaining buffer is provably truncation/corruption. */
static int in_count(In *in, uint64_t *n, uint64_t min_per) {
  if (in_varint(in, n) < 0) return -1;
  if (min_per && *n > (uint64_t)(in->end - in->p) / min_per) {
    PyErr_SetString(PyExc_ValueError, "specenc: implausible count");
    return -1;
  }
  return 0;
}

static PyObject *dec_value(In *in, int depth) {
  uint8_t tag;
  if (depth > MAX_DEPTH) {
    PyErr_SetString(PyExc_ValueError, "specenc: nesting too deep");
    return NULL;
  }
  if (in_u8(in, &tag) < 0) return NULL;
  switch (tag) {
    case T_NONE:
      Py_RETURN_NONE;
    case T_TRUE:
      Py_RETURN_TRUE;
    case T_FALSE:
      Py_RETURN_FALSE;
    case T_STR:
      return dec_str(in);
    case T_BYTES: {
      uint64_t n;
      const char *p;
      if (in_varint(in, &n) < 0 || in_span(in, n, &p) < 0) return NULL;
      return PyBytes_FromStringAndSize(p, (Py_ssize_t)n);
    }
    case T_INT: {
      uint64_t v;
      if (in_varint(in, &v) < 0) return NULL;
      return PyLong_FromLongLong(unzigzag(v));
    }
    case T_FLOAT: {
      const char *p;
      double d;
      if (in_span(in, 8, &p) < 0) return NULL;
      memcpy(&d, p, 8);
      return PyFloat_FromDouble(d);
    }
    case T_LSTR:
    case T_LIST:
    case T_TUPLE: {
      uint64_t n;
      if (in_count(in, &n, 1) < 0) return NULL;
      PyObject *lst = (tag == T_TUPLE) ? PyTuple_New((Py_ssize_t)n)
                                       : PyList_New((Py_ssize_t)n);
      if (!lst) return NULL;
      for (uint64_t k = 0; k < n; k++) {
        PyObject *s = (tag == T_LSTR) ? dec_str(in)
                                      : dec_value(in, depth + 1);
        if (!s) {
          Py_DECREF(lst);
          return NULL;
        }
        if (tag == T_TUPLE)
          PyTuple_SET_ITEM(lst, (Py_ssize_t)k, s);
        else
          PyList_SET_ITEM(lst, (Py_ssize_t)k, s);
      }
      return lst;
    }
    case T_MAP: {
      uint64_t n;
      if (in_count(in, &n, 2) < 0) return NULL;
      PyObject *d = PyDict_New();
      if (!d) return NULL;
      for (uint64_t k = 0; k < n; k++) {
        PyObject *key = dec_str(in);
        PyObject *val = key ? dec_value(in, depth + 1) : NULL;
        if (!val || PyDict_SetItem(d, key, val) < 0) {
          Py_XDECREF(key);
          Py_XDECREF(val);
          Py_DECREF(d);
          return NULL;
        }
        Py_DECREF(key);
        Py_DECREF(val);
      }
      return d;
    }
    case T_DSF: {
      uint64_t n;
      if (in_count(in, &n, 9) < 0) return NULL;
      PyObject *d = PyDict_New();
      if (!d) return NULL;
      for (uint64_t k = 0; k < n; k++) {
        PyObject *key = dec_str(in);
        if (!key) {
          Py_DECREF(d);
          return NULL;
        }
        const char *p;
        double val;
        if (in_span(in, 8, &p) < 0) {
          Py_DECREF(key);
          Py_DECREF(d);
          return NULL;
        }
        memcpy(&val, p, 8);
        PyObject *f = PyFloat_FromDouble(val);
        if (!f || PyDict_SetItem(d, key, f) < 0) {
          Py_XDECREF(f);
          Py_DECREF(key);
          Py_DECREF(d);
          return NULL;
        }
        Py_DECREF(key);
        Py_DECREF(f);
      }
      return d;
    }
    case T_PAIR_SI: {
      PyObject *s = dec_str(in);
      if (!s) return NULL;
      uint64_t v;
      if (in_varint(in, &v) < 0) {
        Py_DECREF(s);
        return NULL;
      }
      PyObject *i = PyLong_FromLongLong(unzigzag(v));
      if (!i) {
        Py_DECREF(s);
        return NULL;
      }
      PyObject *t = PyTuple_Pack(2, s, i);
      Py_DECREF(s);
      Py_DECREF(i);
      return t;
    }
    default:
      PyErr_Format(PyExc_ValueError, "specenc: bad tag %d", (int)tag);
      return NULL;
  }
}

/* ---- module functions ---- */

static PyObject *specenc_pack(PyObject *self, PyObject *arg) {
  if (!PyTuple_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "pack() expects a tuple");
    return NULL;
  }
  Out o = {0};
  Py_ssize_t n = PyTuple_GET_SIZE(arg);
  if (out_u8(&o, MAGIC) < 0 || out_u8(&o, VERSION) < 0 ||
      out_varint(&o, (uint64_t)n) < 0)
    goto fail;
  for (Py_ssize_t k = 0; k < n; k++)
    if (enc_value(&o, PyTuple_GET_ITEM(arg, k), 0) < 0) goto fail;
  {
    PyObject *res = PyBytes_FromStringAndSize(o.buf, o.len);
    PyMem_Free(o.buf);
    return res;
  }
fail:
  PyMem_Free(o.buf);
  return NULL;
}

static PyObject *specenc_unpack(PyObject *self, PyObject *arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
  In in = {(const char *)view.buf, (const char *)view.buf + view.len};
  uint8_t magic, version;
  uint64_t n;
  PyObject *tup = NULL;
  if (in_u8(&in, &magic) < 0 || in_u8(&in, &version) < 0) goto done;
  if (magic != MAGIC || version != VERSION) {
    PyErr_SetString(PyExc_ValueError, "specenc: bad magic/version");
    goto done;
  }
  if (in_varint(&in, &n) < 0) goto done;
  if (n > 4096) {
    PyErr_SetString(PyExc_ValueError, "specenc: implausible field count");
    goto done;
  }
  tup = PyTuple_New((Py_ssize_t)n);
  if (!tup) goto done;
  for (uint64_t k = 0; k < n; k++) {
    PyObject *v = dec_value(&in, 0);
    if (!v) {
      Py_CLEAR(tup);
      goto done;
    }
    PyTuple_SET_ITEM(tup, (Py_ssize_t)k, v);
  }
done:
  PyBuffer_Release(&view);
  return tup;
}

static PyObject *specenc_pack_value(PyObject *self, PyObject *arg) {
  Out o = {0};
  if (enc_value(&o, arg, 0) < 0) {
    PyMem_Free(o.buf);
    return NULL;
  }
  PyObject *res = PyBytes_FromStringAndSize(o.buf, o.len);
  PyMem_Free(o.buf);
  return res;
}

static PyObject *specenc_unpack_value(PyObject *self, PyObject *arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
  In in = {(const char *)view.buf, (const char *)view.buf + view.len};
  PyObject *v = dec_value(&in, 0);
  if (v && in.p != in.end) {
    /* A decoder that silently ignores trailing bytes would mask a
     * misframed stream; the wire layer treats this as corruption. */
    Py_CLEAR(v);
    PyErr_SetString(PyExc_ValueError, "specenc: trailing bytes");
  }
  PyBuffer_Release(&view);
  return v;
}

static PyMethodDef methods[] = {
    {"pack", specenc_pack, METH_O,
     "pack(tuple) -> bytes: tagged compact encoding"},
    {"unpack", specenc_unpack, METH_O,
     "unpack(bytes) -> tuple: inverse of pack"},
    {"pack_value", specenc_pack_value, METH_O,
     "pack_value(obj) -> bytes: one tagged value, no header"},
    {"unpack_value", specenc_unpack_value, METH_O,
     "unpack_value(bytes) -> obj: inverse of pack_value"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_specenc",
    "compact TaskSpec field codec (C fast path)", -1, methods,
};

PyMODINIT_FUNC PyInit__specenc(void) { return PyModule_Create(&moduledef); }
