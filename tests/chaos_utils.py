"""Shared helpers for the chaos-plane test matrix.

Modeled on the reference's fault-injection strategy (SURVEY.md §4 —
RayletKiller / WorkerKillerActor in _private/test_utils.py:1449): spec
builders for the deterministic fault plane (faultinject.py), agent
process management for whole-node death tests, and busy-worker killers.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import ray_tpu


def drop_delay_spec(peer: str = "node_agent", *, drop: float = 0.05,
                    delay_ms: float = 50.0, seed: int = 7,
                    kind: str = "*", direction: str = "send") -> dict:
    """The acceptance-criteria spec: probabilistic drop + added latency
    on every message matching ``peer``/``kind``."""
    return {"seed": seed, "rules": [
        {"peer": peer, "kind": kind, "direction": direction,
         "drop": drop, "delay_ms": delay_ms},
    ]}


def partition_spec(kind: str, peer: str = "", seed: int = 11) -> dict:
    """Hard partition: drop EVERYTHING matching the filter."""
    return {"seed": seed, "rules": [
        {"peer": peer, "kind": kind, "partition": True},
    ]}


def spec_env(spec: dict, base: "dict | None" = None) -> dict:
    """Env for a subprocess that should boot with the fault plane on."""
    env = dict(os.environ if base is None else base)
    env["RAY_TPU_FAULT_SPEC"] = json.dumps(spec)
    return env


def start_agent(address: str, *, node_id: str, num_cpus: int = 4,
                resources: "dict | None" = None,
                force_remote: bool = True,
                extra_env: "dict | None" = None) -> subprocess.Popen:
    """One node agent joining ``address`` (same pattern as
    test_multinode, plus an env hook for fault specs)."""
    cmd = [
        sys.executable, "-m", "ray_tpu._private.node_agent",
        "--address", address, "--num-cpus", str(num_cpus),
        "--node-id", node_id,
    ]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    if force_remote:
        cmd.append("--force-remote-objects")
    env = dict(os.environ)
    env.pop("RAY_TPU_REMOTE", None)
    env.pop("RAY_TPU_FAULT_SPEC", None)
    env.update(extra_env or {})
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def stop_agent(agent: "subprocess.Popen | None") -> None:
    if agent is not None and agent.poll() is None:
        agent.kill()
        try:
            agent.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


def wait_nodes(n: int, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = [x for x in ray_tpu.nodes() if x["alive"]]
        if len(alive) >= n:
            return
        time.sleep(0.2)
    raise TimeoutError(f"cluster never reached {n} nodes: {ray_tpu.nodes()}")


def wait_alive_nodes_at_most(n: int, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = [x for x in ray_tpu.nodes() if x["alive"]]
        if len(alive) <= n:
            return
        time.sleep(0.2)
    raise TimeoutError(
        f"node never declared dead: {[x for x in ray_tpu.nodes() if x['alive']]}")


def kill_actor_worker(actor_id: str, deadline_s: float = 20.0,
                      sleep_s: float = 0.1) -> bool:
    """SIGKILL the worker process hosting ``actor_id`` (serve chaos:
    replica death mid-request). Returns True if a process was killed."""
    from ray_tpu.util import state as us

    my_pid = os.getpid()
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for w in us.list_workers():
            if w.get("actor_id") == actor_id and w.get("pid") not in (None,
                                                                      my_pid):
                try:
                    os.kill(w["pid"], signal.SIGKILL)
                    return True
                except ProcessLookupError:
                    return False
        time.sleep(sleep_s)
    return False


def kill_busy_workers(count: int, deadline_s: float = 20.0,
                      sleep_s: float = 0.2) -> int:
    """SIGKILL up to ``count`` busy non-actor workers (never ourselves).
    Returns how many were actually killed."""
    from ray_tpu.util import state as us

    my_pid = os.getpid()
    killed = 0
    deadline = time.monotonic() + deadline_s
    while killed < count and time.monotonic() < deadline:
        busy = [w for w in us.list_workers(filters=[("busy", "=", "True")])
                if w["pid"] not in (None, my_pid) and not w["actor_id"]]
        if busy:
            try:
                os.kill(busy[0]["pid"], signal.SIGKILL)
                killed += 1
            except ProcessLookupError:
                pass
        time.sleep(sleep_s)
    return killed
