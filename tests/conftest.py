"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-host TPU topology is
simulated the way the reference simulates multi-node clusters with in-process
fixtures — SURVEY.md §4 "lesson"). Must be set before jax is imported
anywhere in the process; worker subprocesses inherit the env and therefore
also stay off the real TPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Arm the lock-order witness for the whole tier-1 run (and, via env
# inheritance, every worker subprocess the tests spawn). Set before
# the hermetic re-exec below so it survives the execve; the session
# fixture at the bottom fails the run if any acquisition-order cycle
# (potential deadlock) was observed. Opt out with
# RAY_TPU_LOCK_WITNESS=0.
os.environ.setdefault("RAY_TPU_LOCK_WITNESS", "1")

import pytest  # noqa: E402


def pytest_configure(config):
    """Hermeticity: if this interpreter inherited a TPU device-plugin
    site hook (its gate vars are set), env pins are NOT enough — the
    hook wraps backend init and can hang even JAX_PLATFORMS=cpu when the
    hardware path is degraded. Re-exec the whole pytest run once under a
    sanitized environment (plugin gates unset, startup-hook PYTHONPATH
    entries stripped, cpu pinned) so tests never depend on TPU
    reachability.

    Done from pytest_configure, not conftest import: initial conftests
    load inside the capture manager's global-capture window, where fds
    1/2 point at capture temp files — an exec there would silently send
    the whole run's output into them. By configure time capture is
    suspended and the real fds are back.
    """
    from ray_tpu._private.hermetic import hermetic_cpu_env, is_hermetic_cpu

    if not is_hermetic_cpu() and os.environ.get("_RAY_TPU_TEST_REEXEC") != "1":
        env = hermetic_cpu_env(8)
        env["_RAY_TPU_TEST_REEXEC"] = "1"
        # -m pytest, not argv[0]: pytest's __main__.py run as a script
        # path loses console output.
        os.execve(sys.executable,
                  [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # The env vars alone are not enough when a sitecustomize has already
    # imported jax (its config defaults are then frozen from the original
    # environment). jax.config.update rewrites the live config, and the
    # backend has not been initialized yet at configure time (test
    # modules import later, during collection).
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # Older jax (< 0.5) has no jax_num_cpu_devices option; the
        # XLA_FLAGS host_platform_device_count pin set above (before
        # any backend init) provides the same 8-device CPU mesh.
        pass
    assert jax.device_count() == 8, (
        "tests require the virtual 8-device CPU mesh, got "
        f"{jax.devices()}"
    )

    # Native artifacts are not committed (ADVICE r3): build them from
    # src/ before any test imports a ctypes loader.
    from ray_tpu._private.native_build import ensure_native

    ensure_native()


# --- test tiers (VERDICT r4 #10; reference: Bazel size/team tags,
# python/ray/tests/BUILD:21-92). Whole modules land in a tier here;
# individual tests can still carry @pytest.mark.slow/chaos/scale inline.
# Everything not in a slower tier is `fast`, so `-m fast` covers every
# component's core paths in a sub-5-minute inner loop.

_CHAOS_MODULES = {
    "test_stress",
}
_SCALE_MODULES = {
    "test_scale_envelope",
}
_SLOW_MODULES: set = set()

# Individual tests >= ~4 s measured (full-suite --durations=0 run,
# benchmarks/tier_from_durations.py proposes updates). Marking tests,
# not modules, keeps every component represented in the fast tier.
# test_core::test_simple_task is deliberately NOT here: its measured
# 60 s is one-time cluster warmup (native build + worker jax imports)
# that whichever test runs first would pay anyway, and it is the canary.
# Re-measured 2026-08 (chaos-plane PR): fast tier = 232 s reported /
# <4 min wall on an undisturbed run — under the 300 s budget with no
# further demotions; the chaos-plane workload matrix is slow-marked
# inline (tests/test_chaos_plane.py), its SIGKILL/partition recovery
# tests deliberately stay fast. Measure on an idle box only: parallel
# pytest sessions inflate sub-second tests to tens of seconds.
_SLOW_TESTS = {
    "test_graft_entry::test_dryrun_multichip_8",
    "test_train_elastic::test_elastic_restart_shrinks_world",
    "test_streaming_generators::test_error_mid_stream",
    "test_core::test_actor_handle_passing",
    "test_train_integrations::test_tensorflow_trainer_multiworker",
    "test_rllib_dreamerv3::test_dreamerv3_trains_and_losses_improve",
    "test_data::test_from_tf",
    "test_train_integrations::test_transformers_report_callback",
    "test_ops_parallel::test_ring_attention_grads_flow",
    "test_models::test_grad_accumulation_matches_full_batch",
    "test_models::test_fused_ce_matches_checkpoint_ce",
    "test_train_torch::test_torch_trainer_ddp_converges_and_syncs",
    "test_dashboard_data::test_dashboard_memory_profiler",
    "test_rllib::test_algorithm_is_tune_trainable",
    "test_models::test_sharded_train_step[gpt2]",
    "test_models::test_sharded_train_step[llama]",
    "test_rllib::test_ppo_remote_env_runners",
    "test_rllib_offline::test_cql_learns_expert_policy_offline",
    "test_rllib::test_impala_trains_with_async_runners",
    "test_moe::test_expert_parallel_train_step_on_mesh",
    "test_rllib_algos::test_appo_runs_cartpole",
    "test_models::test_chunked_ce_matches_dense_loss",
    "test_rllib_dreamerv3::test_dreamerv3_checkpoint_roundtrip",
    "test_train::test_trainer_dp_two_workers_loss_drops",
    "test_llm_e2e::test_openai_http_endpoints",
    "test_multislice::test_hierarchical_train_step_2x4",
    "test_models::test_fused_clip_adamw_matches_optax",
    "test_moe::test_moe_forward_loss_and_grads_finite",
    "test_models::test_fused_adamw_in_train_step",
    "test_doc_examples::test_doc_example_runs[llm_quickstart.py]",
    "test_models::test_grad_accumulation_moe_keeps_router_aux",
    "test_doc_examples::test_doc_example_runs[train_torch_quickstart.py]",
    "test_llm_sampling::test_serving_n_and_best_of",
    "test_doc_examples::test_doc_example_runs[rllib_quickstart.py]",
    "test_head_ft::test_kill_head_restart_recovers",
    "test_llm_sampling::test_batched_prefill_matches_sequential",
    "test_models::test_train_step_learns[gpt2]",
    "test_models::test_decode_matches_forward[gpt2]",
    "test_ops_parallel::test_ring_attention_matches_reference[True]",
    "test_llm::test_single_request_roundtrip",
    "test_ops_parallel::test_flash_backward_kernels_multiblock[True]",
    "test_llm_spec::TestSpeculativeDecoding::test_smaller_draft_architecture",
    "test_fault_tolerance::test_reconstruction_cap",
    "test_rllib_offline::test_cql_checkpoint_restores_targets_and_bc_counter",
    "test_dashboard_data::test_dashboard_sampling_profiler",
    "test_device_channel::test_device_edge_between_actors",
    "test_llm::test_tp2_decode_matches_tp1",
    "test_models::test_decode_matches_forward[llama]",
    "test_ops_parallel::test_flash_gradients_match_reference",
    "test_jax_distributed::test_two_process_jax_cluster",
    "test_rllib_algos::test_sac_runs_pendulum",
    "test_doc_examples::test_doc_example_runs[device_channel_pipeline.py]",
    "test_models::test_train_step_learns[llama]",
    "test_device_channel::test_device_edge_repeated_executions",
    "test_tune::test_asha_stops_bad_trials",
    "test_moe::test_moe_single_expert_matches_dense_swiglu",
    "test_tune::test_pbt_synch_exploits_better_config",
    "test_rllib_multi_agent::test_multi_agent_ppo_learns_signal_match",
    "test_jax_distributed::test_jax_trainer_distributed_on",
    "test_head_ft::test_external_store_head_ha",
    "test_rllib::test_ppo_learns_cartpole",
    "test_device_channel::test_device_edge_pytree_and_driver_read",
    "test_llm_spec::TestSpeculativeDecoding::test_near_cache_capacity",
    "test_llm_spec::TestSpeculativeDecoding::"
    "test_perfect_draft_matches_and_accelerates",
    "test_llm::test_pp2_decode_matches_pp1",
    "test_core::test_out_of_order_actor_execution",
    "test_multinode::test_node_label_scheduling",
    "test_models::test_loss_mask",
    "test_llm_e2e::test_batch_inference_over_dataset",
    "test_cpp_api::test_cpp_frontend_builds_and_runs",
    # 2-4 s band (same measurement run):
    "test_tune_hyperband::test_hyperband_prunes_to_best",
    "test_llm_prefix::TestChunkedPrefill::test_llama_arch_rope_offsets",
    "test_ops_parallel::test_ring_attention_matches_reference[False]",
    "test_llm::test_continuous_batching_staggered_admission",
    "test_llm_lora::test_adapter_changes_output_base_unaffected",
    "test_refcount_borrowing::test_ref_in_actor_state_outlives_passing_task",
    "test_tune::test_max_concurrent_trials_and_time_fields",
    "test_llm_prefix::TestChunkedPrefill::test_matches_whole_prompt_prefill",
    "test_ownership::test_result_lands_in_owner_store",
    "test_llm::test_greedy_matches_reference_generate",
    "test_async_actors::test_cancel_queued_actor_call",
    "test_refcount_borrowing::test_ref_returned_inside_container",
    "test_fault_tolerance::test_reconstruction_is_transparent_to_wait",
    "test_ownership::test_dependent_task_fetches_from_owner",
    "test_models::test_generate[gpt2]",
    "test_ownership::test_fire_and_forget_then_dependent",
    "test_rllib::test_env_runner_batch_layout",
    "test_llm_prefix::TestChunkedPrefill::test_near_cache_capacity",
    "test_moe::test_capacity_overflow_drops_tokens",
    "test_refcount_borrowing::test_borrow_churn_stress",
    "test_ops_parallel::test_spmd_pipeline_matches_sequential",
    "test_multinode::test_p2p_object_transfer_bypasses_head",
    "test_tune::test_tuner_function_trainable",
    "test_multinode::test_node_death_fails_over",
    "test_runtime_env::test_conda_lite_venv_isolated_version",
    "test_refcount_borrowing::test_owner_death_with_live_borrowers",
    "test_ownership::test_error_results_via_owner_plane",
    "test_cli_job_serve::test_serve_deploy_status_shutdown",
    "test_ops_parallel::test_blockwise_matches_reference",
    "test_rllib_offline::test_marwil_beats_bc_on_mixed_data",
    "test_models::test_generate[llama]",
    "test_rllib_connectors::test_ppo_with_connectors_learns",
    "test_models::test_causality[gpt2]",
    "test_worker_hermetic::test_tpu_worker_keeps_plugin_and_pins_chips",
    "test_ownership::test_big_results_take_store_path",
    "test_rllib::test_rl_module_forward_and_weights",
    "test_channels::test_compiled_dag_function_node_falls_back",
    "test_moe::test_topk_dispatch_shapes_and_mass",
    "test_head_ft::test_head_restart_readopts_node_agent",
    "test_models::test_forward_shapes[llama]",
    "test_collective::test_broadcast_slow_joiner",
    "test_worker_hermetic::test_chipless_worker_strips_plugin_hooks",
    "test_refcount_borrowing::test_nested_arg_ref_survives_fire_and_forget",
    "test_rllib::test_compute_single_action_after_training",
    "test_ops_parallel::test_blockwise_noncausal_with_padding",
    "test_llm::test_default_config_works_with_byte_tokenizer",
    "test_dashboard_data::test_from_huggingface_roundtrip",
    "test_rllib::test_evaluate_and_evaluation_interval",
    "test_rllib::test_ppo_checkpoint_roundtrip",
    "test_models::test_forward_shapes[gpt2]",
    "test_rllib_multi_agent::test_multi_agent_shared_policy_and_checkpoint",
    "test_rllib_algos::test_dqn_learns_cartpole",
    "test_rllib_offline::test_marwil_beta_zero_is_bc",
    "test_review_regressions::test_pipelined_nested_get_no_deadlock",
    "test_rllib_dreamerv3::test_symlog_twohot_roundtrip",
    "test_zero_copy::test_nested_and_multiple_arrays_share_one_pin",
    "test_ops_parallel::test_flash_backward_kernels_multiblock[False]",
    "test_train_torch::test_torch_trainer_single_worker_no_pg",
    "test_llm_prefix::TestPrefixCache::test_multi_slot_interleaving",
    "test_llm_prefix::TestPrefixCache::test_shared_prefix_divergent_tail",
    "test_serve::test_autoscaling_scales_up_under_load",
    "test_doc_examples::test_doc_example_runs[serve_quickstart.py]",
    "test_doc_examples::test_doc_example_runs[tune_quickstart.py]",
    "test_core::test_duplicate_pending_dep_runs_once",
    "test_cpp_client::test_malformed_path_func_id_errors",
    "test_util_bridges::test_pool_map_and_starmap",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rpartition(".")[2]
        if mod in _CHAOS_MODULES:
            item.add_marker(pytest.mark.chaos)
        elif mod in _SCALE_MODULES:
            item.add_marker(pytest.mark.scale)
        elif mod in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
        else:
            # item.name carries parametrization ([gpt2]); class-scoped
            # tests join as Class::name to match the set's keys.
            cls = getattr(item, "cls", None)
            key = (f"{mod}::{cls.__name__}::{item.name}" if cls
                   else f"{mod}::{item.name}")
            if key in _SLOW_TESTS:
                item.add_marker(pytest.mark.slow)
        if not any(m.name in ("slow", "chaos", "scale")
                   for m in item.iter_markers()):
            item.add_marker(pytest.mark.fast)


@pytest.fixture(scope="session", autouse=True)
def _lock_witness_gate():
    """Fail the session if the armed lock witness saw an acquisition-
    order cycle anywhere in the run — a potential deadlock even if the
    wedging interleaving never fired (docs/INVARIANTS.md, RT-L003's
    dynamic complement)."""
    yield
    from ray_tpu._private import lockwitness

    if lockwitness.installed() and lockwitness.cycles():
        raise AssertionError(lockwitness.report())


@pytest.fixture
def ray_start():
    """Fresh single-node cluster per test (reference analogue:
    ray_start_regular in python/ray/tests/conftest.py:580)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def ray_start_shared():
    """Shared cluster for cheap read-only tests."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
