"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-host TPU topology is
simulated the way the reference simulates multi-node clusters with in-process
fixtures — SURVEY.md §4 "lesson"). Must be set before jax is imported
anywhere in the process; worker subprocesses inherit the env and therefore
also stay off the real TPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    """Hermeticity: if this interpreter inherited a TPU device-plugin
    site hook (its gate vars are set), env pins are NOT enough — the
    hook wraps backend init and can hang even JAX_PLATFORMS=cpu when the
    hardware path is degraded. Re-exec the whole pytest run once under a
    sanitized environment (plugin gates unset, startup-hook PYTHONPATH
    entries stripped, cpu pinned) so tests never depend on TPU
    reachability.

    Done from pytest_configure, not conftest import: initial conftests
    load inside the capture manager's global-capture window, where fds
    1/2 point at capture temp files — an exec there would silently send
    the whole run's output into them. By configure time capture is
    suspended and the real fds are back.
    """
    from ray_tpu._private.hermetic import hermetic_cpu_env, is_hermetic_cpu

    if not is_hermetic_cpu() and os.environ.get("_RAY_TPU_TEST_REEXEC") != "1":
        env = hermetic_cpu_env(8)
        env["_RAY_TPU_TEST_REEXEC"] = "1"
        # -m pytest, not argv[0]: pytest's __main__.py run as a script
        # path loses console output.
        os.execve(sys.executable,
                  [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # The env vars alone are not enough when a sitecustomize has already
    # imported jax (its config defaults are then frozen from the original
    # environment). jax.config.update rewrites the live config, and the
    # backend has not been initialized yet at configure time (test
    # modules import later, during collection).
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
    assert jax.device_count() == 8, (
        "tests require the virtual 8-device CPU mesh, got "
        f"{jax.devices()}"
    )

    # Native artifacts are not committed (ADVICE r3): build them from
    # src/ before any test imports a ctypes loader.
    from ray_tpu._private.native_build import ensure_native

    ensure_native()


# --- test tiers (VERDICT r4 #10; reference: Bazel size/team tags,
# python/ray/tests/BUILD:21-92). Whole modules land in a tier here;
# individual tests can still carry @pytest.mark.slow/chaos/scale inline.
# Everything not in a slower tier is `fast`, so `-m fast` covers every
# component's core paths in a sub-5-minute inner loop.

_CHAOS_MODULES = {
    "test_stress",
}
_SCALE_MODULES = {
    "test_scale_envelope",
}
_SLOW_MODULES: set = set()  # filled from measured durations


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rpartition(".")[2]
        if mod in _CHAOS_MODULES:
            item.add_marker(pytest.mark.chaos)
        elif mod in _SCALE_MODULES:
            item.add_marker(pytest.mark.scale)
        elif mod in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
        if not any(m.name in ("slow", "chaos", "scale")
                   for m in item.iter_markers()):
            item.add_marker(pytest.mark.fast)


@pytest.fixture
def ray_start():
    """Fresh single-node cluster per test (reference analogue:
    ray_start_regular in python/ray/tests/conftest.py:580)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def ray_start_shared():
    """Shared cluster for cheap read-only tests."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
