"""Importable task targets for cross-language clients (the native C++
client submits functions by import path — "path:module:attr", see
runtime.get_function)."""


def add_scaled(a, b, scale=1):
    return (a + b) * scale


def echo(x):
    return x
