"""Importable task targets for cross-language clients (the native C++
client submits functions by import path — "path:module:attr", see
runtime.get_function)."""


def add_scaled(a, b, scale=1):
    return (a + b) * scale


def echo(x):
    return x


class Accumulator:
    """Cross-language actor target (created by class import path)."""

    def __init__(self, start=0):
        self.total = start

    def add(self, x):
        self.total += x
        return self.total
