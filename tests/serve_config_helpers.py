"""Importable deployment classes for serve build/deploy config tests."""

from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x):
        return 2 * x


@serve.deployment
class Chain:
    def __init__(self, inner):
        self.inner = inner

    def __call__(self, x):
        doubled = self.inner.remote(x).result()
        return doubled + 1


def doubler_app():
    """Zero-arg builder for `ray-tpu serve run` tests."""
    return Doubler.bind()
