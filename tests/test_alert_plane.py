"""Telemetry history + SLO alerting plane (tsdb.py / alertplane.py).

Unit layer: ring-buffer tier bounds and downsampling, the
``(other series)`` cardinality fold, window algebra, threshold
firing→resolved lifecycle with for-duration hysteresis, multi-window
burn-rate math on synthetic series, and the webhook sink against a
real local HTTP server.

E2E layer (module cluster, fast knobs): the head's health-tick
self-sample populates the store, ``util.state.query_metrics`` range
queries work, a seeded SLO violation fires a burn-rate alert whose
record pins REAL cross-plane evidence (a retained trace exemplar id
and an overlapping continuous-profiling window), then resolves into
the history ring; kill switches empty every surface; the operator CLI
(``ray-tpu top`` / ``alerts`` / ``metrics query``) renders and emits
parseable JSON.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import alertplane, tsdb
from ray_tpu._private.config import Config
from ray_tpu._private.worker_context import get_head, global_runtime
from ray_tpu.util import state as us


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(
        num_cpus=4, object_store_memory=64 * 1024 * 1024,
        _system_config={
            "health_check_period_s": 0.2,
            "tsdb_sample_interval_s": 0.25,
            "alerts_eval_interval_s": 0.25,
            "trace_slow_threshold_s": 0.01,
            "profiling_window_s": 1.0,
        })
    yield
    ray_tpu.shutdown()


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError(f"never happened: {msg}")


def _cfg(**over):
    return Config().apply_overrides(over)


# ---------------------------------------------------------------------------
# tsdb unit: tiers, bounds, downsampling, fold


def test_tsdb_tier_bounds_and_downsample():
    cfg = _cfg(tsdb_raw_resolution_s=1.0, tsdb_raw_retention_s=10.0,
               tsdb_rollup_resolution_s=5.0, tsdb_rollup_retention_s=60.0)
    store = tsdb.SeriesStore(cfg)
    t0 = 1000.0
    for i in range(120):
        store.ingest("m", {"a": "1"}, float(i), t0 + i)
    # Ring bounds hold regardless of ingest volume.
    s = store._series[("m", (("a", "1"),))]
    assert len(s.raw.buckets) <= 10
    assert len(s.rollup.buckets) <= 12
    now = t0 + 119
    # Recent window reads the raw tier at raw resolution...
    res = store.query("m", {"a": "1"}, start=now - 5, now=now)
    assert len(res) == 1 and res[0]["resolution_s"] == 1.0
    assert res[0]["points"]
    # ...a window reaching past raw retention reads the rollups...
    res = store.query("m", start=now - 50, now=now)
    assert res[0]["resolution_s"] == 5.0
    # rollup buckets aggregate the raw samples they cover
    b = res[0]["points"][0]
    assert b[tsdb.COUNT] >= 2 and b[tsdb.MIN] < b[tsdb.MAX]
    # ...and an explicit coarse step coalesces further.
    res = store.query("m", start=now - 50, end=now, step=20.0, now=now)
    pts = res[0]["points"]
    assert all(p[tsdb.TS] % 20 == 0 for p in pts)
    assert sum(p[tsdb.COUNT] for p in pts) >= 10


def test_tsdb_bucket_aggregates_and_label_match():
    cfg = _cfg(tsdb_raw_resolution_s=10.0)
    store = tsdb.SeriesStore(cfg)
    for v in (3.0, 1.0, 2.0):
        store.ingest("g", {"pool": "p", "x": "y"}, v, 1005.0)
    res = store.query("g", {"pool": "p"}, start=990, end=1010, now=1010)
    assert len(res) == 1  # subset label match
    b = res[0]["points"][0]
    assert b[tsdb.MIN] == 1.0 and b[tsdb.MAX] == 3.0
    assert b[tsdb.SUM] == 6.0 and b[tsdb.COUNT] == 3
    assert b[tsdb.LAST] == 2.0
    # Mismatched filter matches nothing; non-numeric values are dropped.
    assert store.query("g", {"pool": "other"}, now=1010) == []
    store.ingest("g", None, "not-a-number", 1006.0)
    assert store.stats()["ingested_total"] == 3


def test_tsdb_series_bound_folds_to_other():
    cfg = _cfg(tsdb_max_series=8)
    store = tsdb.SeriesStore(cfg)
    for i in range(20):
        store.ingest(f"series_{i}", None, 1.0, 1000.0 + i)
    st = store.stats()
    assert st["series"] == 9  # 8 real + the catch-all
    assert st["dropped_total"] == 12
    assert tsdb.OTHER_SERIES in store.names()
    other = store.query(tsdb.OTHER_SERIES, now=1100)
    assert sum(b[tsdb.COUNT] for b in other[0]["points"]) == 12


def test_tsdb_window_algebra():
    pts = [[0, 1.0, 3.0, 4.0, 2, 3.0], [10, 2.0, 8.0, 10.0, 2, 8.0]]
    assert tsdb.agg_over(pts, "min") == 1.0
    assert tsdb.agg_over(pts, "max") == 8.0
    assert tsdb.agg_over(pts, "last") == 8.0
    assert tsdb.agg_over(pts, "sum") == 14.0
    assert tsdb.agg_over(pts, "avg") == pytest.approx(14.0 / 4)
    assert tsdb.agg_over(pts, "rate") == pytest.approx((8.0 - 3.0) / 10)
    assert tsdb.agg_over([pts[0]], "rate") == 0.0  # one bucket: no slope
    assert tsdb.agg_over([], "avg") is None
    with pytest.raises(ValueError):
        tsdb.agg_over(pts, "median")


# ---------------------------------------------------------------------------
# alert engine unit: lifecycle, hysteresis, burn-rate math


def _threshold_rule(**over):
    rule = {
        "name": "unit-threshold", "kind": "threshold", "series": "g",
        "agg": "last", "window_s": 60.0, "op": ">", "threshold": 5.0,
        "for_s": 0.0, "severity": "warn", "summary": "unit",
    }
    rule.update(over)
    return rule


def test_threshold_lifecycle_firing_then_resolved():
    store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
    eng = alertplane.AlertEngine(_cfg(), rules=[_threshold_rule()])
    t = 1000.0
    store.ingest("g", None, 9.0, t)
    fired = eng.evaluate(store, now=t, force=True)
    assert [r["name"] for r in fired] == ["unit-threshold"]
    assert eng.active["unit-threshold"]["state"] == "firing"
    assert eng.fired_total == 1
    # Still bad: stays firing, no duplicate fire.
    store.ingest("g", None, 8.0, t + 2)
    assert eng.evaluate(store, now=t + 2, force=True) == []
    # Recovered: firing -> resolved, moved to history.
    store.ingest("g", None, 1.0, t + 70)  # old samples age out of window
    assert eng.evaluate(store, now=t + 70, force=True) == []
    assert "unit-threshold" not in eng.active
    assert eng.resolved_total == 1
    hist = eng.list(include_history=True)
    assert hist and hist[-1]["state"] == "resolved"
    assert hist[-1]["resolved_at"] == t + 70
    # note_resolved announces each resolution exactly once.
    assert [r["name"] for r in eng.note_resolved()] == ["unit-threshold"]
    assert eng.note_resolved() == []


def test_threshold_for_duration_hysteresis():
    """A breach shorter than for_s never fires — and the pending timer
    RESETS on recovery (a second blip starts from zero)."""
    store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
    eng = alertplane.AlertEngine(
        _cfg(), rules=[_threshold_rule(for_s=10.0, window_s=5.0)])
    t = 2000.0
    store.ingest("g", None, 9.0, t)
    assert eng.evaluate(store, now=t, force=True) == []
    assert eng.active["unit-threshold"]["state"] == "pending"
    # Blip ends before for_s: pending record vanishes without firing.
    store.ingest("g", None, 1.0, t + 4)
    assert eng.evaluate(store, now=t + 4, force=True) == []
    assert eng.active == {} and eng.fired_total == 0
    # Second breach must hold for the FULL for_s from its own start.
    store.ingest("g", None, 9.0, t + 6)
    assert eng.evaluate(store, now=t + 6, force=True) == []
    store.ingest("g", None, 9.0, t + 10)
    assert eng.evaluate(store, now=t + 10, force=True) == []
    store.ingest("g", None, 9.0, t + 17)
    fired = eng.evaluate(store, now=t + 17, force=True)
    assert len(fired) == 1 and fired[0]["fired_at"] == t + 17


def test_threshold_no_data_never_fires():
    store = tsdb.SeriesStore(_cfg())
    eng = alertplane.AlertEngine(
        _cfg(), rules=[_threshold_rule(op="<", threshold=100.0)])
    assert eng.evaluate(store, now=1000.0, force=True) == []
    assert eng.active == {}


def test_burn_rate_counter_pair_math():
    """bad/total = 10% against a 99.9% objective => burn 100x."""
    store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
    t = 5000.0
    for i in range(0, 100, 2):
        store.ingest("bad_total", None, float(i) * 0.1, t + i, "counter")
        store.ingest("all_total", None, float(i), t + i, "counter")
    rule = {"name": "b", "kind": "burn_rate", "bad": "bad_total",
            "total": "all_total", "objective": 0.999,
            "fast_window_s": 50.0, "slow_window_s": 200.0,
            "burn_factor": 14.4, "for_s": 0.0, "severity": "page"}
    now = t + 98
    fast = alertplane.burn_rate(store, rule, 50.0, now)
    slow = alertplane.burn_rate(store, rule, 200.0, now)
    assert fast == pytest.approx(100.0, rel=0.01)
    assert slow == pytest.approx(100.0, rel=0.01)
    eng = alertplane.AlertEngine(_cfg(), rules=[rule])
    fired = eng.evaluate(store, now=now, force=True)
    assert len(fired) == 1
    assert fired[0]["burn_fast"] == pytest.approx(100.0, rel=0.01)


def test_burn_rate_requires_both_windows():
    """Fast window hot but slow window cold => NO page (the multi-
    window rule exists exactly to suppress this flap)."""
    store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
    t = 6000.0
    # 200s of clean traffic, then a 20s burst at the end: the burst is
    # 90% errors (way past a 99% objective) but the full window's
    # 18/218 ~ 8% keeps the SLOW burn under the factor.
    for i in range(0, 220, 2):
        store.ingest("all_total", None, float(i), t + i, "counter")
        bad = 0.0 if i < 200 else float(i - 200) * 0.9
        store.ingest("bad_total", None, bad, t + i, "counter")
    rule = {"name": "b", "kind": "burn_rate", "bad": "bad_total",
            "total": "all_total", "objective": 0.99,
            "fast_window_s": 20.0, "slow_window_s": 2000.0,
            "burn_factor": 14.4, "for_s": 0.0, "severity": "page"}
    now = t + 218
    fast = alertplane.burn_rate(store, rule, 20.0, now)
    slow = alertplane.burn_rate(store, rule, 2000.0, now)
    assert fast > 14.4          # the burst alone looks like a cliff
    assert slow < 14.4          # ...but the hour says budget is fine
    eng = alertplane.AlertEngine(_cfg(), rules=[rule])
    assert eng.evaluate(store, now=now, force=True) == []


def test_burn_rate_gauge_form():
    """Latency-gauge SLO: fraction of observed time above ``over``."""
    store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
    t = 7000.0
    # 40 buckets, half above the 2.0s bound.
    for i in range(40):
        store.ingest("p99", {"phase": "exec"},
                     5.0 if i % 2 else 0.5, t + i)
    rule = {"name": "g", "kind": "burn_rate", "series": "p99",
            "labels": {"phase": "exec"}, "over": 2.0,
            "objective": 0.99, "fast_window_s": 60.0,
            "slow_window_s": 600.0, "burn_factor": 14.4,
            "for_s": 0.0, "severity": "page"}
    burn = alertplane.burn_rate(store, rule, 60.0, t + 39)
    assert burn == pytest.approx(0.5 / 0.01, rel=0.01)  # 50x budget
    # No data in window -> None -> never fires.
    assert alertplane.burn_rate(store, rule, 60.0, t + 5000) is None


def test_default_rules_reference_config_thresholds():
    cfg = _cfg(alert_serve_p99_slo_s=1.25, alert_kv_pages_min=7.0)
    rules = alertplane.default_rules(cfg)
    by_name = {r["name"]: r for r in rules}
    assert by_name["serve-p99-slo-burn"]["over"] == 1.25
    assert by_name["kv-page-exhaustion"]["threshold"] == 7.0
    assert all(r["severity"] in alertplane.SEVERITIES for r in rules)
    # The engine caps the registry at alerts_max_rules.
    eng = alertplane.AlertEngine(
        _cfg(alerts_max_rules=2), rules=rules)
    assert len(eng.rules) == 2


# ---------------------------------------------------------------------------
# webhook sink against a real local HTTP server


def test_webhook_sink_posts_transitions(monkeypatch):
    from http.server import BaseHTTPRequestHandler, HTTPServer

    got: "list[dict]" = []
    done = threading.Event()

    class Hook(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            got.append(json.loads(body))
            if len(got) >= 2:
                done.set()
            self.send_response(204)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        monkeypatch.setenv(
            "RAY_TPU_ALERT_WEBHOOK",
            f"http://127.0.0.1:{srv.server_port}/alert")
        store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
        eng = alertplane.AlertEngine(
            _cfg(), rules=[_threshold_rule(window_s=5.0)])
        t = 9000.0
        store.ingest("g", None, 9.0, t)
        assert len(eng.evaluate(store, now=t, force=True)) == 1
        store.ingest("g", None, 1.0, t + 10)
        eng.evaluate(store, now=t + 10, force=True)
        eng.note_resolved()
        assert done.wait(10), f"webhook saw {len(got)} posts"
        transitions = {p["transition"] for p in got}
        assert transitions == {"FIRING", "RESOLVED"}
        assert all(p["name"] == "unit-threshold" for p in got)
        assert got[0]["severity"] == "warn"
    finally:
        srv.shutdown()


def test_webhook_failure_is_swallowed(monkeypatch):
    """A dead receiver must cost nothing: firing still works."""
    monkeypatch.setenv("RAY_TPU_ALERT_WEBHOOK",
                       "http://127.0.0.1:1/nothing-listens-here")
    store = tsdb.SeriesStore(_cfg(tsdb_raw_resolution_s=1.0))
    eng = alertplane.AlertEngine(_cfg(), rules=[_threshold_rule()])
    store.ingest("g", None, 9.0, 100.0)
    assert len(eng.evaluate(store, now=100.0, force=True)) == 1


# ---------------------------------------------------------------------------
# kill switches


def test_kill_switch_env_parsing(monkeypatch):
    for off in ("0", "false", "no", "off", "FALSE"):
        monkeypatch.setenv("RAY_TPU_TSDB_ENABLED", off)
        monkeypatch.setenv("RAY_TPU_ALERTS_ENABLED", off)
        assert not tsdb.enabled() and not alertplane.enabled()
    for on in ("1", "true", "yes"):
        monkeypatch.setenv("RAY_TPU_TSDB_ENABLED", on)
        monkeypatch.setenv("RAY_TPU_ALERTS_ENABLED", on)
        assert tsdb.enabled() and alertplane.enabled()
    monkeypatch.delenv("RAY_TPU_TSDB_ENABLED")
    monkeypatch.delenv("RAY_TPU_ALERTS_ENABLED")
    assert tsdb.enabled() and alertplane.enabled()  # defaults ship ON


def test_disabled_surfaces_answer_empty(cluster, monkeypatch):
    """With the stores gone (what the kill switches produce at boot),
    every query surface answers empty-but-well-formed instead of
    erroring."""
    head = get_head()
    monkeypatch.setattr(head, "tsdb", None)
    monkeypatch.setattr(head, "alerts", None)
    r = us.query_metrics("ray_tpu_tasks_finished_total")
    assert r == {"series": [], "enabled": False}
    a = us.list_alerts()
    assert a["alerts"] == [] and a["stats"] == {}
    assert a["enabled"] is False
    # The sweep is a no-op, not a crash.
    head._telemetry_sweep(time.time())


# ---------------------------------------------------------------------------
# e2e: sampling, query surface, seeded SLO breach with cross-plane joins


def test_e2e_head_samples_history(cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(20)]) == \
        list(range(1, 21))
    def _sampled():
        r = us.query_metrics("ray_tpu_tasks_finished_total")["series"]
        # The series may exist from a pre-task sweep at value 0: wait
        # for a sweep that has SEEN the completions, not mere existence.
        if r and r[0]["points"][-1][tsdb.LAST] >= 20:
            return r
        return None

    r = _wait(_sampled, msg="tasks_finished never reached the tsdb")
    pts = r[0]["points"]
    assert pts and all(len(b) == 6 for b in pts)
    assert [b[tsdb.TS] for b in pts] == sorted(b[tsdb.TS] for b in pts)
    assert pts[-1][tsdb.LAST] >= 20
    # Derived phase quantile gauges carry the phase label.
    r = _wait(lambda: (us.query_metrics("ray_tpu_phase_p95_seconds")
                       ["series"] or None),
              msg="phase p95 gauges never sampled")
    assert any(s["labels"].get("phase") == "exec" for s in r)
    # Gauges sampled from the head's own tables.
    g = us.query_metrics("ray_tpu_workers_alive")["series"]
    assert g and g[0]["points"][-1][tsdb.LAST] >= 1
    # Self-metrics ride the exposition.
    from ray_tpu.util import metrics as um

    text = um.prometheus_text()
    assert "ray_tpu_tsdb_series " in text
    assert 'ray_tpu_alerts_firing{severity="page"} 0' in text


def test_e2e_node_system_sample(cluster):
    """Per-node load1/meminfo gauge series exist with the node_id
    label — agent heartbeats piggyback them on multi-node clusters;
    in-process the head self-samples its own host."""
    r = _wait(lambda: (us.query_metrics("ray_tpu_node_load1")["series"]
                       or None),
              msg="node load1 gauge never sampled")
    assert r[0]["labels"].get("node_id")
    mem = us.query_metrics("ray_tpu_node_mem_total_bytes")["series"]
    assert mem and mem[0]["points"][-1][tsdb.LAST] > 0


def test_e2e_seeded_slo_breach_fires_with_evidence_then_resolves(cluster):
    """The acceptance scenario: a seeded burn-rate breach fires on the
    head's own health loop (not a forced evaluate), the record pins a
    REAL retained trace exemplar id and an overlapping profiling
    window, and withdrawing the breach resolves it into history."""
    from ray_tpu._private import traceplane, worker_context

    head = get_head()

    # Ground truth for the joins: a slow traced call becomes a retained
    # exemplar, and the always-on profiler ships windows.
    @ray_tpu.remote
    def slow(x):
        time.sleep(0.05)
        return x

    ctx = traceplane.mint_trace("slo-breach-evidence")
    assert ctx and ctx[2] == 1
    t0 = time.time()
    tok = worker_context.push_trace_context(ctx)
    try:
        assert ray_tpu.get(slow.remote(1)) == 1
    finally:
        worker_context.pop_trace_context(tok)
    # The root span is the entry surface's job (the serve proxy emits
    # it around the request); mimic that here so the >threshold
    # duration marks the trace slow -> retained as an exemplar.
    import os as _os

    traceplane.buffer_span({
        "event": "span", "name": "http.request", "kind": "proxy",
        "trace_id": ctx[0], "span_id": ctx[1], "parent_span_id": "",
        "pid": _os.getpid(), "start": t0, "end": time.time(),
        "failed": False, "status": 200, "attributes": {},
    })
    _wait(lambda: (head.traces.stats().get("exemplar_ids") or None),
          msg="trace exemplar never retained")
    _wait(lambda: len(head.cluster_profile) > 0,
          msg="no profiling windows")

    @ray_tpu.remote
    def burn(x):
        return x

    assert ray_tpu.get([burn.remote(i) for i in range(30)]) == \
        list(range(30))

    # Seed: the stock serve-p99 rule shape with an impossible SLO —
    # "exec p99 must be 0s" — so 100% of observed buckets violate a
    # 99% objective => burn 100x on every window, deterministically
    # (a counter-pair seed would stop firing once the counter
    # plateaus and its windowed rate decays to 0).
    seeded = {
        "name": "seeded-slo-breach", "kind": "burn_rate",
        "series": "ray_tpu_phase_p99_seconds",
        "labels": {"phase": "exec"}, "over": 0.0,
        "objective": 0.99, "fast_window_s": 120.0,
        "slow_window_s": 600.0, "burn_factor": 14.4, "for_s": 0.0,
        "severity": "page", "summary": "seeded breach (test)",
    }
    with head.alerts._lock:
        head.alerts.rules.append(seeded)
    try:
        rec = _wait(
            lambda: next((a for a in us.list_alerts()["alerts"]
                          if a["name"] == "seeded-slo-breach"
                          and a["state"] == "firing"), None),
            msg="seeded rule never fired on the health loop")
        assert rec["severity"] == "page"
        assert rec["burn_fast"] > 14.4 and rec["burn_slow"] > 14.4
        ctx_ev = rec.get("context") or {}
        # >=1 real trace exemplar id, resolvable through the trace API.
        assert ctx_ev.get("trace_exemplars")
        tid = ctx_ev["trace_exemplars"][0]
        assert us.get_trace(tid) is not None
        # >=1 profiling window overlapping the alert window.
        wins = ctx_ev.get("profile_windows")
        assert wins and wins[-1]["end"] >= rec["fired_at"] - 120.0
        # Exposition reflects the firing severity while it burns.
        from ray_tpu.util import metrics as um

        assert 'ray_tpu_alerts_firing{severity="page"} 1' \
            in um.prometheus_text()
        # Withdraw the breach: point the rule at a silent series -> no
        # data -> condition clears -> firing -> resolved into history.
        with head.alerts._lock:
            seeded["series"] = "ray_tpu_series_nobody_emits"
        hist = _wait(
            lambda: next((a for a in us.list_alerts(history=True)
                          ["alerts"]
                          if a["name"] == "seeded-slo-breach"
                          and a["state"] == "resolved"), None),
            msg="seeded rule never resolved")
        assert hist["resolved_at"] >= hist["fired_at"]
        assert us.list_alerts(history=True)["stats"]["resolved_total"] >= 1
    finally:
        with head.alerts._lock:
            head.alerts.rules.remove(seeded)
            head.alerts.active.pop("seeded-slo-breach", None)


# ---------------------------------------------------------------------------
# exposition timestamps (RAY_TPU_METRICS_TIMESTAMPS)


def test_prometheus_timestamps_and_escaping(cluster, monkeypatch):
    import re

    from ray_tpu.util import metrics as um

    gauge = um.Gauge("alertplane_test_gauge", tag_keys=("deployment",))
    gauge.set(1.5, {"deployment": 'a"b\\c\nd'})
    gauge._flush(force=True)
    _wait(lambda: "alertplane_test_gauge" in um.prometheus_text(),
          msg="user gauge never reached the head")

    # Default: NO timestamps anywhere (bit-compatible with pre-PR).
    text = um.prometheus_text()
    for line in text.splitlines():
        if line.startswith("ray_tpu_workers_alive"):
            assert re.fullmatch(r"ray_tpu_workers_alive \S+", line)
    # Label escaping: backslash, quote, newline all survive.
    assert 'deployment="a\\"b\\\\c\\nd"' in text

    monkeypatch.setenv("RAY_TPU_METRICS_TIMESTAMPS", "1")
    text = um.prometheus_text()
    stamped = [ln for ln in text.splitlines()
               if ln.startswith("ray_tpu_workers_alive")]
    assert stamped and all(
        re.fullmatch(r"ray_tpu_workers_alive \S+ \d{13}", ln)
        for ln in stamped)
    # User gauge samples are stamped too...
    user = [ln for ln in text.splitlines()
            if ln.startswith("alertplane_test_gauge")]
    assert user and all(re.search(r" \d{13}$", ln) for ln in user)
    # ...counters stay bare (cumulative value, scrape-time semantics).
    counters = [ln for ln in text.splitlines()
                if ln.startswith("ray_tpu_tasks_finished_total")]
    assert counters and all(
        not re.search(r" \d{13}$", ln) for ln in counters)


# ---------------------------------------------------------------------------
# Grafana alert-rule export rides the same registry


def test_grafana_alert_rules_render_from_registry():
    from ray_tpu.util import metrics_export

    bundle = metrics_export.grafana_alert_rules()
    rules = bundle["groups"][0]["rules"]
    names = {r["title"] for r in rules}
    assert names == {r["name"]
                     for r in alertplane.default_rules(Config())}
    by_name = {r["title"]: r for r in rules}
    burn = by_name["shed-ratio-slo-burn"]["data"][0]["model"]["expr"]
    # Multi-window AND, both sides against the burn factor.
    assert " and " in burn and burn.count("> 14.4") == 2
    assert "ray_tpu_tasks_shed_total" in burn
    thr = by_name["kv-page-exhaustion"]["data"][0]["model"]["expr"]
    assert thr.startswith("min(min_over_time(")
    assert by_name["kv-page-exhaustion"]["labels"]["severity"] == "page"
    assert by_name["phase-p95-queue-wait"]["for"] == "30s"
    json.loads(metrics_export.grafana_alert_rules_json())  # valid JSON


# ---------------------------------------------------------------------------
# operator CLI: ray-tpu top / alerts / metrics query


def test_cli_surfaces(cluster, capsys, monkeypatch):
    from ray_tpu import scripts

    monkeypatch.setattr(scripts, "_connect", lambda addr: None)

    @ray_tpu.remote
    def f(x):
        return x

    assert ray_tpu.get([f.remote(i) for i in range(10)]) == \
        list(range(10))
    _wait(lambda: us.query_metrics("ray_tpu_tasks_finished_total")
          ["series"] or None, msg="history for CLI")

    def _args(**kw):
        return type("Args", (), kw)()

    # top: one frame, human-readable.
    assert scripts.cmd_top(_args(address="local", interval=0.1,
                                 once=True, iterations=0,
                                 json=False)) == 0
    out = capsys.readouterr().out
    assert "ray-tpu top" in out and "tasks:" in out
    assert "tsdb:" in out and "alert" in out.lower()

    # top --json: machine-readable snapshot.
    assert scripts.cmd_top(_args(address="local", interval=0.1,
                                 once=True, iterations=0,
                                 json=True)) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "gauges" in doc and "alerts" in doc

    # alerts table + JSON.
    assert scripts.cmd_alerts(_args(address="local", history=True,
                                    format="table")) == 0
    out = capsys.readouterr().out
    assert "rule(s):" in out
    assert scripts.cmd_alerts(_args(address="local", history=False,
                                    format="json")) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["enabled"] is True and "stats" in doc

    # metrics query via the full argparse path (table, then JSON).
    assert scripts.main([
        "metrics", "query", "ray_tpu_tasks_finished_total",
        "--address", "ignored", "--window", "600"]) == 0
    out = capsys.readouterr().out
    assert "ray_tpu_tasks_finished_total" in out and "last=" in out
    assert scripts.main([
        "metrics", "query", "ray_tpu_tasks_finished_total",
        "--address", "ignored", "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["series"] and doc["series"][0]["points"]
    # Unknown series: empty table, exit 1.
    assert scripts.main([
        "metrics", "query", "ray_tpu_series_nobody_emits_total",
        "--address", "ignored"]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# dashboard: /api/metrics/query, /api/alerts, /api/grafana_alerts, Charts SPA


def test_e2e_dashboard_metrics_endpoints(cluster):
    import urllib.request

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    def _get(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.read().decode()

    _wait(lambda: us.query_metrics("ray_tpu_tasks_finished_total")
          ["series"] or None, msg="history for dashboard")
    port = start_dashboard()
    try:
        doc = json.loads(_get(
            port, "/api/metrics/query?name=ray_tpu_tasks_finished_total"))
        assert doc["enabled"] is True and doc["series"]
        assert doc["series"][0]["points"]
        # Label filtering via label.-prefixed query params.
        doc = json.loads(_get(
            port, "/api/metrics/query?name=ray_tpu_phase_p95_seconds"
                  "&label.phase=exec"))
        assert all(s["labels"].get("phase") == "exec"
                   for s in doc["series"])
        a = json.loads(_get(port, "/api/alerts?history=1"))
        assert a["enabled"] is True and a["stats"]["rules"] >= 5
        g = json.loads(_get(port, "/api/grafana_alerts"))
        assert g["groups"][0]["rules"]
        # The SPA drives these APIs: Charts view + alert badge.
        html = _get(port, "/")
        assert "/api/metrics/query" in html and "Charts" in html
        assert "alertbadge" in html
    finally:
        stop_dashboard()
