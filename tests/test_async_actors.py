"""Asyncio actors + concurrency groups.

Reference: async actors execute coroutine methods concurrently on one
event loop (core_worker/transport/fiber.h:17, actor_scheduling_queue.h);
concurrency groups bound per-group parallelism
(concurrency_group_manager.h:37, @ray.remote(concurrency_groups={...})).
"""

import time

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_async_methods_interleave(cluster):
    """Two in-flight calls awaiting each other's signal can only finish
    if they interleave on the loop — threads are not needed."""

    @ray_tpu.remote
    class Rendezvous:
        def __init__(self):
            import asyncio

            self.event = asyncio.Event()

        async def waiter(self):
            await self.event.wait()
            return "woke"

        async def setter(self):
            self.event.set()
            return "set"

    a = Rendezvous.remote()
    w = a.waiter.remote()
    time.sleep(0.2)  # waiter is parked on the loop
    assert ray_tpu.get(a.setter.remote(), timeout=30) == "set"
    assert ray_tpu.get(w, timeout=30) == "woke"


def test_async_concurrency_many_calls(cluster):
    """100 sleeping coroutines finish in ~one sleep, not 100."""

    @ray_tpu.remote
    class Sleeper:
        async def nap(self, s):
            import asyncio

            await asyncio.sleep(s)
            return s

    a = Sleeper.remote()
    t0 = time.time()
    out = ray_tpu.get([a.nap.remote(0.3) for _ in range(100)], timeout=60)
    assert out == [0.3] * 100
    assert time.time() - t0 < 8.0


def test_async_actor_state_and_context(cluster):
    """Interleaved calls share instance state; nested submissions from
    inside a coroutine work (ContextVar-carried task context)."""

    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        async def bump(self):
            import asyncio

            self.n += 1
            await asyncio.sleep(0.01)
            return self.n

        async def nested(self, x):
            return ray_tpu.get(double.remote(x))

    c = Counter.remote()
    ray_tpu.get([c.bump.remote() for _ in range(10)], timeout=30)
    assert ray_tpu.get(c.bump.remote(), timeout=30) == 11
    assert ray_tpu.get(c.nested.remote(21), timeout=30) == 42


def test_async_max_concurrency_bound(cluster):
    """max_concurrency bounds the loop's in-flight calls."""

    @ray_tpu.remote(max_concurrency=2)
    class Gate:
        def __init__(self):
            self.active = 0
            self.peak = 0

        async def hold(self):
            import asyncio

            self.active += 1
            self.peak = max(self.peak, self.active)
            await asyncio.sleep(0.2)
            self.active -= 1
            return self.peak

    g = Gate.remote()
    peaks = ray_tpu.get([g.hold.remote() for _ in range(6)], timeout=30)
    assert max(peaks) == 2, peaks


def test_async_errors_and_generators(cluster):
    @ray_tpu.remote
    class A:
        async def boom(self):
            raise ValueError("async kaboom")

        async def stream(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * i

    a = A.remote()
    with pytest.raises(Exception, match="kaboom"):
        ray_tpu.get(a.boom.remote(), timeout=30)
    got = [ray_tpu.get(r, timeout=30) for r in a.stream.remote(4)]
    assert got == [0, 1, 4, 9]


def test_concurrency_groups_async(cluster):
    """Per-group semaphores: the io group runs 2-wide while compute
    stays serialized."""

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.io_active = 0
            self.io_peak = 0
            self.c_active = 0
            self.c_peak = 0

        @ray_tpu.method(concurrency_group="io")
        async def fetch(self):
            import asyncio

            self.io_active += 1
            self.io_peak = max(self.io_peak, self.io_active)
            await asyncio.sleep(0.15)
            self.io_active -= 1

        @ray_tpu.method(concurrency_group="compute")
        async def crunch(self):
            import asyncio

            self.c_active += 1
            self.c_peak = max(self.c_peak, self.c_active)
            await asyncio.sleep(0.15)
            self.c_active -= 1

        async def peaks(self):
            return self.io_peak, self.c_peak

    w = Worker.remote()
    refs = [w.fetch.remote() for _ in range(4)] + \
           [w.crunch.remote() for _ in range(4)]
    ray_tpu.get(refs, timeout=30)
    io_peak, c_peak = ray_tpu.get(w.peaks.remote(), timeout=30)
    assert io_peak == 2, io_peak
    assert c_peak == 1, c_peak


def test_concurrency_groups_threaded(cluster):
    """Threaded actors get one pool per group; per-call override via
    .options(concurrency_group=...)."""

    @ray_tpu.remote(max_concurrency=4, concurrency_groups={"solo": 1})
    class T:
        def __init__(self):
            self.solo_active = 0
            self.solo_peak = 0
            import threading

            self.lock = threading.Lock()

        def slow(self):
            with self.lock:
                self.solo_active += 1
                self.solo_peak = max(self.solo_peak, self.solo_active)
            time.sleep(0.15)
            with self.lock:
                self.solo_active -= 1
            return True

        def peak(self):
            return self.solo_peak

    t = T.remote()
    refs = [t.slow.options(concurrency_group="solo").remote()
            for _ in range(3)]
    assert all(ray_tpu.get(refs, timeout=30))
    assert ray_tpu.get(t.peak.remote(), timeout=30) == 1


def test_sync_actor_unchanged(cluster):
    """Plain sync actors keep strict FIFO single-thread semantics."""

    @ray_tpu.remote
    class S:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return list(self.log)

    s = S.remote()
    outs = ray_tpu.get([s.add.remote(i) for i in range(5)], timeout=30)
    assert outs[-1] == [0, 1, 2, 3, 4]


def test_cancel_queued_actor_call(cluster):
    """cancel(ref) on an actor call queued behind a running one drops it
    before execution: get raises TaskCancelledError-tagged TaskError
    instead of hanging, and the running call is untouched (reference:
    actor-task cancel semantics, recursive=False)."""
    import time

    import pytest

    @ray_tpu.remote
    class A:
        def slow(self):
            time.sleep(3)
            return "done"

        def quick(self):
            return "q"

    a = A.remote()
    assert ray_tpu.get(a.quick.remote(), timeout=30) == "q"
    r1 = a.slow.remote()     # occupies the single-threaded executor
    r2 = a.slow.remote()     # queued behind r1
    ray_tpu.cancel(r2)
    with pytest.raises(Exception, match="TaskCancelled"):
        ray_tpu.get(r2, timeout=30)
    assert ray_tpu.get(r1, timeout=30) == "done"
    # Still serving after the cancel.
    assert ray_tpu.get(a.quick.remote(), timeout=30) == "q"
