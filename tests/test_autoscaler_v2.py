"""Autoscaler v2 reconciler (reference: autoscaler/v2 — instance manager,
lifecycle transitions, idle scale-down)."""

import pytest

from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    NodeType,
    Reconciler,
)
from ray_tpu.autoscaler.v2 import (
    ALLOCATED,
    QUEUED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATING,
    InstanceStorage,
)


def _setup(launch_delay=0.0, idle_timeout=0.0):
    provider = FakeNodeProvider(launch_delay_s=launch_delay)
    config = AutoscalerConfig(
        node_types=[NodeType("cpu4", {"CPU": 4.0}, max_workers=5)],
        idle_timeout_s=idle_timeout,
    )
    storage = InstanceStorage()
    return provider, Reconciler(provider, storage, config), storage


def test_demand_launches_through_lifecycle():
    provider, rec, storage = _setup()
    ray_nodes = set()
    res = rec.reconcile([{"CPU": 4.0}, {"CPU": 4.0}],
                        ray_running=lambda cid: cid in ray_nodes,
                        node_is_idle=lambda cid: False)
    assert res["launched"] == {"cpu4": 2}
    # QUEUED instances were provider-requested in the same pass and the
    # fake provider runs them instantly -> ALLOCATED on next observe.
    assert len(provider.non_terminated_nodes()) == 2
    res = rec.reconcile([], lambda cid: cid in ray_nodes, lambda cid: False)
    assert res["instances"][ALLOCATED] == 2
    # Cluster reports ray up on both -> RAY_RUNNING; no relaunch occurs
    # while capacity covers the demand.
    ray_nodes.update(provider.non_terminated_nodes())
    res = rec.reconcile([{"CPU": 4.0}], lambda cid: cid in ray_nodes,
                        lambda cid: False)
    assert res["launched"] == {}
    assert res["instances"][RAY_RUNNING] == 2


def test_idle_scale_down_and_sweep():
    provider, rec, storage = _setup(idle_timeout=0.0)
    rec.reconcile([{"CPU": 1.0}], lambda cid: True, lambda cid: False)
    rec.reconcile([], lambda cid: True, lambda cid: False)
    assert storage.all(RAY_RUNNING)
    # Two passes: first marks idle_since, second terminates (timeout 0).
    rec.reconcile([], lambda cid: True, lambda cid: True)
    res = rec.reconcile([], lambda cid: True, lambda cid: True)
    assert res["terminated"] or res["swept"]
    # Terminated instances leave the table once the cloud confirms.
    res = rec.reconcile([], lambda cid: True, lambda cid: True)
    assert not provider.non_terminated_nodes()
    assert not storage.all(RAY_RUNNING, TERMINATING)


def test_preempted_node_detected_and_replaced():
    provider, rec, storage = _setup()
    rec.reconcile([{"CPU": 2.0}], lambda cid: True, lambda cid: False)
    rec.reconcile([], lambda cid: True, lambda cid: False)
    (inst,) = storage.all(ALLOCATED, RAY_RUNNING)
    # Cloud preempts the instance out from under us.
    provider.terminate_node(inst.cloud_instance_id)
    res = rec.reconcile([{"CPU": 2.0}], lambda cid: True, lambda cid: False)
    # The dead instance was swept and demand relaunched a replacement.
    assert res["launched"] == {"cpu4": 1}
    assert len(provider.non_terminated_nodes()) == 1


def test_max_workers_cap():
    provider, rec, _ = _setup()
    res = rec.reconcile([{"CPU": 4.0}] * 9, lambda cid: False,
                        lambda cid: False)
    assert sum(res["launched"].values()) == 5  # capped by max_workers


def test_request_resources_sdk(tmp_path):
    """sdk.request_resources persists a demand hint the autoscaler's
    demand source folds in (reference: autoscaler/sdk/sdk.py:206)."""
    import ray_tpu
    from ray_tpu.autoscaler import StandardAutoscaler
    from ray_tpu.autoscaler.sdk import request_resources, requested_resources

    ray_tpu.init(num_cpus=1, object_store_memory=32 * 1024 * 1024,
                 ignore_reinit_error=True)
    try:
        request_resources(num_cpus=2, bundles=[{"CPU": 4.0}])
        got = requested_resources()
        assert got == [{"CPU": 1.0}, {"CPU": 1.0}, {"CPU": 4.0}]
        # Demand source folds the hints into the bin-pack input.
        demands = StandardAutoscaler._head_demand()
        assert {"CPU": 4.0} in demands
        # Overridden by the next call; no-arg clears.
        request_resources()
        assert requested_resources() == []
    finally:
        ray_tpu.shutdown()
