"""Bulk transfer plane (reference: object push/pull,
src/ray/object_manager/push_manager.h:32, pull_manager.h:57): raw-socket
striped pulls, head bulk server for off-host clients, replica
registration + promotion (spanning-tree broadcast fan-out)."""

import os
import threading

import numpy as np
import pytest

from ray_tpu._private import bulk_transfer


def _wait_pins_released(reader, timeout=5.0):
    """The server releases a read pin AFTER its send completes — the
    client can hold the full payload while that server thread hasn't
    run yet (observed flaky under a loaded box). Eventual release is
    the contract; poll for it."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if reader.pins == 0:
            return
        time.sleep(0.02)
    raise AssertionError(f"pins never released: {reader.pins}")


class _MemReader:
    """BulkServer reader over an in-memory dict, counting live pins."""

    def __init__(self, objects):
        self.objects = objects
        self.pins = 0
        self.lock = threading.Lock()

    def __call__(self, object_id, start, length):
        data = self.objects[object_id]
        n = min(length, len(data) - start)
        with self.lock:
            self.pins += 1

        def release():
            with self.lock:
                self.pins -= 1

        return memoryview(data)[start:start + n], release


def test_single_stream_roundtrip():
    data = os.urandom(3 * 1024 * 1024)
    reader = _MemReader({"obj": data})
    srv = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        out = bulk_transfer.pull_object(
            srv.address, "obj", len(data), streams=4)
        assert bytes(out) == data
        _wait_pins_released(reader)
    finally:
        srv.stop()


def test_parallel_stripes_roundtrip():
    data = os.urandom(40 * 1024 * 1024)
    reader = _MemReader({"big": data})
    srv = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        out = bulk_transfer.pull_object(
            srv.address, "big", len(data), streams=4, stripe_min=4 << 20)
        assert bytes(out) == data
        _wait_pins_released(reader)
    finally:
        srv.stop()


def test_request_framing_is_binary_no_pickle():
    """The bulk request header is the PR 6 tagged binary encoding —
    round-trips through the wirefmt codec, never pickle."""
    frame = bulk_transfer._encode_request("obj-1", 512, 4096)
    assert frame[4:5] != b"\x80", "request must not be a pickle stream"
    (n,) = bulk_transfer._REQ_HDR.unpack(frame[:4])
    assert n == len(frame) - 4
    assert bulk_transfer._decode_request(frame[4:]) == ("obj-1", 512, 4096)


def test_corrupt_request_typed_error_and_close():
    """Corrupt or legacy-pickled requests raise the typed
    BulkRequestError server-side and CLOSE the connection (the mirror
    of the control plane's WireDecodeError contract)."""
    import pickle
    import socket
    import struct

    # Decoder contract: pickle explicitly rejected, garbage typed.
    with pytest.raises(bulk_transfer.BulkRequestError, match="pickle"):
        bulk_transfer._decode_request(
            pickle.dumps({"object_id": "x", "start": 0, "length": 1}))
    with pytest.raises(bulk_transfer.BulkRequestError):
        bulk_transfer._decode_request(b"\xff\xfe garbage")
    good = bulk_transfer._encode_request("obj", 0, 64)[4:]
    for cut in (1, len(good) // 2, len(good) - 1):
        with pytest.raises(bulk_transfer.BulkRequestError):
            bulk_transfer._decode_request(good[:cut])

    # Server contract: a poisoned frame closes the connection; a fresh
    # dial still works (per-connection blast radius).
    reader = _MemReader({"obj": b"x" * 1024})
    srv = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        sock = socket.create_connection(srv.address, timeout=10)
        bad = pickle.dumps({"object_id": "obj", "start": 0, "length": 8})
        sock.sendall(struct.pack("<I", len(bad)) + bad)
        assert sock.recv(16) == b"", "server must close on corrupt request"
        sock.close()
        out = bulk_transfer.pull_object(srv.address, "obj", 1024)
        assert bytes(out) == b"x" * 1024
    finally:
        srv.stop()


def test_pull_buffer_not_zero_filled():
    """pull_object's default destination comes from alloc_pull_buffer
    (no zero-fill tax at broadcast sizes) and still round-trips."""
    buf = bulk_transfer.alloc_pull_buffer(4096)
    assert memoryview(buf).nbytes == 4096
    data = os.urandom(1 << 20)
    reader = _MemReader({"obj": data})
    srv = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        out = bulk_transfer.pull_object(srv.address, "obj", len(data),
                                        out=bulk_transfer.alloc_pull_buffer(
                                            len(data)))
        assert bytes(out) == data
    finally:
        srv.stop()


def test_unknown_object_raises():
    reader = _MemReader({})
    srv = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        with pytest.raises(bulk_transfer.BulkError, match="nope"):
            bulk_transfer.pull_object(srv.address, "nope", 128)
    finally:
        srv.stop()


def test_partial_range_pull():
    data = bytes(range(256)) * 64
    reader = _MemReader({"obj": data})
    srv = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        buf = bytearray(1000)
        sock = bulk_transfer.pull_into(
            srv.address, "obj", memoryview(buf), 512, 1000)
        sock.close()
        assert bytes(buf) == data[512:1512]
    finally:
        srv.stop()


def test_head_bulk_server_serves_remote_client():
    """An off-host (forced-remote) client gets a p2p meta for a big
    head-stored object and pulls it over the bulk plane instead of
    receiving megabytes pickled inline on the control connection."""
    import ray_tpu

    os.environ["RAY_TPU_REMOTE"] = "1"
    try:
        ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)
        try:
            arr = np.arange(2_000_000, dtype=np.float64)  # 16 MB > bulk_min
            ref = ray_tpu.put(arr)
            out = ray_tpu.get(ref)
            np.testing.assert_array_equal(out, arr)
            # And again (read pins released correctly, entry intact).
            out2 = ray_tpu.get(ref)
            np.testing.assert_array_equal(out2, arr)
        finally:
            ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_REMOTE", None)


def test_replica_registration_and_promotion():
    """Head directory accepts add_replica, round-robins sources, and
    promotes a replica to primary when the hosting node dies."""
    from ray_tpu._private.config import Config
    from ray_tpu._private.gcs import SEALED, Head, ObjectEntry

    head = Head(Config(object_store_memory=32 * 1024 * 1024), num_cpus=1)
    try:
        e = ObjectEntry("obj1", "owner")
        e.state = SEALED
        e.size = 64 << 20
        e.location = "nodeA"
        e.remote_offset = 0
        head.objects["obj1"] = e
        head.node_bulk_addrs["nodeA"] = ("10.0.0.1", 1111)
        head.node_bulk_addrs["nodeB"] = ("10.0.0.2", 2222)
        head.node_agents["nodeA"] = object()  # liveness markers
        head.node_agents["nodeB"] = object()
        head._h_add_replica(
            {"object_id": "obj1", "node_id": "nodeB",
             "offset": 4096, "size": 64 << 20}, None)
        assert e.replicas == {"nodeB": (4096, 64 << 20)}
        # Round-robin alternates between the two sources.
        seen = set()
        for _ in range(4):
            nid, off, addr = head._pick_source(e)
            seen.add((nid, off, addr))
        assert seen == {("nodeA", 0, ("10.0.0.1", 1111)),
                        ("nodeB", 4096, ("10.0.0.2", 2222))}
        # Primary node dies -> replica promoted, object stays SEALED.
        del head.node_agents["nodeA"]
        head._handle_node_death("nodeA")
        assert e.state == SEALED
        assert e.location == "nodeB"
        assert e.remote_offset == 4096
        assert e.replicas == {}
    finally:
        head.shutdown()
