"""Cgroup v2 worker isolation (reference: src/ray/common/cgroup/
cgroup_setup.h + fake_cgroup_setup.h)."""

import os

from ray_tpu._private.cgroup import CgroupSetup, FakeCgroupSetup, cgroup_v2_available


def test_unavailable_root_disables_cleanly(tmp_path):
    # A plain directory is not a cgroup2 mount: setup must disable, and
    # every method must be a harmless no-op.
    cg = CgroupSetup("n1", root=str(tmp_path))
    assert not cg.enabled
    assert not cg.add_worker_process(os.getpid())
    assert not cg.add_system_process(1)
    cg.set_system_reserved(cpu_weight=100)
    cg.remove_worker(123)
    cg.teardown()


def test_fake_cgroup_records_operations():
    cg = FakeCgroupSetup("n2")
    assert cg.enabled
    assert cg.add_system_process(42)
    assert cg.add_worker_process(100, memory_bytes=1 << 20)
    assert cg.add_worker_process(101)
    assert cg.system_procs == [42]
    assert cg.worker_procs == {100: 1 << 20, 101: None}
    cg.remove_worker(100)
    assert 100 not in cg.worker_procs
    cg.set_system_reserved(cpu_weight=50, memory_min=1 << 30)
    assert cg.reserved["cpu_weight"] == 50
    cg.teardown()
    assert not cg.enabled


def test_simulated_cgroupfs_tree(tmp_path):
    # Simulate a writable cgroup2 root: the marker file is all the
    # availability check needs, and the tree/cap writes are plain files.
    root = tmp_path / "cg"
    root.mkdir()
    (root / "cgroup.controllers").write_text("cpu memory\n")
    assert cgroup_v2_available(str(root))
    cg = CgroupSetup("n3", root=str(root))
    assert cg.enabled
    assert cg.add_worker_process(os.getpid(), memory_bytes=123456)
    child = root / "ray_tpu_node_n3" / "workers" / f"worker_{os.getpid()}"
    assert (child / "memory.max").read_text() == "123456"
    assert (child / "cgroup.procs").read_text() == str(os.getpid())
    cg.remove_worker(os.getpid())
    # rmdir fails on non-empty (files remain) — tolerated.
    cg.set_system_reserved(cpu_weight=10, memory_min=5)
    assert (root / "ray_tpu_node_n3" / "system" / "cpu.weight").read_text() == "10"
    cg.teardown()
