"""Mutable shm channels + channel-compiled DAGs.

Reference: core_worker/experimental_mutable_object_manager.h:44
(WriteAcquire/ReadAcquire/ReadRelease), experimental/channel/
shared_memory_channel.py, dag/compiled_dag_node.py:806 (pinned actor
loops over reusable channels)."""

from __future__ import annotations

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag.nodes import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import (
    Channel,
    ChannelClosed,
    ChannelTimeout,
)


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_channel_roundtrip_and_backpressure():
    # num_slots=1: single-slot mutable-object semantics, where the
    # second write must wait for the release of the first.
    ch = Channel(capacity=1 << 20, num_readers=1, num_slots=1)
    rd = Channel(name=ch.name, _create=False)
    ch.write({"x": np.arange(8), "tag": "m"})
    v = rd.begin_read()
    assert v["tag"] == "m" and v["x"].sum() == 28
    rd.end_read()

    # Second write must wait for release.
    ch.write(1)
    assert rd.begin_read() == 1
    with pytest.raises(ChannelTimeout):
        ch.write(2, timeout_s=0.2)
    rd.end_read()
    ch.write(2)
    assert rd.read() == 2

    ch.close()
    with pytest.raises(ChannelClosed):
        rd.begin_read(timeout_s=1.0)


def test_channel_capacity_enforced():
    ch = Channel(capacity=1024, num_readers=1)
    with pytest.raises(ValueError, match="exceeds channel capacity"):
        ch.write(np.zeros(100000))


def test_channel_ring_runahead():
    """num_slots=4 lets the writer run 4 messages ahead before blocking;
    the reader then drains them in order."""
    ch = Channel(capacity=1 << 16, num_readers=1, num_slots=4)
    rd = Channel(name=ch.name, _create=False)
    for i in range(4):
        ch.write(i, timeout_s=2)  # none consumed yet — must not block
    with pytest.raises(ChannelTimeout):
        ch.write(99, timeout_s=0.2)  # ring full
    assert [rd.read() for _ in range(4)] == [0, 1, 2, 3]
    ch.write(4)
    assert rd.read() == 4


def test_channel_survives_creator_gc():
    """The shm region must outlive the CREATOR handle: the last attached
    handle unlinks, not the creating one (old bug: __del__ on the
    creator unlinked while a reader still drained the ring)."""
    import gc

    ch = Channel(capacity=1 << 16, num_readers=1, num_slots=4)
    name = ch.name
    rd = Channel(name=name, _create=False)
    for i in range(3):
        ch.write(i)
    del ch
    gc.collect()
    # Reader still drains the messages AND the region is still openable.
    assert [rd.read() for _ in range(3)] == [0, 1, 2]
    rd2 = Channel(name=name, _create=False)
    del rd2
    shm_path = "/dev/shm" + name
    import os as _os
    assert _os.path.exists(shm_path)
    del rd
    gc.collect()
    assert not _os.path.exists(shm_path)  # last detacher unlinked


def test_channel_write_abort_on_serialization_failure(monkeypatch):
    """A failure AFTER write_acquire (serializing into the mapped slot)
    must abort the acquired slot — otherwise every later write_acquire
    returns NULL and is misreported as ChannelTimeout forever."""
    from ray_tpu._private import serialization

    ch = Channel(capacity=1 << 16, num_readers=1, num_slots=1)
    rd = Channel(name=ch.name, _create=False)

    real_write_to = serialization.write_to
    calls = {"n": 0}

    def failing_write_to(view, header, buffers):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom mid-slot")
        return real_write_to(view, header, buffers)

    monkeypatch.setattr(serialization, "write_to", failing_write_to)
    with pytest.raises(RuntimeError, match="boom mid-slot"):
        ch.write("doomed")
    # Pre-acquire failures (plain unpicklable value) must not wedge
    # either — serialize() raises before the slot is touched.
    class Bomb:
        def __reduce__(self):
            raise RuntimeError("boom early")

    with pytest.raises(Exception, match="boom early"):
        ch.write(Bomb())
    ch.write("after")  # would raise ChannelTimeout if the slot leaked
    assert rd.read() == "after"


def test_channel_cross_process(cluster):
    """A channel pickled to an actor moves data without the object
    store per message."""

    @ray_tpu.remote
    class Consumer:
        def consume(self, chan, n):
            rd = chan
            total = 0.0
            for _ in range(n):
                v = rd.begin_read(timeout_s=30)
                total += float(v.sum())
                rd.end_read()
            return total

    ch = Channel(capacity=1 << 20, num_readers=1)
    c = Consumer.remote()
    ref = c.consume.remote(ch, 5)
    for i in range(5):
        ch.write(np.full(100, float(i)))
    assert ray_tpu.get(ref, timeout=30) == sum(i * 100 for i in range(5))


def test_compiled_dag_channel_pipeline(cluster):
    """2-stage actor pipeline compiles to channel mode; results flow
    per-execution with no task submission."""

    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x + self.k

    a = Stage.remote(1)
    b = Stage.remote(10)
    with InputNode() as inp:
        dag = b.apply.bind(a.apply.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        for i in range(20):
            assert compiled.execute(i).get(timeout_s=30) == i + 11
    finally:
        compiled.teardown()


def test_compiled_dag_fanout_multi_output(cluster):
    @ray_tpu.remote
    class S:
        def __init__(self, k):
            self.k = k

        def f(self, x):
            return x * self.k

    a, b = S.remote(2), S.remote(3)
    with InputNode() as inp:
        dag = MultiOutputNode([a.f.bind(inp), b.f.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        assert compiled.execute(5).get(timeout_s=30) == [10, 15]
        assert compiled.execute(7).get(timeout_s=30) == [14, 21]
    finally:
        compiled.teardown()


def test_compiled_dag_error_propagates_and_dag_survives(cluster):
    @ray_tpu.remote
    class S:
        def f(self, x):
            if x < 0:
                raise ValueError("negative input")
            return x + 1

    a = S.remote()
    with InputNode() as inp:
        dag = a.f.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        assert compiled.execute(1).get(timeout_s=30) == 2
        with pytest.raises(Exception, match="negative input"):
            compiled.execute(-1).get(timeout_s=30)
        # The pipeline stays usable after a per-execution error.
        assert compiled.execute(5).get(timeout_s=30) == 6
    finally:
        compiled.teardown()


def test_compiled_dag_same_actor_local_memo(cluster):
    """Two steps on one actor pass values in-process, not via channels."""

    @ray_tpu.remote
    class S:
        def first(self, x):
            return x + 1

        def second(self, x):
            return x * 2

    a = S.remote()
    with InputNode() as inp:
        dag = a.second.bind(a.first.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        # first's output has no cross-actor consumer: only the final
        # output channel exists (plus input + ready).
        data_chans = [n for n in compiled._channels if "ready" not in n]
        assert len(data_chans) == 2  # input + output
        assert compiled.execute(4).get(timeout_s=30) == 10
    finally:
        compiled.teardown()


def test_compiled_dag_revisited_actor(cluster):
    """A -> B -> A: the revisited actor must run its early step (feeding
    B) before blocking on B's output — lazy per-step channel acquisition,
    not read-everything-up-front."""

    @ray_tpu.remote
    class S:
        def __init__(self, tag):
            self.tag = tag

        def f(self, x):
            return x + [self.tag]

    a, b = S.remote("a"), S.remote("b")
    with InputNode() as inp:
        dag = a.f.bind(b.f.bind(a.f.bind(inp)))
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        assert compiled.execute([]).get(timeout_s=30) == ["a", "b", "a"]
        assert compiled.execute(["x"]).get(timeout_s=30) == \
            ["x", "a", "b", "a"]
    finally:
        compiled.teardown()


def test_compiled_dag_multi_output_error_keeps_stream_aligned(cluster):
    """One branch failing must still drain BOTH output channels, so the
    next execution's outputs pair correctly."""

    @ray_tpu.remote
    class S:
        def __init__(self, fail_on):
            self.fail_on = fail_on

        def f(self, x):
            if x == self.fail_on:
                raise ValueError(f"boom on {x}")
            return x * 10

    a, b = S.remote(2), S.remote(None)
    with InputNode() as inp:
        dag = MultiOutputNode([a.f.bind(inp), b.f.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        assert compiled.execute(1).get(timeout_s=30) == [10, 10]
        r_bad = compiled.execute(2)
        r_good = compiled.execute(3)
        with pytest.raises(Exception, match="boom on 2"):
            r_bad.get(timeout_s=30)
        # A failed ref keeps raising the same error on repeat get.
        with pytest.raises(Exception, match="boom on 2"):
            r_bad.get(timeout_s=30)
        assert r_good.get(timeout_s=30) == [30, 30]
    finally:
        compiled.teardown()


def test_compiled_dag_out_of_order_get_fails_loudly(cluster):
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    a = S.remote()
    with InputNode() as inp:
        dag = a.f.bind(inp)
    compiled = dag.experimental_compile()
    try:
        r1 = compiled.execute(1)
        r2 = compiled.execute(2)
        with pytest.raises(RuntimeError, match="submission order"):
            r2.get(timeout_s=30)
        assert r1.get(timeout_s=30) == 1
        assert r2.get(timeout_s=30) == 2
    finally:
        compiled.teardown()


def test_compiled_dag_const_only_source_falls_back(cluster):
    """An actor step with no per-execution input would free-run; such
    graphs use the legacy path."""

    @ray_tpu.remote
    class S:
        def f(self):
            return 7

    a = S.remote()
    dag = a.f.bind()
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "legacy"
        assert ray_tpu.get(compiled.execute(), timeout=30) == 7
    finally:
        compiled.teardown()


def test_compiled_dag_function_node_falls_back(cluster):
    @ray_tpu.remote
    def plain(x):
        return x - 1

    with InputNode() as inp:
        dag = plain.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "legacy"
        # ensure_compiled turns the silent fallback into an error users
        # can opt into (the fast path was NOT taken here).
        with pytest.raises(RuntimeError, match="fell back"):
            compiled.ensure_compiled()
        ref = compiled.execute(10)
        assert ray_tpu.get(ref, timeout=30) == 9
    finally:
        compiled.teardown()


def test_compiled_dag_throughput_vs_actor_calls(cluster):
    """The channel pipeline beats by-ref actor calls on 1 MiB payloads.
    CI floor is 2x: this test also runs on single-core boxes where every
    hop is a context switch; on multi-core hosts the spin-path puts the
    gap at an order of magnitude (see benchmarks/channel_bench.py)."""

    @ray_tpu.remote
    class Fwd:
        def f(self, x):
            return x

    a = Fwd.remote()
    payload = np.random.rand(128, 1024)  # 1 MiB

    # Baseline: by-ref actor calls through the object store.
    ref = ray_tpu.put(payload)
    n_base = 50
    ray_tpu.get(a.f.remote(ref), timeout=30)
    t0 = time.time()
    for _ in range(n_base):
        ray_tpu.get(a.f.remote(ref), timeout=30)
    base_rate = n_base / (time.time() - t0)

    with InputNode() as inp:
        dag = a.f.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled._mode == "channels"
        compiled.execute(payload).get(timeout_s=30)  # warm
        n = 200
        window: list = []
        t0 = time.time()
        for _ in range(n):
            if len(window) >= 3:  # ring depth: keep the pipe full
                window.pop(0).get(timeout_s=30)
            window.append(compiled.execute(payload))
        for r in window:
            r.get(timeout_s=30)
        chan_rate = n / (time.time() - t0)
    finally:
        compiled.teardown()
    assert chan_rate > 2 * base_rate, (chan_rate, base_rate)
