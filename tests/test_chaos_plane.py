"""Chaos plane: deterministic fault injection, unified retry policy,
whole-node death recovery.

Mirrors the reference's fault-injection strategy (SURVEY.md §4 — every
RPC edge has retry/timeout semantics, the GCS reconciles node death
end-to-end, and faults are a *tested input*): the matrix injects
drop/delay/dup/error/partition into the transport (faultinject.py),
asserts the RetryPolicy absorbs them, and exercises whole-node death —
SIGKILL and partition — asserting requeue + lineage reconstruction +
actor restart and a provenance-carrying ObjectLostError instead of a
hang for unreconstructable objects.
"""

from __future__ import annotations

import os
import signal
import time
from concurrent.futures import TimeoutError as FutTimeout

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import faultinject, rpc
from ray_tpu._private.faultinject import FaultPlane
from ray_tpu._private.retry import (CircuitBreaker, CircuitOpenError,
                                    RetryPolicy)
from ray_tpu._private.worker_context import get_head
from ray_tpu.exceptions import ObjectLostError
from ray_tpu.util.placement_group import (placement_group,
                                          remove_placement_group)

import chaos_utils as cu

# ---------------------------------------------------------------------------
# fault plane: determinism + filtering (no cluster)


def test_fault_plane_same_seed_same_decisions():
    spec = {"seed": 42, "rules": [{"drop": 0.3}]}
    p1, p2 = FaultPlane.from_spec(spec), FaultPlane.from_spec(spec)
    seq1 = [p1.decide("send", "p", "k") is not None for _ in range(300)]
    seq2 = [p2.decide("send", "p", "k") is not None for _ in range(300)]
    assert seq1 == seq2
    assert any(seq1) and not all(seq1)  # actually probabilistic


def test_fault_plane_different_seed_differs():
    s1 = [FaultPlane.from_spec({"seed": 1, "rules": [{"drop": 0.5}]})
          .decide("send", "p", "k") is not None for _ in range(200)]
    p2 = FaultPlane.from_spec({"seed": 2, "rules": [{"drop": 0.5}]})
    s2 = [p2.decide("send", "p", "k") is not None for _ in range(200)]
    assert s1 != s2


def test_fault_rules_filter_by_peer_and_kind():
    plane = FaultPlane.from_spec({"rules": [
        {"peer": "node_agent", "kind": "spawn_*", "partition": True}]})
    assert plane.decide("send", "node_agent|x", "spawn_worker").drop
    assert plane.decide("send", "node_agent|x", "task_finished") is None
    assert plane.decide("send", "worker|w-1", "spawn_worker") is None
    # recv direction not matched by a send-direction rule
    assert plane.decide("recv", "node_agent|x", "spawn_worker") is None


def test_fault_rule_rejects_unknown_fields():
    with pytest.raises(ValueError, match="unknown fault-rule"):
        FaultPlane.from_spec({"rules": [{"dorp": 0.5}]})


def test_partition_rule_drops_everything():
    plane = FaultPlane.from_spec({"rules": [
        {"kind": "agent_heartbeat", "partition": True}]})
    for _ in range(50):
        act = plane.decide("send", "anything", "agent_heartbeat")
        assert act is not None and act.drop
    assert plane.stats["drop:agent_heartbeat"] == 50


def test_inject_context_scopes_and_restores():
    assert faultinject.active() is None or True  # whatever the env says
    before = faultinject.active()
    with faultinject.inject({"rules": [{"drop": 1.0}]}) as plane:
        assert faultinject.active() is plane
        with faultinject.inject({"rules": []}) as inner:
            assert faultinject.active() is inner
        assert faultinject.active() is plane
    assert faultinject.active() is before


def test_delay_action_sleeps_on_send():
    with faultinject.inject({"rules": [
            {"kind": "ping", "delay_ms": 80}]}) as plane:
        t0 = time.monotonic()
        drop, dup = faultinject.apply_send("p", "ping")
        took = time.monotonic() - t0
        assert not drop and not dup
        assert took >= 0.06
        assert plane.stats["delay:ping"] == 1


# ---------------------------------------------------------------------------
# retry policy + circuit breaker (no cluster)


def test_retry_policy_backoff_grows_and_caps():
    p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=0.5,
                    jitter=0.0)
    assert [p.delay(i) for i in (1, 2, 3, 4, 5)] == [0.1, 0.2, 0.4, 0.5, 0.5]
    pj = RetryPolicy(base_delay_s=0.1, jitter=0.2)
    for i in range(1, 5):
        assert 0.0 <= pj.delay(i) <= pj.max_delay_s * 1.2


def test_retry_policy_run_retries_then_succeeds():
    calls = []

    def flaky(_budget):
        calls.append(_budget)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    p = RetryPolicy(max_attempts=5, base_delay_s=0.01, jitter=0.0)
    assert p.run(flaky, retry_on=(OSError,)) == "ok"
    assert len(calls) == 3


def test_retry_policy_deadline_bounds_total_time():
    p = RetryPolicy(max_attempts=100, base_delay_s=0.05, max_delay_s=0.05,
                    jitter=0.0, deadline_s=0.3, attempt_timeout_s=None)
    t0 = time.monotonic()
    with pytest.raises(OSError):
        p.run(lambda _b: (_ for _ in ()).throw(OSError("down")),
              retry_on=(OSError,))
    assert time.monotonic() - t0 < 2.0


def test_retry_policy_non_retryable_propagates_immediately():
    calls = []

    def boom(_b):
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=5, base_delay_s=0.01).run(
            boom, retry_on=(OSError,))
    assert len(calls) == 1


def test_circuit_breaker_opens_and_half_open_probe():
    b = CircuitBreaker(threshold=3, reset_s=0.2, name="t")
    for _ in range(3):
        assert b.allow()
        b.record_failure()
    assert b.open and not b.allow()  # open: fail fast
    time.sleep(0.25)
    assert b.allow()       # the single half-open probe
    assert not b.allow()   # concurrent callers still fail fast
    b.record_success()
    assert b.allow() and not b.open


def test_retry_run_respects_open_breaker():
    b = CircuitBreaker(threshold=1, reset_s=60.0)
    b.record_failure()
    with pytest.raises(CircuitOpenError):
        RetryPolicy(max_attempts=3, base_delay_s=0.01).run(
            lambda _b: "never", breaker=b, describe="probe")


# ---------------------------------------------------------------------------
# rpc transport under injection (loopback server, no cluster)


@pytest.fixture()
def echo_pair():
    hits = {"echo": 0, "note": 0}

    def handler(kind, body, conn):
        if kind in hits:
            hits[kind] += 1
        return body

    server = rpc.Server(handler)
    conn = rpc.connect(("127.0.0.1", server.address[1]), name="chaos-client")
    yield conn, hits
    conn.close()
    server.stop()


def test_call_retry_absorbs_dropped_replies(echo_pair):
    conn, hits = echo_pair
    # Half the replies vanish; the retried call resends (fresh msg_id)
    # and lands within the attempt budget.
    with faultinject.inject({"seed": 5, "rules": [
            {"kind": rpc.REPLY, "drop": 0.5}]}) as plane:
        policy = RetryPolicy(max_attempts=10, base_delay_s=0.01,
                             jitter=0.0, deadline_s=20.0,
                             attempt_timeout_s=0.25)
        for i in range(10):
            assert conn.call("echo", {"i": i}, retry=policy) == {"i": i}
        assert plane.stats["drop:" + rpc.REPLY] >= 1
    assert hits["echo"] >= 10  # at-least-once: drops re-executed


def test_call_without_retry_times_out_under_reply_partition(echo_pair):
    conn, _ = echo_pair
    with faultinject.inject({"rules": [
            {"kind": rpc.REPLY, "partition": True}]}):
        with pytest.raises(FutTimeout):
            conn.call("echo", {"x": 1}, timeout=0.3)


def test_call_retry_absorbs_recv_side_request_loss(echo_pair):
    conn, _ = echo_pair
    # The server's reader drops half the incoming requests.
    with faultinject.inject({"seed": 9, "rules": [
            {"kind": "echo", "direction": "recv", "drop": 0.5}]}):
        policy = RetryPolicy(max_attempts=10, base_delay_s=0.01,
                             jitter=0.0, attempt_timeout_s=0.25)
        assert conn.call("echo", {"v": 7}, retry=policy) == {"v": 7}


def test_call_retry_absorbs_injected_connection_errors(echo_pair):
    conn, _ = echo_pair
    with faultinject.inject({"seed": 3, "rules": [
            {"kind": "echo", "error": 0.5}]}):
        policy = RetryPolicy(max_attempts=10, base_delay_s=0.01,
                             jitter=0.0, attempt_timeout_s=0.5)
        for i in range(5):
            assert conn.call("echo", {"i": i}, retry=policy) == {"i": i}


def test_injected_error_without_retry_raises_connection_lost(echo_pair):
    conn, _ = echo_pair
    with faultinject.inject({"rules": [{"kind": "echo", "error": 1.0}]}):
        with pytest.raises(rpc.ConnectionLost, match="injected"):
            conn.call("echo", {})
    # The socket itself survived the injected error: plane off, all good.
    assert conn.call("echo", {"back": 1}, timeout=5) == {"back": 1}


def test_dup_action_duplicates_cast(echo_pair):
    conn, hits = echo_pair
    with faultinject.inject({"rules": [{"kind": "note", "dup": 1.0}]}):
        conn.cast("note", {})
        deadline = time.monotonic() + 5
        while hits["note"] < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert hits["note"] == 2


def test_delay_rule_slows_but_completes(echo_pair):
    conn, _ = echo_pair
    with faultinject.inject({"rules": [{"kind": "echo", "delay_ms": 60}]}):
        t0 = time.monotonic()
        assert conn.call("echo", {"ok": 1}, timeout=5) == {"ok": 1}
        assert time.monotonic() - t0 >= 0.05


# ---------------------------------------------------------------------------
# bulk plane under injection (no cluster)


def test_bulk_pull_retries_injected_faults():
    from ray_tpu._private import bulk_transfer

    payload = os.urandom(256 * 1024)

    def reader(object_id, start, length):
        view = memoryview(payload)[start:start + length]
        return view, lambda: None

    server = bulk_transfer.BulkServer(reader, host="127.0.0.1")
    try:
        addr = ("127.0.0.1", server.address[1])
        with faultinject.inject({"seed": 13, "rules": [
                {"peer": "bulk|", "drop": 0.5}]}) as plane:
            policy = RetryPolicy(max_attempts=12, base_delay_s=0.01,
                                 jitter=0.0, deadline_s=30.0)
            out = bulk_transfer.pull_object(addr, "obj", len(payload),
                                            retry=policy)
            assert bytes(out) == payload
            assert plane.stats["drop:bulk_pull"] >= 1
        # Without retry, a partitioned bulk plane raises BulkError fast.
        with faultinject.inject({"rules": [
                {"peer": "bulk|", "partition": True}]}):
            with pytest.raises(bulk_transfer.BulkError):
                bulk_transfer.pull_object(addr, "obj", len(payload))
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# config knobs


def test_fault_and_retry_config_env_knobs(monkeypatch):
    from ray_tpu._private.config import Config

    monkeypatch.setenv("RAY_TPU_FAULT_SPEC",
                       '{"seed": 4, "rules": [{"drop": 0.1}]}')
    monkeypatch.setenv("RAY_TPU_RPC_RETRY_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("RAY_TPU_RPC_BREAKER_THRESHOLD", "2")
    cfg = Config().apply_overrides()
    assert cfg.fault_spec == {"seed": 4, "rules": [{"drop": 0.1}]}
    assert cfg.rpc_retry_max_attempts == 7
    assert cfg.rpc_breaker_threshold == 2


# ---------------------------------------------------------------------------
# whole-node death: SIGKILL and partition
# (head + one agent node as a subprocess, like test_multinode)


@pytest.fixture()
def chaos_cluster():
    """Head (2 CPUs) + agent node (4 CPUs) with tight health timing."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                 _system_config={"health_check_period_s": 0.5,
                                 "health_check_timeout_s": 4.0})
    head = get_head()
    address = f"{head.address[0]}:{head.address[1]}"
    agents: list = []
    yield address, agents
    for a in agents:
        cu.stop_agent(a)
    ray_tpu.shutdown()


def test_agent_sigkill_mid_flood_recovers(chaos_cluster):
    """SIGKILL the node agent while a retryable task flood is leased on
    it: tasks requeue onto surviving nodes, a lost P2P object
    reconstructs through lineage, and the actor restarts elsewhere."""
    address, agents = chaos_cluster
    agent = cu.start_agent(address, node_id="node-chaos")
    agents.append(agent)
    cu.wait_nodes(2)

    # Lineage bait: a P2P payload hosted only on the doomed node.
    @ray_tpu.remote(max_retries=3)
    def produce():
        return np.full(1024 * 1024, 3.0)  # 8 MiB -> agent store

    obj = produce.options(
        scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
            node_id="node-chaos", soft=True)).remote()
    assert ray_tpu.get(obj, timeout=60).sum() == 3.0 * 1024 * 1024

    @ray_tpu.remote(max_restarts=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    counter = Counter.options(
        scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
            node_id="node-chaos", soft=True)).remote()
    assert ray_tpu.get(counter.bump.remote(), timeout=60) == 1

    @ray_tpu.remote(max_retries=5)
    def work(i):
        time.sleep(0.25)
        return i * 7

    refs = [work.remote(i) for i in range(16)]
    time.sleep(1.0)  # let leases land on the agent node
    agent.send_signal(signal.SIGKILL)
    agent.wait(timeout=10)

    # Requeue: every leased task completes on the surviving node.
    results = ray_tpu.get(refs, timeout=120)
    assert sorted(results) == [i * 7 for i in range(16)]
    cu.wait_alive_nodes_at_most(1, timeout=30)

    # Lineage reconstruction: the P2P payload died with the node.
    assert ray_tpu.get(obj, timeout=60).sum() == 3.0 * 1024 * 1024

    # Actor restart: fresh incarnation (state reset), same handle.
    deadline = time.monotonic() + 60
    val = None
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(counter.bump.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.3)
    assert val == 1  # restarted => state reset


def test_unreconstructable_put_raises_object_lost(chaos_cluster):
    """put() data hosted on a killed node has no lineage: the get must
    raise a provenance-carrying ObjectLostError, not hang."""
    address, agents = chaos_cluster
    agent = cu.start_agent(address, node_id="node-loss")
    agents.append(agent)
    cu.wait_nodes(2)

    @ray_tpu.remote(resources={"node:node-loss": 0.001})
    def stash():
        # 8 MiB put from a worker on the agent node -> agent store,
        # directory-only on the head, NO lineage (it's a put).
        return [ray_tpu.put(np.ones(1024 * 1024))]

    (inner,) = ray_tpu.get(stash.remote(), timeout=60)
    head = get_head()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        e = head.objects.get(inner.hex())
        if e is not None and e.location == "node-loss":
            break
        time.sleep(0.2)
    e = head.objects.get(inner.hex())
    assert e is not None and e.location == "node-loss", \
        "test setup: put payload should live on the agent node"

    agent.send_signal(signal.SIGKILL)
    agent.wait(timeout=10)

    t0 = time.monotonic()
    with pytest.raises(ObjectLostError) as info:
        ray_tpu.get(inner, timeout=60)
    assert time.monotonic() - t0 < 30, "loss must surface, not hang"
    # Provenance: which node lost it and who owned it.
    assert info.value.node_id == "node-loss"
    assert info.value.owner_id
    assert "node-loss" in str(info.value)


def test_partitioned_node_declared_dead_after_grace(chaos_cluster):
    """A node partitioned from the head (heartbeats and re-registration
    lost in transit; TCP session never closes by itself) is declared
    dead after health_check_timeout_s and its work requeues.
    Reference: gcs_health_check_manager.h:45."""
    address, agents = chaos_cluster
    agent = cu.start_agent(address, node_id="node-part")
    agents.append(agent)
    cu.wait_nodes(2)

    @ray_tpu.remote(max_retries=5)
    def work(i):
        time.sleep(0.3)
        return i + 100

    # Head-side partition: the head stops hearing the agent — both its
    # heartbeats and any re-registration attempt vanish on arrival.
    with faultinject.inject({"rules": [
            {"kind": "agent_heartbeat", "direction": "recv",
             "partition": True},
            {"kind": "register_node", "direction": "recv",
             "partition": True}]}):
        refs = [work.remote(i) for i in range(8)]
        # The node goes silent past the 4 s grace: declared dead even
        # though its connection never closed; leased work requeues.
        cu.wait_alive_nodes_at_most(1, timeout=30)
        assert sorted(ray_tpu.get(refs, timeout=120)) == \
            list(range(100, 108))


# ---------------------------------------------------------------------------
# the acceptance matrix: 5% drop + 50 ms delay on head<->agent RPCs
# (slow tier: several cluster bring-ups under injected latency)


@pytest.mark.slow
def test_workloads_complete_under_head_agent_drop_delay(chaos_cluster):
    """With the acceptance-criteria spec injected on BOTH ends of the
    head<->agent link (the agent process via RAY_TPU_FAULT_SPEC, the
    head in-process via inject()), the fault-tolerance workloads still
    complete: retries absorb the faults. Matrix: tasks, actors,
    generators, bulk transfer, placement groups."""
    address, agents = chaos_cluster
    spec = cu.drop_delay_spec("node_agent", drop=0.05, delay_ms=50)
    agent = cu.start_agent(address, node_id="node-chaos2",
                           extra_env=cu.spec_env(spec))
    agents.append(agent)
    # Head-side sends to the agent match "node_agent" via the
    # "node_agent_for:<id>" descriptor suffix.
    with faultinject.inject(spec) as plane:
        cu.wait_nodes(2)

        # -- tasks under retries (the test_fault_tolerance workload) --
        @ray_tpu.remote(max_retries=10)
        def chunk(i):
            time.sleep(0.1)
            return i

        refs = [chunk.remote(i) for i in range(12)]
        assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(12))

        # -- actors --
        @ray_tpu.remote(max_restarts=2)
        class Acc:
            def __init__(self):
                self.v = 0

            def add(self, d):
                self.v += d
                return self.v

        acc = Acc.remote()
        for i in range(5):
            assert ray_tpu.get(acc.add.remote(1), timeout=60) == i + 1

        # -- streaming generators --
        @ray_tpu.remote
        def gen(n):
            for i in range(n):
                yield i * 2

        got = [ray_tpu.get(r, timeout=60) for r in gen.remote(5)]
        assert got == [0, 2, 4, 6, 8]

        # -- bulk transfer (P2P payload crosses the injected link) --
        @ray_tpu.remote(resources={"node:node-chaos2": 0.001},
                        max_retries=5)
        def produce():
            return np.arange(1024 * 1024, dtype=np.float64)  # 8 MiB

        arr = ray_tpu.get(produce.remote(), timeout=120)
        assert arr.shape == (1024 * 1024,) and arr[-1] == 1024 * 1024 - 1

        # -- placement groups --
        pg = placement_group([{"CPU": 1}], strategy="PACK")
        ray_tpu.get(pg.ready(), timeout=60)

        @ray_tpu.remote(num_cpus=1, max_retries=5)
        def in_pg():
            return "pg-ok"

        strat = ray_tpu.PlacementGroupSchedulingStrategy(placement_group=pg)
        assert ray_tpu.get(
            in_pg.options(scheduling_strategy=strat).remote(),
            timeout=120) == "pg-ok"
        remove_placement_group(pg)

        # The chaos was real: the plane actually dropped/delayed frames.
        assert sum(v for k, v in plane.stats.items()
                   if k.startswith(("drop:", "delay:"))) > 0


# ---------------------------------------------------------------------------
# direct-call plane under chaos: worker death mid-pipeline, link drops


@pytest.fixture()
def direct_cluster():
    """Local cluster with a fast direct-plane watchdog so re-routing
    fires in test time, not the production 10 s."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    saved = GLOBAL_CONFIG.direct_resubmit_timeout_s
    GLOBAL_CONFIG.direct_resubmit_timeout_s = 1.0
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    GLOBAL_CONFIG.direct_resubmit_timeout_s = saved
    ray_tpu.shutdown()


def _wait_direct_route(actor_id: str, timeout: float = 15.0):
    from ray_tpu._private.worker_context import global_runtime

    rt = global_runtime()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = rt._direct.routes.get(actor_id)
        if r is not None and r.mode == "direct":
            return r
        time.sleep(0.05)
    raise TimeoutError("actor route never entered direct mode")


def test_actor_worker_sigkill_mid_direct_pipeline(direct_cluster):
    """SIGKILL an actor's worker while a direct pipeline is in flight:
    the head revokes the route, the actor restarts, and every in-flight
    direct call re-routes (max_task_retries) onto the restarted
    incarnation instead of hanging — the owner's recovery and the
    head's death requeue dedup by task state, so calls complete exactly
    once per surviving attempt."""

    @ray_tpu.remote(max_restarts=1, max_task_retries=-1)
    class Slow:
        def __init__(self):
            self.seen = 0

        def work(self, i):
            self.seen += 1
            time.sleep(0.05)
            return i

    a = Slow.remote()
    assert ray_tpu.get(a.work.remote(-1), timeout=60) == -1
    _wait_direct_route(a._actor_id)

    head = get_head()
    with head.lock:
        rec = head.workers.get(head.actors[a._actor_id].worker_id)
        pid = rec.pid if rec else None
    assert pid, "actor worker pid unknown"

    refs = [a.work.remote(i) for i in range(24)]
    time.sleep(0.15)  # a few executed, the rest mid-pipeline
    os.kill(pid, signal.SIGKILL)

    # Every call resolves on the restarted incarnation (at-least-once
    # execution for the ones whose results died with the worker).
    assert ray_tpu.get(refs, timeout=120) == list(range(24))
    # The restarted actor keeps serving — and the route heals back to
    # direct mode for new calls.
    assert ray_tpu.get(a.work.remote(99), timeout=60) == 99
    _wait_direct_route(a._actor_id, timeout=30)
    assert ray_tpu.get(a.work.remote(100), timeout=60) == 100


def test_direct_link_drop_spills_back_to_head(direct_cluster):
    """Blackhole the direct owner→worker link (send-side partition of
    direct_push frames): unacked calls hit the watchdog and re-route
    through the head path, which completes them — spillback, not a
    hang. Delivery acks are dropped too, so recovery is at-least-once
    by design."""
    from ray_tpu._private.worker_context import global_runtime

    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, d):
            self.total += d
            return self.total

        def read(self):
            return self.total

    a = Acc.remote()
    assert ray_tpu.get(a.add.remote(0), timeout=60) == 0
    _wait_direct_route(a._actor_id)
    rt = global_runtime()
    # Establish the direct link with real traffic first — the failure
    # under test is an ESTABLISHED link going black, not a dial error.
    for _ in range(3):
        ray_tpu.get(a.add.remote(0), timeout=60)
    assert rt._direct.stats["direct_actor_calls"] >= 3
    recovered_before = rt._direct.stats["recovered"]

    # Peer-level partition: direct pushes ride CAST_BATCH frames, so
    # the blackhole filters by the owner-peer connection, not by the
    # inner message kind — everything the owner sends the worker
    # directly is eaten; the head connection stays healthy.
    with faultinject.inject({"rules": [
            {"peer": "owner-peer", "direction": "send",
             "partition": True}]}):
        refs = [a.add.remote(1) for i in range(8)]
        # The pushes are eaten by the fault plane; the watchdog must
        # re-route them through the head within its 1 s timeout.
        results = ray_tpu.get(refs, timeout=60)
    # Monotone partial sums in SOME order — each call executed exactly
    # once here (the drop ate the push, never a duplicate), and none
    # hung.
    assert sorted(results) == list(range(1, 9))
    assert ray_tpu.get(a.read.remote(), timeout=60) == 8
    # Every blackholed call was re-routed through the head.
    assert rt._direct.stats["recovered"] - recovered_before >= 8


# ---------------------------------------------------------------------------
# overload-protection plane under chaos: flood + drop/delay


@pytest.mark.slow
def test_overload_flood_under_drop_delay_degrades_gracefully(chaos_cluster):
    """Sustained ~10x-capacity submit flood while the head<->agent link
    drops and delays frames: the overload plane keeps the head queue
    depth bounded (admission budgets), sheds expired work with typed
    TaskTimeoutError, fast-fails over-budget submits with typed
    PendingCallsLimitError instead of letting the backlog grow into an
    OOM-kill cascade, and returns to steady state once the flood stops
    (no worker memory-monitor-killed along the way)."""
    import threading

    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.exceptions import (PendingCallsLimitError,
                                    TaskTimeoutError)

    address, agents = chaos_cluster
    head = get_head()
    spec = cu.drop_delay_spec("node_agent", drop=0.05, delay_ms=30)
    agent = cu.start_agent(address, node_id="node-flood",
                           extra_env=cu.spec_env(spec))
    agents.append(agent)
    kills_before = (head.memory_monitor.num_kills
                    if head.memory_monitor else 0)
    saved = (GLOBAL_CONFIG.admission_max_pending_per_owner,
             GLOBAL_CONFIG.admission_mode)
    budget = 24
    GLOBAL_CONFIG.admission_max_pending_per_owner = budget
    head.config.admission_max_pending_per_owner = budget
    max_pending = [0]
    stop = threading.Event()

    def sample():
        while not stop.is_set():
            max_pending[0] = max(max_pending[0], head.pending_total)
            time.sleep(0.005)

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    try:
        with faultinject.inject(spec) as plane:
            cu.wait_nodes(2)

            @ray_tpu.remote(max_retries=5)
            def grind(t):
                time.sleep(t)
                return 1

            # Phase 1 — blocking-submit flood, deadline-stamped: ~6 CPUs
            # of capacity vs 120 x 0.2 s of demand with 2 s deadlines.
            refs = [grind.options(timeout_s=2.0).remote(0.2)
                    for _ in range(120)]
            done = shed = 0
            for r in refs:
                try:
                    assert ray_tpu.get(r, timeout=120) == 1
                    done += 1
                except TaskTimeoutError:
                    shed += 1
            assert done + shed == 120
            assert done > 0, "the flood must not starve all work"

            # Phase 2 — fast-fail mode: over-budget submits are TYPED
            # rejections at .remote(), never an unbounded queue.
            GLOBAL_CONFIG.admission_mode = "fail"
            refs2, rejected = [], 0
            for _ in range(80):
                try:
                    refs2.append(grind.options(timeout_s=5.0).remote(0.1))
                except PendingCallsLimitError:
                    rejected += 1
            assert rejected > 0, "over-budget submits must be rejected"
            done2 = shed2 = 0
            for r in refs2:
                try:
                    ray_tpu.get(r, timeout=120)
                    done2 += 1
                except TaskTimeoutError:
                    shed2 += 1
            assert done2 + shed2 == len(refs2)

            # The chaos was real.
            assert sum(v for k, v in plane.stats.items()
                       if k.startswith(("drop:", "delay:"))) > 0

        stop.set()
        sampler.join(timeout=5)
        # Bounded head backlog throughout the flood: the owner budget
        # caps queued+inflight, so head-side pending can never exceed it
        # (small slack for requeues riding worker death/retry paths).
        assert max_pending[0] <= budget + 4, \
            f"head queue depth {max_pending[0]} escaped the budget"
        # Graceful degradation — not an OOM-kill cascade.
        kills_after = (head.memory_monitor.num_kills
                       if head.memory_monitor else 0)
        assert kills_after == kills_before
        # Recovery: the cluster serves normally after the flood.
        GLOBAL_CONFIG.admission_mode = "block"
        assert ray_tpu.get(
            [grind.options(timeout_s=60.0).remote(0.01)
             for _ in range(8)], timeout=120) == [1] * 8
        deadline = time.monotonic() + 30
        while head.pending_total and time.monotonic() < deadline:
            time.sleep(0.05)
        assert head.pending_total == 0
    finally:
        stop.set()
        (GLOBAL_CONFIG.admission_max_pending_per_owner,
         GLOBAL_CONFIG.admission_mode) = saved
        head.config.admission_max_pending_per_owner = saved[0]
