"""`ray-tpu job ...` and `ray-tpu serve ...` CLI subcommands (reference:
dashboard/modules/job/cli.py, serve/scripts.py)."""

import json
import subprocess
import sys
import time

import pytest


@pytest.fixture(scope="module")
def cli_head():
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts", "start", "--head",
         "--port", "0", "--num-cpus", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    line = proc.stdout.readline()
    # start prints e.g. "head started at 127.0.0.1:PORT"
    address = line.strip().rsplit(" ", 1)[-1]
    assert ":" in address, line
    yield address
    proc.terminate()
    proc.wait(timeout=15)


def _cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", *args],
        capture_output=True, text=True, timeout=timeout,
    )


def test_job_submit_status_logs_list(cli_head):
    out = _cli("job", "submit", "--address", cli_head, "--wait",
               "--", sys.executable, "-c", "print('JOB-RAN')")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "SUCCEEDED" in out.stdout
    assert "JOB-RAN" in out.stdout
    job_id = out.stdout.splitlines()[0].split()[-1]

    st = _cli("job", "status", "--address", cli_head, job_id)
    assert st.returncode == 0
    assert json.loads(st.stdout)["status"] == "SUCCEEDED"

    logs = _cli("job", "logs", "--address", cli_head, job_id)
    assert "JOB-RAN" in logs.stdout

    ls = _cli("job", "list", "--address", cli_head)
    assert job_id in ls.stdout


def test_job_stop(cli_head):
    out = _cli("job", "submit", "--address", cli_head,
               "--", sys.executable, "-c", "import time; time.sleep(60)")
    job_id = out.stdout.splitlines()[0].split()[-1]
    time.sleep(1.0)
    stop = _cli("job", "stop", "--address", cli_head, job_id)
    assert stop.returncode == 0
    assert "stopped" in stop.stdout
    deadline = time.time() + 20
    while time.time() < deadline:
        st = json.loads(_cli("job", "status", "--address", cli_head,
                             job_id).stdout)
        if st["status"] in ("STOPPED", "FAILED"):
            break
        time.sleep(0.5)
    assert st["status"] in ("STOPPED", "FAILED")


def test_memory_and_logs_cli(cli_head):
    """`ray-tpu memory` (reference: `ray memory` — internal_api.py
    memory_summary) and `ray-tpu logs [name]` (reference: `ray logs`)."""
    # Park an object in the cluster via a job so memory has a row.
    out = _cli("job", "submit", "--address", cli_head, "--wait", "--",
               sys.executable, "-c",
               "import ray_tpu, os;"
               f"ray_tpu.init(address={cli_head!r});"
               "r = ray_tpu.put(b'x' * 100_000);"
               "print('LOGS-CLI-LINE');"
               "ray_tpu.shutdown()")
    assert out.returncode == 0, out.stdout + out.stderr

    mem = _cli("memory", "--address", cli_head)
    assert mem.returncode == 0, mem.stdout + mem.stderr
    assert "OBJECT ID" in mem.stdout and "store:" in mem.stdout
    mem_j = json.loads(_cli("memory", "--address", cli_head,
                            "--json").stdout)
    assert "store" in mem_j and isinstance(mem_j["objects"], list)
    # The text summary renders the REAL store stats keys — a head with
    # a default store must show nonzero capacity, not "0/0".
    assert mem_j["store"]["capacity"] > 0
    assert f"/{mem_j['store']['capacity']} bytes used" in mem.stdout

    idx = _cli("logs", "--address", cli_head)
    assert idx.returncode == 0, idx.stdout + idx.stderr
    names = [ln.split()[-1] for ln in idx.stdout.splitlines() if ln.strip()]
    assert names, "no logs listed"
    found = False
    for name in names:
        tail = _cli("logs", name, "--address", cli_head)
        assert tail.returncode == 0
        if "LOGS-CLI-LINE" in tail.stdout:
            found = True
    assert found, f"job print not in any log: {names}"


def test_stop_cli():
    """`ray-tpu stop` terminates a CLI-started head (reference: `ray
    stop`). Own head — the module fixture's must survive other tests."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts", "start", "--head",
         "--port", "0", "--num-cpus", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        address = proc.stdout.readline().strip().rsplit(" ", 1)[-1]
        assert ":" in address
        out = _cli("stop", "--address", address)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "stopping head" in out.stdout
        assert proc.wait(timeout=20) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_serve_deploy_status_shutdown(cli_head, tmp_path):
    config = {
        "applications": [{
            "name": "default",
            "deployments": [{
                "name": "Doubler",
                "import_path": "tests.serve_config_helpers.Doubler",
                "num_replicas": 1,
                "route_prefix": "/",
                "init_args": [],
                "init_kwargs": {},
            }],
        }]
    }
    cfg_file = tmp_path / "serve.json"
    cfg_file.write_text(json.dumps(config))
    out = _cli("serve", "deploy", "--address", cli_head, str(cfg_file))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "deployed" in out.stdout

    st = _cli("serve", "status", "--address", cli_head)
    assert "Doubler" in st.stdout

    down = _cli("serve", "shutdown", "--address", cli_head)
    assert down.returncode == 0
    # Cross-process shutdown actually killed the controller: a fresh
    # status query reports nothing (and must not resurrect serve).
    st2 = _cli("serve", "status", "--address", cli_head)
    assert st2.returncode == 0
    assert json.loads(st2.stdout) == {}


def test_serve_run_import_path(cli_head):
    """`ray-tpu serve run module:attr` (reference: serve/scripts.py run)
    deploys a zero-arg builder or a bound app by import path."""
    out = _cli("serve", "run", "--address", cli_head,
               "tests.serve_config_helpers:doubler_app")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "running" in out.stdout
    st = _cli("serve", "status", "--address", cli_head)
    assert "Doubler" in st.stdout
    down = _cli("serve", "shutdown", "--address", cli_head)
    assert down.returncode == 0
