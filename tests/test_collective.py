"""Host-level collective library tests across real actor processes.

Reference coverage analogue: python/ray/util/collective tests (gloo backend).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Rank:
    def __init__(self, world_size, rank):
        from ray_tpu.util import collective

        self.g = collective.init_collective_group(world_size, rank, group_name="t")
        self.rank = rank

    def allreduce(self, value):
        return self.g.allreduce(np.full(4, value, dtype=np.float64))

    def broadcast(self, value=None):
        return self.g.broadcast(np.full(2, value) if value is not None else None, src=0)

    def allgather(self, value):
        return self.g.allgather(np.array([value]))

    def reducescatter(self):
        return self.g.reducescatter(np.arange(4, dtype=np.float64))

    def sendto(self, dst, value):
        self.g.send(np.array([value]), dst)

    def recvfrom(self, src):
        return self.g.recv(src)

    def broadcast_many(self, values, delay=0.0):
        """src=0 path: fire k broadcasts back to back."""
        import time

        time.sleep(delay)
        if self.rank == 0:
            return [float(self.g.broadcast(np.array([v]), timeout=20)[0])
                    for v in values]
        return [float(self.g.broadcast(None, timeout=20)[0])
                for _ in values]


def test_allreduce(cluster):
    world = [Rank.remote(3, r) for r in range(3)]
    outs = ray_tpu.get([w.allreduce.remote(float(i + 1)) for i, w in enumerate(world)], timeout=60)
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 6.0))


def test_broadcast(cluster):
    world = [Rank.remote(2, r) for r in range(2)]
    outs = ray_tpu.get(
        [world[0].broadcast.remote(7.0), world[1].broadcast.remote(None)], timeout=60
    )
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[1], np.full(2, 7.0))


def test_broadcast_slow_joiner(cluster):
    """Regression: the source must not outrun consumers — its lazy seq-2
    key GC would delete broadcasts a slow joiner (worker still importing
    jax) never read, deadlocking it. Broadcast is all-blocking now."""
    world = [Rank.remote(2, r) for r in range(2)]
    vals = [float(i) for i in range(6)]
    fast = world[0].broadcast_many.remote(vals)
    slow = world[1].broadcast_many.remote(vals, delay=2.0)
    out_fast, out_slow = ray_tpu.get([fast, slow], timeout=60)
    assert out_fast == vals and out_slow == vals


def test_allgather_and_reducescatter(cluster):
    world = [Rank.remote(2, r) for r in range(2)]
    gathered = ray_tpu.get([w.allgather.remote(r) for r, w in enumerate(world)], timeout=60)
    for g in gathered:
        assert [x.item() for x in g] == [0, 1]
    shards = ray_tpu.get([w.reducescatter.remote() for w in world], timeout=60)
    np.testing.assert_array_equal(np.concatenate(shards), np.arange(4) * 2.0)


def test_p2p(cluster):
    world = [Rank.remote(2, r) for r in range(2)]
    send = world[0].sendto.remote(1, 42.0)
    out = ray_tpu.get(world[1].recvfrom.remote(0), timeout=60)
    ray_tpu.get(send, timeout=60)
    np.testing.assert_array_equal(out, np.array([42.0]))
