"""Core runtime tests: tasks, objects, actors, fault tolerance.

Modeled on the reference's python/ray/tests/test_basic*.py,
test_actor*.py, test_reconstruction*.py coverage areas.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
    WorkerCrashedError,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------- objects


def test_put_get_roundtrip(cluster):
    for value in [1, "hello", {"a": [1, 2]}, (None, True), b"\x00\xff" * 100]:
        assert ray_tpu.get(ray_tpu.put(value)) == value


def test_put_get_numpy_large(cluster):
    arr = np.random.rand(512, 1024)  # 4 MiB -> shm path
    out = ray_tpu.get(ray_tpu.put(arr))
    np.testing.assert_array_equal(arr, out)


def test_get_many(cluster):
    refs = [ray_tpu.put(i) for i in range(50)]
    assert ray_tpu.get(refs) == list(range(50))


def test_get_timeout(cluster):
    @ray_tpu.remote
    def never():
        time.sleep(60)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(never.remote(), timeout=0.2)


# ---------------------------------------------------------------- tasks


def test_simple_task(cluster):
    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get(f.remote(21)) == 42


def test_task_dependencies(cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    ref = f.remote(0)
    for _ in range(5):
        ref = f.remote(ref)
    assert ray_tpu.get(ref) == 6


def test_task_numpy_arg(cluster):
    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    big = np.ones((256, 1024))
    assert ray_tpu.get(total.remote(ray_tpu.put(big))) == big.size


def test_multi_return(cluster):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_error_propagates_through_dependency(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("root cause")

    @ray_tpu.remote
    def consume(x):
        return x

    # The consumer receives the error when resolving its arg and fails too.
    with pytest.raises(TaskError, match="root cause"):
        ray_tpu.get(consume.remote(boom.remote()))


def test_parallel_execution(cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(0.5)
        return 1

    t0 = time.time()
    assert sum(ray_tpu.get([slow.remote() for _ in range(4)])) == 4
    assert time.time() - t0 < 1.9  # 4 serial would be >= 2.0


def test_task_options(cluster):
    @ray_tpu.remote
    def whoami():
        return os.getpid()

    assert isinstance(ray_tpu.get(whoami.options(num_cpus=2).remote()), int)


def test_runtime_env_env_vars(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_TEST_VAR": "abc"}})
    def read_env():
        return os.environ.get("MY_TEST_VAR")

    assert ray_tpu.get(read_env.remote()) == "abc"


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(0)) == 11


# ---------------------------------------------------------------- wait


def test_wait_basic(cluster):
    @ray_tpu.remote
    def quick(i):
        return i

    refs = [quick.remote(i) for i in range(4)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=4, timeout=10)
    assert len(ready) == 4 and not not_ready


def test_wait_partial(cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    @ray_tpu.remote
    def quick():
        return 1

    r_slow, r_quick = slow.remote(), quick.remote()
    ready, not_ready = ray_tpu.wait([r_slow, r_quick], num_returns=1, timeout=10)
    assert ready == [r_quick] and not_ready == [r_slow]
    ray_tpu.cancel(r_slow)


# ---------------------------------------------------------------- actors


def test_actor_basic(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def inc(self, k=1):
            self.v += k
            return self.v

    c = Counter.remote(100)
    assert ray_tpu.get(c.inc.remote()) == 101
    assert ray_tpu.get(c.inc.remote(9)) == 110


def test_actor_ordering(cluster):
    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.items = []

        def append(self, x):
            self.items.append(x)

        def get(self):
            return self.items

    log = Log.remote()
    for i in range(20):
        log.append.remote(i)
    assert ray_tpu.get(log.get.remote()) == list(range(20))


def test_named_actor(cluster):
    @ray_tpu.remote
    class Svc:
        def ping(self):
            return "pong"

    Svc.options(name="svc-test").remote()
    handle = ray_tpu.get_actor("svc-test")
    assert ray_tpu.get(handle.ping.remote()) == "pong"


def test_actor_handle_passing(cluster):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.v = {}

        def set(self, k, v):
            self.v[k] = v

        def get(self, k):
            return self.v.get(k)

    @ray_tpu.remote
    def writer(store):
        ray_tpu.get(store.set.remote("from", "task"))
        return True

    s = Store.remote()
    assert ray_tpu.get(writer.remote(s))
    assert ray_tpu.get(s.get.remote("from")) == "task"


def test_actor_error(cluster):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method failed")

    b = Bad.remote()
    with pytest.raises(TaskError, match="actor method failed"):
        ray_tpu.get(b.fail.remote())


def test_actor_death_and_restart(cluster):
    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def crash(self):
            os._exit(1)

        def ping(self):
            return "ok"

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == "ok"
    with pytest.raises(ActorDiedError):
        ray_tpu.get(f.crash.remote(), timeout=20)
    assert ray_tpu.get(f.ping.remote(), timeout=30) == "ok"


def test_kill_actor(cluster):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return 1

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote()) == 1
    ray_tpu.kill(v)
    time.sleep(0.5)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(v.ping.remote(), timeout=20)


def test_actor_max_concurrency(cluster):
    @ray_tpu.remote(max_concurrency=4)
    class Conc:
        def ready(self):
            return True

        def slow(self):
            time.sleep(0.6)
            return 1

    c = Conc.remote()
    ray_tpu.get(c.ready.remote(), timeout=20)  # wait for startup
    t0 = time.time()
    assert sum(ray_tpu.get([c.slow.remote() for _ in range(4)], timeout=20)) == 4
    assert time.time() - t0 < 2.0  # serial would be 2.4s


# ---------------------------------------------------------------- fault tolerance


def test_task_retry_on_worker_crash(cluster, tmp_path):
    marker = str(tmp_path / "marker")

    @ray_tpu.remote(max_retries=2)
    def flaky():
        if not os.path.exists(marker):
            open(marker, "w").write("x")
            os._exit(1)
        return "recovered"

    assert ray_tpu.get(flaky.remote(), timeout=40) == "recovered"


def test_no_retry_fails(cluster):
    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=40)


# ---------------------------------------------------------------- cluster info


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0
    assert "memory" in total


def test_nodes(cluster):
    nodes = ray_tpu.nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]


def test_runtime_context_in_task(cluster):
    @ray_tpu.remote
    def ctx():
        c = ray_tpu.get_runtime_context()
        return c.get_task_id(), c.get_node_id()

    task_id, node_id = ray_tpu.get(ctx.remote())
    assert task_id.startswith("task-") and node_id.startswith("node-")


# ----------------------------------------------------- out-of-order actors


def test_out_of_order_actor_execution(cluster):
    """Opt-in out-of-order execution (reference:
    out_of_order_actor_submit_queue.h): a call parked on an unresolved
    arg does not block later dep-ready calls; the default stays strict
    submission order."""

    @ray_tpu.remote
    def slow_dep():
        time.sleep(2.0)
        return "late"

    @ray_tpu.remote(allow_out_of_order_execution=True)
    class OOO:
        def eat(self, x):
            return x

        def quick(self):
            return "quick"

    a = OOO.remote()
    blocked = a.eat.remote(slow_dep.remote())  # parked on the slow dep
    t0 = time.time()
    assert ray_tpu.get(a.quick.remote(), timeout=30) == "quick"
    assert time.time() - t0 < 1.5  # overtook the parked call
    assert ray_tpu.get(blocked, timeout=30) == "late"

    # Control: the DEFAULT actor preserves submission order.
    @ray_tpu.remote
    class Ordered:
        def eat(self, x):
            return x

        def quick(self):
            return "quick"

    b = Ordered.remote()
    b.eat.remote(slow_dep.remote())
    t0 = time.time()
    assert ray_tpu.get(b.quick.remote(), timeout=30) == "quick"
    assert time.time() - t0 > 1.0  # waited behind the parked call


def test_duplicate_pending_dep_runs_once(cluster):
    """f.remote(x, x) with x still pending must execute exactly once when
    x seals (the dep index is per distinct object; a per-occurrence
    registration would wake and dispatch the task twice)."""
    import time

    @ray_tpu.remote
    def slow():
        time.sleep(1.0)
        return 3

    @ray_tpu.remote
    def add(a, b):
        import os
        return (a + b, os.getpid(), time.time())

    x = slow.remote()
    r = add.remote(x, x)  # same pending ref twice
    total, _, _ = ray_tpu.get(r, timeout=60)
    assert total == 6
    # A double execution would seal the return id twice; hard to observe
    # directly, but a second dispatch would also double-count the task.
    # Exercise the path a few more times with fan-in shapes.
    y = slow.remote()
    rs = [add.remote(y, y) for _ in range(4)]
    assert [v for v, _, _ in ray_tpu.get(rs, timeout=60)] == [6, 6, 6, 6]


def test_list_named_actors(cluster):
    from ray_tpu.util import list_named_actors

    @ray_tpu.remote
    class N:
        def ping(self):
            return 1

    h = N.options(name="alpha").remote()
    ray_tpu.get(h.ping.remote(), timeout=30)
    names = list_named_actors()
    assert "alpha" in names
    both = list_named_actors(all_namespaces=True)
    assert {"namespace": "", "name": "alpha"} in both or any(
        e["name"] == "alpha" for e in both)
    ray_tpu.kill(h)


def test_runtime_context_surface(cluster):
    ctx = ray_tpu.get_runtime_context()
    assert ctx.get_worker_id() == "driver"
    assert ctx.get_job_id() == "driver"
    assert ":" in ctx.gcs_address

    @ray_tpu.remote
    def probe():
        c = ray_tpu.get_runtime_context()
        return {
            "worker": c.get_worker_id(),
            "task": c.get_task_id(),
            "env": c.get_runtime_env(),
        }

    out = ray_tpu.get(probe.options(
        runtime_env={"env_vars": {"X": "1"}}).remote(), timeout=30)
    assert out["worker"].startswith("worker-")
    assert out["task"].startswith("task-")
    assert out["env"].get("env_vars") == {"X": "1"}


def test_accelerator_manager_vendors(monkeypatch):
    """Vendor managers mirror the reference env-var contracts
    (amd_gpu.py / intel_gpu.py / hpu.py / npu.py)."""
    from ray_tpu.accelerators import (
        AMDGPUAcceleratorManager,
        HPUAcceleratorManager,
        IntelGPUAcceleratorManager,
        NPUAcceleratorManager,
    )

    monkeypatch.setenv("HIP_VISIBLE_DEVICES", "0,1")
    assert AMDGPUAcceleratorManager.get_current_node_num_accelerators() == 2
    assert AMDGPUAcceleratorManager.get_current_process_visible_accelerator_ids() == ["0", "1"]
    AMDGPUAcceleratorManager.set_current_process_visible_accelerator_ids(["3"])
    assert os.environ["HIP_VISIBLE_DEVICES"] == "3"

    monkeypatch.setenv("HABANA_VISIBLE_MODULES", "0,1,2")
    assert HPUAcceleratorManager.get_current_node_num_accelerators() == 3
    assert HPUAcceleratorManager.get_resource_name() == "HPU"

    monkeypatch.setenv("ASCEND_RT_VISIBLE_DEVICES", "")
    assert NPUAcceleratorManager.get_current_node_num_accelerators() == 0

    monkeypatch.setenv("ONEAPI_DEVICE_SELECTOR", "level_zero:0,1")
    assert IntelGPUAcceleratorManager.get_current_node_num_accelerators() == 2


def test_max_calls_recycles_worker(cluster):
    """@remote(max_calls=N): the executing worker exits after N
    completed calls of that function and a fresh process replaces it
    (reference: remote_function.py max_calls — the lever against
    native-memory leaks). All results still arrive."""
    import os as _os

    @ray_tpu.remote(max_calls=2)
    def pid():
        return _os.getpid()

    pids = [ray_tpu.get(pid.remote(), timeout=60) for _ in range(6)]
    # 6 calls at max_calls=2 => at least 3 distinct processes.
    assert len(set(pids)) >= 3, pids
    # No two consecutive pairs share beyond the budget.
    from collections import Counter

    assert max(Counter(pids).values()) <= 2, pids
