"""C++ API frontend (reference: cpp/ — ray::Init/Task/Get example app).
Builds the embedded-runtime C++ library + example with g++ and runs it."""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_cpp_frontend_builds_and_runs():
    build = subprocess.run(
        ["make", "-C", os.path.join(REPO, "cpp")],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr[-2000:]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    run = subprocess.run(
        [os.path.join(REPO, "cpp", "build", "example")],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert run.returncode == 0, (run.stdout[-1000:], run.stderr[-2000:])
    assert "CPP-OK" in run.stdout
    assert "task: 42" in run.stdout
