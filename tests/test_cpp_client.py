"""Native C++ control-plane client (src/client/ray_client.cc): register,
put/get inline objects, cross-language task by import path, against a
live in-process head. Counterpart of the reference's C++ frontend tests
(reference: cpp/ runtime tests)."""

import os
import subprocess
import sys

import pytest

import ray_tpu

DEMO = os.path.join(os.path.dirname(__file__), "..", "ray_tpu", "_native",
                    "rtpu_client_demo")


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _head_address():
    from ray_tpu._private.worker_context import global_runtime

    return global_runtime().address


def _demo_built() -> bool:
    from ray_tpu._private.native_build import ensure_native

    ensure_native()
    return os.path.exists(DEMO)


@pytest.mark.skipif(not _demo_built(),
                    reason="native client failed to build (make -C src)")
def test_native_client_roundtrip(cluster):
    host, port = _head_address()
    env = dict(os.environ)
    # the worker must import tests.cross_lang_helpers
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    r = subprocess.run([DEMO, host, str(port)], capture_output=True,
                       text=True, timeout=120, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "NATIVE_CLIENT_OK" in r.stdout, (r.stdout, r.stderr)


def test_cross_language_function_id(cluster):
    """The path: convention works from Python submitters too."""
    from ray_tpu._private import worker_context as rt
    from ray_tpu._private.task_spec import TaskSpec

    g = rt.global_runtime()
    packed, deps, borrowed = g.pack_args((5, 6), {"scale": 2})
    ret = "t" * 0 + os.urandom(16).hex()
    spec = TaskSpec(
        task_id=os.urandom(16).hex(), name="xlang",
        func_id="path:tests.cross_lang_helpers:add_scaled",
        args=packed, deps=deps, return_ids=[ret],
        resources={"CPU": 1.0}, owner_id=g.client_id,
        borrowed_ids=borrowed,
    )
    g.submit_task(spec)
    from ray_tpu._private.ids import ObjectRef

    assert ray_tpu.get(ObjectRef(ret)) == 22


def test_malformed_path_func_id_errors(cluster):
    from ray_tpu._private import worker_context as rt
    from ray_tpu._private.task_spec import TaskSpec
    from ray_tpu._private.ids import ObjectRef

    g = rt.global_runtime()
    packed, deps, borrowed = g.pack_args((), {})
    ret = os.urandom(16).hex()
    spec = TaskSpec(
        task_id=os.urandom(16).hex(), name="bad",
        func_id="path:nonexistent_module_xyz:fn",
        args=packed, deps=deps, return_ids=[ret],
        resources={"CPU": 1.0}, owner_id=g.client_id,
        borrowed_ids=borrowed,
    )
    g.submit_task(spec)
    with pytest.raises(Exception):
        ray_tpu.get(ObjectRef(ret), timeout=60)
