"""DAG graphs, job submission, dashboard, autoscaler decisions.

Reference test models: dag tests (python/ray/dag/tests), job manager tests
(dashboard/modules/job/tests), autoscaler resource-demand tests
(tests/test_resource_demand_scheduler.py)."""

from __future__ import annotations

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeNodeProvider,
    NodeType,
    ResourceDemandScheduler,
    StandardAutoscaler,
)
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# DAG


def test_function_dag_execute():
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    with InputNode() as inp:
        s = add.bind(inp, 10)
        out = mul.bind(s, 2)
    ref = out.execute(5)
    assert ray_tpu.get(ref) == 30


def test_actor_dag_compiled_repeated_execution():
    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k
            self.calls = 0

        def apply(self, x):
            self.calls += 1
            return x + self.k

        def count(self):
            return self.calls

    a = Stage.remote(1)
    b = Stage.remote(100)
    with InputNode() as inp:
        dag = b.apply.bind(a.apply.bind(inp))
    compiled = dag.experimental_compile()
    for i in range(5):
        assert ray_tpu.get(compiled.execute(i)) == i + 101
    # Both stages really ran per execute (pinned actors, stateful).
    assert ray_tpu.get(a.count.remote()) == 5
    compiled.teardown()
    with pytest.raises(RuntimeError):
        compiled.execute(0)


def test_dag_diamond_runs_shared_node_once():
    @ray_tpu.remote
    class Tracker:
        def __init__(self):
            self.n = 0

        def produce(self, x):
            self.n += 1
            return x

        def count(self):
            return self.n

    t = Tracker.remote()

    @ray_tpu.remote
    def combine(a, b):
        return (a, b)

    with InputNode() as inp:
        shared = t.produce.bind(inp)
        out = combine.bind(shared, shared)
    assert ray_tpu.get(out.execute(7)) == (7, 7)
    assert ray_tpu.get(t.count.remote()) == 1  # memoized: one call per execute


def test_multi_output_node():
    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def dec(x):
        return x - 1

    with InputNode() as inp:
        dag = MultiOutputNode([inc.bind(inp), dec.bind(inp)])
    refs = dag.execute(10)
    assert ray_tpu.get(refs) == [11, 9]


# ---------------------------------------------------------------------------
# jobs


def test_job_submission_lifecycle():
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint="python -c \"print('job ran ok')\"",
        runtime_env={"env_vars": {"MY_FLAG": "42"}},
    )
    status = client.wait_until_finished(job_id, timeout_s=60)
    assert status == "SUCCEEDED"
    assert "job ran ok" in client.get_job_logs(job_id)
    assert any(j["job_id"] == job_id for j in client.list_jobs())


def test_job_driver_joins_cluster():
    """The job's driver connects to THIS cluster (RAY_TPU_ADDRESS), so it
    sees named actors created before submission."""
    from ray_tpu.job_submission import JobSubmissionClient

    @ray_tpu.remote(name="job_target", num_cpus=0)
    class Target:
        def hello(self):
            return "from-cluster"

    t = Target.remote()
    ray_tpu.get(t.hello.remote())

    script = (
        "import ray_tpu; ray_tpu.init();"
        "a = ray_tpu.get_actor('job_target');"
        "print('GOT:', ray_tpu.get(a.hello.remote()))"
    )
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f'python -c "{script}"')
    status = client.wait_until_finished(job_id, timeout_s=90)
    logs = client.get_job_logs(job_id)
    assert status == "SUCCEEDED", logs
    assert "GOT: from-cluster" in logs


def test_job_failure_reported():
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="python -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(job_id, timeout_s=60) == "FAILED"
    assert "exited with code 3" in client.get_job_info(job_id)["message"]


def test_job_stop_from_another_client():
    from ray_tpu.job_submission import JobSubmissionClient

    a = JobSubmissionClient()
    job_id = a.submit_job(entrypoint="python -c 'import time; time.sleep(60)'")
    deadline = time.monotonic() + 30
    while a.get_job_status(job_id) == "PENDING" and time.monotonic() < deadline:
        time.sleep(0.2)
    # A DIFFERENT client can stop it: supervisors live in the shared
    # JobManager actor, not in the submitting client.
    b = JobSubmissionClient()
    assert b.stop_job(job_id) is True
    assert b.wait_until_finished(job_id, timeout_s=30) == "STOPPED"


def test_job_supervisor_death_marks_failed():
    from ray_tpu.job_submission import JobSubmissionClient
    from ray_tpu.util import state as us

    client = JobSubmissionClient()
    before = {a["actor_id"] for a in us.list_actors()}
    job_id = client.submit_job(entrypoint="python -c 'import time; time.sleep(120)'")
    deadline = time.monotonic() + 30
    while client.get_job_status(job_id) == "PENDING" and time.monotonic() < deadline:
        time.sleep(0.2)
    # Kill the supervisor actor (the only new actor since submission).
    new = [a for a in us.list_actors()
           if a["actor_id"] not in before and a["state"] == "ALIVE"]
    assert len(new) == 1
    import os as _os
    import signal as _signal

    _os.kill(new[0]["pid"], _signal.SIGKILL)
    # The JobManager monitor notices the dead run() future.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if client.get_job_status(job_id) == "FAILED":
            break
        time.sleep(0.3)
    info = client.get_job_info(job_id)
    assert info["status"] == "FAILED", info
    assert "supervisor died" in info["message"]


# ---------------------------------------------------------------------------
# dashboard


def test_dashboard_endpoints():
    import requests

    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def traced_job():
        return 1

    ray_tpu.get(traced_job.remote())
    port = start_dashboard()
    base = f"http://127.0.0.1:{port}"
    try:
        cluster = requests.get(base + "/api/cluster", timeout=10).json()
        assert cluster["resources_total"]["CPU"] == 8.0
        tasks = requests.get(base + "/api/tasks", timeout=10).json()["tasks"]
        assert any(t["name"] == "traced_job" for t in tasks)
        assert requests.get(base + "/", timeout=10).status_code == 200
        assert requests.get(base + "/metrics", timeout=10).status_code == 200
        assert requests.get(base + "/api/nope", timeout=10).status_code == 404
    finally:
        stop_dashboard()


# ---------------------------------------------------------------------------
# autoscaler


def test_demand_scheduler_bin_packing():
    sched = ResourceDemandScheduler(
        [
            NodeType("small", {"CPU": 4}),
            NodeType("big", {"CPU": 16, "TPU": 4}),
        ]
    )
    # 6 x 1-CPU demands: 4 fit one small node, 2 spill to a second.
    plan = sched.get_nodes_to_launch([{"CPU": 1}] * 6, [], {})
    assert plan == {"small": 2}
    # TPU demand needs the big type even though small is cheaper.
    plan = sched.get_nodes_to_launch([{"TPU": 2}], [], {})
    assert plan == {"big": 1}
    # Existing capacity absorbs demand: nothing to launch.
    plan = sched.get_nodes_to_launch([{"CPU": 2}], [{"CPU": 8}], {})
    assert plan == {}
    # max_workers cap respected.
    capped = ResourceDemandScheduler([NodeType("small", {"CPU": 1}, max_workers=1)])
    plan = capped.get_nodes_to_launch([{"CPU": 1}] * 3, [], {})
    assert plan == {"small": 1}


def test_standard_autoscaler_loop_scales_up_and_down():
    provider = FakeNodeProvider()
    demands = [[{"CPU": 4}] * 3]  # mutable cell

    cfg = AutoscalerConfig(
        node_types=[NodeType("worker", {"CPU": 4}, min_workers=1, max_workers=5)],
        idle_timeout_s=0.0,
    )
    scaler = StandardAutoscaler(provider, cfg, demand_source=lambda: demands[0])
    r = scaler.update()
    # min_workers floor (1 node, absorbs one 4-CPU demand within its
    # launch grace) + 2 more for the remaining demands.
    assert sum(r["launched"].values()) == 3
    assert len(provider.non_terminated_nodes()) == 3
    # Persistent demand must NOT relaunch: fresh nodes count as capacity.
    r = scaler.update()
    assert sum(r["launched"].values()) == 0, r
    assert len(provider.non_terminated_nodes()) == 3
    # Demand drains → idle nodes terminate down to min_workers.
    demands[0] = []
    r = scaler.update()
    assert len(provider.non_terminated_nodes()) == 1
    assert len(r["terminated"]) == 2


def test_local_provider_autoscales_real_capacity():
    """LocalNodeProvider (reference: autoscaler local/fake-multi-node
    providers): the v1 autoscaler's launch decision spawns a REAL agent
    subprocess, the node registers with the head, queued work schedules
    onto the new capacity, and terminate_node kills the agent."""
    import os
    import time

    import ray_tpu
    from ray_tpu.autoscaler.local import LocalNodeProvider

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    provider = None
    try:
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        provider = LocalNodeProvider(
            node_types={"cpu-node": {"num_cpus": 2,
                                     "resources": {"annex": 1}}},
            env=env)

        @ray_tpu.remote(resources={"annex": 0.1})
        def on_annex():
            return "scaled"

        # The demand: tasks needing a resource only the new node type
        # has — unplaceable until the autoscaler launches one.
        refs = [on_annex.remote() for _ in range(3)]

        cfg = AutoscalerConfig(
            node_types=[NodeType("cpu-node", {"CPU": 2, "annex": 1},
                                 max_workers=2)],
            idle_timeout_s=3600.0,
        )
        scaler = StandardAutoscaler(provider, cfg)
        deadline = time.time() + 60
        launched = 0
        while time.time() < deadline:
            launched += sum(scaler.update()["launched"].values())
            if launched:
                break
            time.sleep(0.5)
        assert launched >= 1, "autoscaler never launched for the demand"
        assert ray_tpu.get(refs, timeout=300) == ["scaled"] * 3  # generous: autoscale + agent spawn under a loaded 1-CPU box

        nodes = provider.non_terminated_nodes()
        assert nodes and all(provider.is_running(n) for n in nodes)
        assert provider.node_type_of(nodes[0]) == "cpu-node"
        for n in nodes:
            provider.terminate_node(n)
        assert provider.non_terminated_nodes() == []
    finally:
        if provider is not None:
            provider.shutdown()
        ray_tpu.shutdown()
