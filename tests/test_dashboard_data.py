"""Dashboard serve/logs endpoints + HF/torch data converters (reference:
dashboard/modules/{serve,log}, ray.data.from_huggingface/from_torch)."""

import json
import urllib.request

import pytest

import ray_tpu
import ray_tpu.data
from ray_tpu import serve


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        body = r.read().decode()
    try:
        return json.loads(body)
    except json.JSONDecodeError:
        return body


def test_dashboard_serve_and_log_endpoints():
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    # Generate some worker logs first.
    @ray_tpu.remote
    def noisy():
        print("NOISY-LINE")
        return 1

    assert ray_tpu.get(noisy.remote()) == 1

    from tests.serve_config_helpers import Doubler

    serve.run(Doubler.bind(), proxy=False)

    port = start_dashboard()
    try:
        st = _get(port, "/api/serve")
        assert "Doubler" in st["deployments"]
        logs = _get(port, "/api/logs")["logs"]
        assert logs and all("name" in l and "bytes" in l for l in logs)
        # Tail a worker log and find the printed line.
        found = False
        for entry in logs:
            tail = _get(port, f"/api/logs/{entry['name']}")
            if any("NOISY-LINE" in ln for ln in tail["lines"]):
                found = True
        assert found
        # Path traversal is rejected.
        evil = _get(port, "/api/logs/..%2Fsecrets")
        assert evil["lines"] == []
    finally:
        serve.delete("Doubler")
        stop_dashboard()


def test_from_huggingface_roundtrip():
    datasets = pytest.importorskip("datasets")
    hf = datasets.Dataset.from_dict(
        {"text": ["a", "bb", "ccc"], "n": [1, 2, 3]}
    )
    ds = ray_tpu.data.from_huggingface(hf)
    assert ds.count() == 3
    assert ds.sum("n") == 6
    assert ds.take(1)[0]["text"] == "a"


def test_from_torch_roundtrip():
    torch = pytest.importorskip("torch")

    class DS(torch.utils.data.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return {"x": float(i), "y": float(i * i)}

    ds = ray_tpu.data.from_torch(DS())
    assert ds.count() == 4
    assert ds.sum("y") == 0 + 1 + 4 + 9


def test_dashboard_web_ui_served():
    """'/' serves the SPA (reference: dashboard/client web UI)."""
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    port = start_dashboard()
    try:
        html = _get(port, "/")
        assert "<html" in html.lower()
        assert "ray_tpu dashboard" in html
        # The SPA drives the same JSON APIs.
        for endpoint in ("/api/cluster", "/api/tasks", "/api/actors"):
            assert endpoint in html
    finally:
        stop_dashboard()


def test_dashboard_live_profile_endpoint():
    """/api/profile/{worker_id}: faulthandler stack capture of a live
    worker (reference: reporter/profile_manager.py py-spy flow)."""
    import time

    from ray_tpu._private.worker_context import get_head
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    class Sleeper:
        def park(self):
            time.sleep(20)

        def ping(self):
            return 1

    s = Sleeper.remote()
    ray_tpu.get(s.ping.remote(), timeout=30)
    s.park.remote()  # in-flight: the dump shows it on the stack
    time.sleep(0.5)
    head = get_head()
    worker_id = next(w.worker_id for w in head.workers.values()
                     if w.actor_id == s._actor_id and w.pid is not None)
    port = start_dashboard()
    try:
        out = _get(port, f"/api/profile/{worker_id}")
        assert out.get("stacks"), out
        text = "\n".join(out["stacks"])
        assert "Thread" in text and "park" in text, text[:500]
        unknown = _get(port, "/api/profile/worker-nope")
        assert unknown["error"] == "unknown worker"
    finally:
        stop_dashboard()
        ray_tpu.kill(s)


def test_dashboard_sampling_profiler():
    """/api/profile/{id}?duration=N: folded collapsed stacks showing
    where a BUSY worker spends time — not just one snapshot (reference:
    profile_manager.py:191 py-spy record)."""
    import time

    from ray_tpu._private.worker_context import get_head
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    class Burner:
        def spin_hotly(self, seconds):
            t0 = time.time()
            x = 0
            while time.time() - t0 < seconds:
                x += 1
            return x

        def ping(self):
            return 1

    b = Burner.remote()
    ray_tpu.get(b.ping.remote(), timeout=30)
    fut = b.spin_hotly.remote(6.0)
    time.sleep(0.3)
    head = get_head()
    worker_id = next(w.worker_id for w in head.workers.values()
                     if w.actor_id == b._actor_id and w.pid is not None)
    port = start_dashboard()
    try:
        out = _get(port, f"/api/profile/{worker_id}?duration=1.5")
        assert out.get("samples", 0) > 10, out
        folded = out.get("folded") or {}
        assert folded, out
        # The hot method dominates the folded stacks.
        hot = sum(n for stack, n in folded.items() if "spin_hotly" in stack)
        total = sum(folded.values())
        assert hot > 0.2 * total, (hot, total, list(folded)[:5])
        # Folded format: outer;...;inner frames joined by ';'.
        assert any(";" in stack for stack in folded)
    finally:
        stop_dashboard()
        ray_tpu.get(fut, timeout=30)
        ray_tpu.kill(b)


def test_dashboard_memory_profiler():
    """?duration=N&mode=memory: tracemalloc allocation tracing for the
    window (reference: profile_manager.py memray attach)."""
    import time

    from ray_tpu._private.worker_context import get_head
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    class Alloc:
        def churn(self, seconds):
            t0 = time.time()
            keep = []
            while time.time() - t0 < seconds:
                keep.append(bytes(64 * 1024))
                if len(keep) > 64:
                    keep.pop(0)
            return len(keep)

        def ping(self):
            return 1

    a = Alloc.remote()
    ray_tpu.get(a.ping.remote(), timeout=30)
    # Long churn + retried short windows: on a loaded 1-CPU CI box the
    # churn loop can be starved for a whole 1.5 s window, which is a
    # scheduling artifact, not a profiler bug.
    fut = a.churn.remote(20.0)
    time.sleep(0.3)
    head = get_head()
    worker_id = next(w.worker_id for w in head.workers.values()
                     if w.actor_id == a._actor_id and w.pid is not None)
    port = start_dashboard()
    try:
        out = {}
        for _ in range(4):
            out = _get(port,
                       f"/api/profile/{worker_id}?duration=1.5&mode=memory")
            allocs = out.get("allocations") or {}
            if allocs and sum(v["bytes"] for v in allocs.values()) > 64 * 1024:
                break
        else:
            raise AssertionError(f"no allocations captured in 4 windows: {out}")
    finally:
        stop_dashboard()
        ray_tpu.get(fut, timeout=60)
        ray_tpu.kill(a)


def test_dashboard_serve_apps_train_and_node_detail():
    """New depth pages (VERDICT r3 #10; reference:
    dashboard/modules/serve + /train + node detail): /api/serve/apps
    groups deployments with routes, /api/train lists registry runs fed
    by RunStateActor, /api/nodes/<id> returns the per-node breakdown,
    and the SPA carries the Train nav + node drill-down."""
    from ray_tpu.dashboard import start_dashboard, stop_dashboard
    from tests.serve_config_helpers import Doubler

    serve.run(Doubler.bind(), route_prefix="/dbl", proxy=False)

    # A real (tiny) train run populates the registry.
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        from ray_tpu import train

        for i in range(2):
            train.report({"loss": 1.0 / (i + 1)})

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.error is None

    port = start_dashboard()
    try:
        apps = _get(port, "/api/serve/apps")["apps"]
        app = next(iter(apps.values()))
        assert "Doubler" in app["deployments"]
        assert any(r["prefix"] == "/dbl" for r in app["routes"])

        runs = _get(port, "/api/train")["runs"]
        assert runs, "train registry empty"
        run = runs[0]
        assert run["status"] == "FINISHED"
        assert run["iterations"] == 2
        assert run["last_metrics"]["loss"] == pytest.approx(0.5)

        nodes = _get(port, "/api/cluster")["nodes"]
        detail = _get(port, f"/api/nodes/{nodes[0]['node_id']}")
        assert detail["node"]["node_id"] == nodes[0]["node_id"]
        assert isinstance(detail["workers"], list)
        assert isinstance(detail["tasks"], list)

        ui = _get(port, "/")
        assert 'data-view="train"' in ui and "/api/serve/apps" in ui
        assert "/api/nodes/" in ui
    finally:
        serve.delete("Doubler")
        stop_dashboard()


def test_dashboard_task_and_actor_drilldown():
    """Per-task and per-actor detail pages (VERDICT r4 #8; reference:
    dashboard/modules/actor + task drill-down over state + events +
    logs): /api/tasks/<id> returns record + profile events + the owning
    worker's log tail, /api/actors/<id> returns record + its tasks +
    log tail, and the SPA wires clickable drill-down rows."""
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def traced():
        print("DRILL-LINE")
        return 7

    assert ray_tpu.get(traced.remote()) == 7

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1

    port = start_dashboard()
    try:
        # Task detail: find the traced() task, drill in.
        tasks = _get(port, "/api/tasks")["tasks"]
        row = next(t for t in tasks if t["name"] == "traced")
        detail = _get(port, f"/api/tasks/{row['task_id']}")
        assert detail["task"]["task_id"] == row["task_id"]
        assert detail["task"]["state"] == "FINISHED"
        assert any(e["task_id"] == row["task_id"] for e in detail["events"])
        assert any("DRILL-LINE" in ln
                   for ln in detail["worker_log"].get("lines", []))

        # Actor detail: record + its tasks + worker binding.
        actors = _get(port, "/api/actors")["actors"]
        arow = next(a for a in actors if a["state"] == "ALIVE")
        adetail = _get(port, f"/api/actors/{arow['actor_id']}")
        assert adetail["actor"]["actor_id"] == arow["actor_id"]
        assert adetail["actor"]["worker_id"]
        assert isinstance(adetail["tasks"], list) and adetail["tasks"]
        assert "worker_log" in adetail

        # Unknown ids are 404, not 500.
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/api/tasks/nonexistent")
        assert ei.value.code == 404

        # SPA carries the drill-down wiring + embedded metrics charts.
        ui = _get(port, "/")
        assert "/api/tasks/" in ui and "/api/actors/" in ui
        assert "taskId" in ui and "actorId" in ui
        assert 'data-view="metrics"' in ui and "spark(" in ui
    finally:
        stop_dashboard()


def test_metrics_runtime_exposition_and_grafana():
    """Core runtime metrics in the Prometheus exposition + generated
    Grafana dashboard / service discovery (reference:
    dashboard/modules/metrics — scrape config + dashboard JSON)."""
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def unit():
        return 1

    assert ray_tpu.get([unit.remote() for _ in range(5)],
                       timeout=60) == [1] * 5

    port = start_dashboard()
    try:
        text = _get(port, "/metrics")
        assert "# TYPE ray_tpu_tasks_finished_total counter" in text
        finished = next(float(ln.split()[1]) for ln in text.splitlines()
                        if ln.startswith("ray_tpu_tasks_finished_total "))
        assert finished >= 5
        assert "ray_tpu_workers_alive" in text
        assert "ray_tpu_object_store_used_bytes" in text

        dash = _get(port, "/api/grafana_dashboard")
        assert dash["uid"] == "ray-tpu-cluster"
        exprs = [t["expr"] for p in dash["panels"]
                 for t in p.get("targets", [])]
        # Every default panel queries a metric the exposition emits.
        for expr in exprs[:6]:
            name = expr.split("(")[-1].split("[")[0].rstrip(")")
            assert name in text, (name, expr)

        sd = _get(port, "/api/prometheus_sd?host=1.2.3.4&port=9999")
        assert sd[0]["targets"] == ["1.2.3.4:9999"]
        assert sd[0]["labels"]["__metrics_path__"] == "/metrics"
    finally:
        stop_dashboard()
