"""ray_tpu.data: Dataset plan building, execution, IO, Train integration.

Modeled on the reference's data test strategy (SURVEY.md §4 — Data 102
test files: per-op transforms, datasource roundtrips, iterator formats)."""

from __future__ import annotations

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.executor import _rebatch


@pytest.fixture(scope="module", autouse=True)
def _shutdown_leaked_runtime():
    """Many tests here run data ops that auto-init the runtime without
    an explicit init/shutdown pair; tear it down at module end so the
    next module's fresh `ray_tpu.init()` doesn't see a live session."""
    yield
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# blocks


def test_block_accessor_dict_roundtrip():
    b = {"a": np.arange(5), "b": np.arange(5) * 2.0}
    acc = BlockAccessor(b)
    assert acc.num_rows() == 5
    assert acc.column_names() == ["a", "b"]
    assert BlockAccessor(acc.slice(1, 3)).num_rows() == 2
    rows = list(acc.iter_rows())
    assert rows[2] == {"a": 2, "b": 4.0}


def test_block_concat_schema_mismatch_raises():
    with pytest.raises(ValueError, match="differing schemas"):
        BlockAccessor.concat([{"a": np.arange(2)}, {"b": np.arange(2)}])


def test_rebatch_exact_sizes_linear():
    blocks = [{"x": np.arange(i * 10, i * 10 + 10)} for i in range(5)]
    out = list(_rebatch(iter(blocks), 16))
    sizes = [BlockAccessor(b).num_rows() for b in out]
    assert sizes == [16, 16, 16, 2]
    all_vals = np.concatenate([b["x"] for b in out])
    np.testing.assert_array_equal(all_vals, np.arange(50))


# ---------------------------------------------------------------------------
# core transforms (local thread mode)


def test_range_map_filter_count():
    ds = rd.range(100).map_batches(lambda b: {"id": b["id"] * 2})
    ds = ds.filter(lambda r: r["id"] % 4 == 0)
    assert ds.count() == 50
    assert ds.take(3) == [{"id": 0}, {"id": 4}, {"id": 8}]


def test_map_rows_and_flat_map():
    ds = rd.from_items([1, 2, 3]).map(lambda x: x + 10)
    assert ds.take_all() == [11, 12, 13]
    ds2 = rd.from_items([1, 2]).flat_map(lambda x: [x, x * 100])
    assert ds2.take_all() == [1, 100, 2, 200]


def test_column_ops():
    ds = rd.from_numpy({"a": np.arange(4), "b": np.ones(4)})
    ds = ds.add_column("c", lambda cols: cols["a"] + cols["b"])
    ds = ds.rename_columns({"b": "ones"}).drop_columns(["a"])
    rows = ds.take_all()
    assert rows[0] == {"ones": 1.0, "c": 1.0}
    sel = rd.from_numpy({"a": np.arange(4), "b": np.ones(4)}).select_columns(["a"])
    assert sel.columns() == ["a"]


def test_sort_shuffle_limit_repartition():
    ds = rd.from_numpy({"v": np.array([3, 1, 2, 5, 4])})
    assert [r["v"] for r in ds.sort("v").take_all()] == [1, 2, 3, 4, 5]
    assert [r["v"] for r in ds.sort("v", descending=True).take(2)] == [5, 4]
    shuffled = ds.random_shuffle(seed=0)
    assert sorted(r["v"] for r in shuffled.take_all()) == [1, 2, 3, 4, 5]
    assert ds.limit(2).count() == 2
    blocks = list(ds.repartition(3).iter_blocks())
    assert len(blocks) == 3
    assert sum(BlockAccessor(b).num_rows() for b in blocks) == 5


def test_union_and_zip():
    a = rd.from_numpy({"x": np.arange(3)})
    b = rd.from_numpy({"x": np.arange(3, 6)})
    assert [r["x"] for r in a.union(b).take_all()] == [0, 1, 2, 3, 4, 5]
    z = a.zip(rd.from_numpy({"y": np.arange(10, 13)}))
    assert z.take_all() == [
        {"x": 0, "y": 10}, {"x": 1, "y": 11}, {"x": 2, "y": 12}
    ]


def test_stats_and_unique():
    ds = rd.from_numpy({"v": np.array([1.0, 2.0, 2.0, 3.0])})
    assert ds.sum("v") == 8.0
    assert ds.min("v") == 1.0
    assert ds.max("v") == 3.0
    assert ds.mean("v") == 2.0
    assert ds.unique("v") == [1.0, 2.0, 3.0]


def test_groupby():
    ds = rd.from_items(
        [{"k": "a", "v": 1}, {"k": "b", "v": 10}, {"k": "a", "v": 3}]
    )
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {"a": 2, "b": 1}
    sums = {r["k"]: r["v"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {"a": 4, "b": 10}
    maxes = {r["k"]: r["v"] for r in ds.groupby("k").max("v").take_all()}
    assert maxes == {"a": 3, "b": 10}


def test_groupby_std_and_aggregate():
    import numpy as np

    ds = rd.from_items(
        [{"k": "a", "v": 1}, {"k": "a", "v": 3}, {"k": "a", "v": 5},
         {"k": "b", "v": 10}]
    )
    stds = {r["k"]: r["v"] for r in ds.groupby("k").std("v").take_all()}
    assert abs(stds["a"] - 2.0) < 1e-9  # std([1,3,5], ddof=1) = 2
    assert stds["b"] == 0.0  # single element: defined as 0
    rows = ds.groupby("k").aggregate(
        total=("v", np.sum), spread=("v", lambda v: v.max() - v.min()),
    ).take_all()
    agg = {r["k"]: (r["total"], r["spread"]) for r in rows}
    assert agg == {"a": (9, 4), "b": (10, 0)}
    import pytest as _pytest

    with _pytest.raises(KeyError, match="nope"):
        ds.groupby("k").aggregate(x=("nope", np.sum)).take_all()
    with _pytest.raises(ValueError, match="group key"):
        ds.groupby("k").aggregate(k=("v", np.sum))


def test_class_udf_map_batches():
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(10).map_batches(AddConst, fn_constructor_args=(100,))
    assert ds.take(2) == [{"id": 100}, {"id": 101}]


# ---------------------------------------------------------------------------
# iterators


def test_tfrecords_roundtrip(tmp_path):
    """write_tfrecords/read_tfrecords with a pure-Python
    tf.train.Example codec (reference read_api.py read_tfrecords uses
    TensorFlow; the wire format here is identical and TF-free)."""
    ds = rd.from_items([
        {"name": "a", "score": 1.5, "count": 3},
        {"name": "b", "score": -2.25, "count": 40},
        {"name": "c", "score": 0.5, "count": -7},
    ])
    out_dir = str(tmp_path / "tfr")
    files = ds.write_tfrecords(out_dir)
    assert files and all(f.endswith(".tfrecords") for f in files)
    back = sorted(rd.read_tfrecords(out_dir).iter_rows(),
                  key=lambda r: r["name"])
    assert [r["name"] for r in back] == [b"a", b"b", b"c"]  # bytes_list
    assert [r["count"] for r in back] == [3, 40, -7]  # signed int64
    assert np.allclose([r["score"] for r in back], [1.5, -2.25, 0.5])


def test_tfrecord_crc_and_framing(tmp_path):
    """The emitted framing carries valid masked CRC32Cs (a TF reader
    would verify them; known-answer check for crc32c('123456789'))."""
    from ray_tpu.data.datasource import _crc32c, _masked_crc

    assert _crc32c(b"123456789") == 0xE3069283  # CRC-32C check value
    head = np.uint64(5).tobytes()
    assert _masked_crc(head) != _crc32c(head)  # masking applied


def test_tfrecords_sparse_features_and_unpacked_ints(tmp_path):
    """Valid wire forms beyond what our writer emits: records with
    HETEROGENEOUS feature keys normalize to the union (missing ->
    None), and UNPACKED int64 varints decode signed."""
    from ray_tpu.data.datasource import (
        _ld,
        _masked_crc,
        _varint,
        decode_example,
    )

    # Unpacked negative int64: Int64List.value as a direct varint field.
    neg = (1 << 64) - 7  # -7 two's complement
    feature = _ld(3, _varint(1 << 3 | 0) + _varint(neg))
    entry = _ld(1, b"count") + _ld(2, feature)
    ex = _ld(1, _ld(1, entry))
    assert decode_example(ex) == {"count": [-7]}

    # Sparse keys across records in one file.
    from ray_tpu.data.datasource import encode_example

    out = tmp_path / "sparse.tfrecords"
    with open(out, "wb") as f:
        for row in [{"a": 1, "b": 2}, {"a": 3}]:
            data = encode_example(row)
            head = np.uint64(len(data)).tobytes()
            f.write(head + np.uint32(_masked_crc(head)).tobytes())
            f.write(data + np.uint32(_masked_crc(data)).tobytes())
    rows = list(rd.read_tfrecords(str(out)).iter_rows())
    assert [r["a"] for r in rows] == [1, 3]
    assert rows[0]["b"] == 2 and rows[1]["b"] is None

    # Ragged list features stay LISTS for every record of the column
    # (no scalar-vs-list mixing when some records have length 1).
    out2 = tmp_path / "ragged.tfrecords"
    with open(out2, "wb") as f:
        for row in [{"ids": [7]}, {"ids": [3, 4]}]:
            data = encode_example(row)
            head = np.uint64(len(data)).tobytes()
            f.write(head + np.uint32(_masked_crc(head)).tobytes())
            f.write(data + np.uint32(_masked_crc(data)).tobytes())
    ragged = list(rd.read_tfrecords(str(out2)).iter_rows())
    assert ragged[0]["ids"] == [7] and ragged[1]["ids"] == [3, 4]

    # Corruption is loud, not silent.
    blob = out.read_bytes()
    (out.parent / "bad.tfrecords").write_bytes(blob[:-6])  # truncated
    with pytest.raises(Exception, match="truncated|corrupt"):
        list(rd.read_tfrecords(str(out.parent / "bad.tfrecords"))
             .iter_rows())


def test_read_images_skips_non_image_files(tmp_path):
    pytest.importorskip("PIL")
    from PIL import Image

    Image.new("RGB", (4, 4)).save(tmp_path / "ok.png")
    (tmp_path / "README.txt").write_text("not an image")
    assert rd.read_images(str(tmp_path)).count() == 1


def test_read_sql_sqlite(tmp_path):
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO t VALUES (?, ?)",
                     [(i, f"row{i}") for i in range(10)])
    conn.commit()
    conn.close()

    ds = rd.read_sql("SELECT id, name FROM t WHERE id < 5",
                     lambda: sqlite3.connect(db))
    rows = sorted(ds.iter_rows(), key=lambda r: r["id"])
    assert len(rows) == 5 and rows[4]["name"] == "row4"


def test_read_images(tmp_path):
    PIL = pytest.importorskip("PIL")  # noqa: F841
    from PIL import Image

    for i in range(3):
        Image.new("RGB", (8, 6), color=(i * 10, 0, 0)).save(
            tmp_path / f"img{i}.png")
    ds = rd.read_images(str(tmp_path), size=(4, 4), include_paths=True)
    batches = list(ds.iter_batches(batch_size=None))
    imgs = np.concatenate([b["image"] for b in batches])
    assert imgs.shape == (3, 4, 4, 3)
    assert ds.count() == 3


def test_bounded_memory_streaming():
    """Memory-budgeted backpressure (reference: streaming_executor.py:48
    byte-bounded output queues): streaming a dataset ~10x larger than
    the budget keeps buffered stage output within budget + one
    in-flight window, regardless of how slowly the consumer drains."""
    from ray_tpu.data.dataset import DataContext

    ctx = DataContext.get_current()
    old = (ctx.use_tasks, ctx.parallelism, ctx.target_max_bytes_in_flight)
    block_bytes = 100 * 1000 * 8  # 100k int64 rows per block
    try:
        ctx.use_tasks = False
        ctx.parallelism = 4
        ctx.target_max_bytes_in_flight = 4 * block_bytes  # dataset is 10x
        ds = rd.range(40 * 100 * 1000, parallelism=40)  # 40 blocks
        total = 0
        for batch in ds.iter_batches(batch_size=50 * 1000):
            total += len(batch["id"])
        assert total == 40 * 100 * 1000
        peak = ctx.stats.get("max_bytes_buffered", 0)
        assert peak > 0
        # Budget + the parallelism in-flight overshoot window.
        assert peak <= ctx.target_max_bytes_in_flight + \
            ctx.parallelism * block_bytes, peak
    finally:
        ctx.use_tasks, ctx.parallelism, ctx.target_max_bytes_in_flight = old


def test_zero_copy_batches_are_views():
    """zero_copy_batch=True hands out slices of the source blocks
    (numpy views / arrow slices) when a batch is one contiguous run —
    no bytes copied; the default path still copies."""
    ds = rd.range_tensor(1000, shape=(4,), parallelism=4)  # 250-row blocks
    zc = list(ds.iter_batches(batch_size=125, zero_copy_batch=True))
    assert all(b["data"].base is not None for b in zc)  # views
    assert sum(len(b["data"]) for b in zc) == 1000
    copied = list(ds.iter_batches(batch_size=125))
    assert all(b["data"].base is None for b in copied)  # owned copies
    # Values identical either way.
    assert np.array_equal(zc[0]["data"], copied[0]["data"])


def test_iter_batches_shapes_and_drop_last():
    ds = rd.range(70)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 6]
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32, drop_last=True)]
    assert sizes == [32, 32]


def test_iter_jax_batches():
    import jax

    ds = rd.range(16)
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 2
    assert isinstance(batches[0]["id"], jax.Array)
    assert batches[0]["id"].shape == (8,)


def test_iter_torch_batches():
    import torch

    b = next(rd.range(8).iter_torch_batches(batch_size=8))
    assert isinstance(b["id"], torch.Tensor)


def test_streaming_split_covers_all_rows():
    ds = rd.range(100, parallelism=10)
    shards = ds.streaming_split(3)
    seen = []
    for s in shards:
        seen.extend(r["id"] for r in s.iter_rows())
    assert sorted(seen) == list(range(100))
    assert all(s.count() > 0 for s in shards)


def test_split_materializes_evenly():
    parts = rd.range(10).split(2)
    assert [p.count() for p in parts] == [5, 5]


# ---------------------------------------------------------------------------
# IO roundtrips


def test_parquet_roundtrip(tmp_path):
    ds = rd.from_numpy({"a": np.arange(20), "b": np.arange(20) * 1.5})
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 20
    assert back.sort("a").take(1) == [{"a": 0, "b": 0.0}]


def test_csv_roundtrip(tmp_path):
    rd.from_numpy({"x": np.arange(5)}).write_csv(str(tmp_path / "csv"))
    back = rd.read_csv(str(tmp_path / "csv"))
    assert [r["x"] for r in back.sort("x").take_all()] == [0, 1, 2, 3, 4]


def test_json_write_text_read(tmp_path):
    rd.from_items([{"m": 1}, {"m": 2}]).write_json(str(tmp_path / "j"))
    back = rd.read_json(str(tmp_path / "j"))
    assert sorted(r["m"] for r in back.take_all()) == [1, 2]
    p = tmp_path / "t.txt"
    p.write_text("hello\n\nworld\n")
    assert [r["text"] for r in rd.read_text(str(p)).take_all()] == ["hello", "world"]


def test_read_numpy_and_binary(tmp_path):
    np.save(tmp_path / "arr.npy", np.arange(6).reshape(2, 3))
    ds = rd.read_numpy(str(tmp_path / "arr.npy"))
    assert ds.take_all()[0]["data"].shape == (3,) or ds.count() == 2
    (tmp_path / "blob.bin").write_bytes(b"\x01\x02")
    bd = rd.read_binary_files(str(tmp_path / "blob.bin"), include_paths=True)
    row = bd.take_all()[0]
    assert row["bytes"] == b"\x01\x02"


# ---------------------------------------------------------------------------
# distributed execution + Train integration


def test_distributed_map_batches_over_cluster():
    ray_tpu.init(num_cpus=8, object_store_memory=64 * 1024 * 1024, ignore_reinit_error=True)
    try:
        import os

        ds = rd.range(40, parallelism=4).map_batches(
            lambda b: {"id": b["id"], "pid": np.full(len(b["id"]), os.getpid())}
        )
        rows = ds.take_all()
        assert sorted(r["id"] for r in rows) == list(range(40))
        # Stages ran in worker processes, not the driver.
        assert all(r["pid"] != os.getpid() for r in rows)
    finally:
        ray_tpu.shutdown()


def test_trainer_consumes_streaming_split(tmp_path):
    ray_tpu.init(num_cpus=8, object_store_memory=64 * 1024 * 1024, ignore_reinit_error=True)
    try:
        from ray_tpu import train
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

        def loop(config):
            shard = train.get_dataset_shard("train")
            total = sum(int(b["id"].sum()) for b in shard.iter_batches(batch_size=8))
            train.report({"shard_sum": total})

        result = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name="data_split", storage_path=str(tmp_path)),
            datasets={"train": rd.range(20)},
        ).fit()
        # Workers both reported; the union of shards is the full range.
        assert result.metrics["shard_sum"] >= 0
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# round-3 datasources: avro, webdataset, refs, tf


def _zigzag(n: int) -> bytes:
    # Independent encoder (not the reader's code) per the Avro 1.11 spec.
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_bytes(b: bytes) -> bytes:
    return _zigzag(len(b)) + b


def _write_avro(path, rows, codec=b"null"):
    import json
    import struct
    import zlib

    schema = {
        "type": "record", "name": "R",
        "fields": [
            {"name": "id", "type": "long"},
            {"name": "name", "type": "string"},
            {"name": "score", "type": "double"},
            {"name": "tags", "type": {"type": "array", "items": "string"}},
            {"name": "opt", "type": ["null", "long"]},
        ],
    }
    body = bytearray()
    for r in rows:
        body += _zigzag(r["id"])
        body += _avro_bytes(r["name"].encode())
        body += struct.pack("<d", r["score"])
        if r["tags"]:
            body += _zigzag(len(r["tags"]))
            for t in r["tags"]:
                body += _avro_bytes(t.encode())
        body += _zigzag(0)  # array terminator
        if r["opt"] is None:
            body += _zigzag(0)
        else:
            body += _zigzag(1) + _zigzag(r["opt"])
    payload = bytes(body)
    if codec == b"deflate":
        payload = zlib.compress(payload)[2:-4]  # raw deflate
    sync = b"S" * 16
    meta = (_zigzag(2)
            + _avro_bytes(b"avro.schema")
            + _avro_bytes(json.dumps(schema).encode())
            + _avro_bytes(b"avro.codec") + _avro_bytes(codec)
            + _zigzag(0))
    with open(path, "wb") as f:
        f.write(b"Obj\x01" + meta + sync)
        f.write(_zigzag(len(rows)) + _zigzag(len(payload)) + payload + sync)


ROWS = [
    {"id": 1, "name": "a", "score": 0.5, "tags": ["x", "y"], "opt": None},
    {"id": -7, "name": "bb", "score": -2.25, "tags": [], "opt": 42},
    {"id": 2**40, "name": "", "score": 0.0, "tags": ["z"], "opt": -1},
]


@pytest.mark.parametrize("codec", [b"null", b"deflate"])
def test_read_avro(tmp_path, codec):
    p = str(tmp_path / "f.avro")
    _write_avro(p, ROWS, codec=codec)
    got = ray_tpu.data.read_avro(p).take_all()
    assert got == ROWS


def test_read_webdataset(tmp_path):
    import io
    import json
    import tarfile

    p = str(tmp_path / "shard-000.tar")
    with tarfile.open(p, "w") as tf:
        for key, cls, meta in [("s1", 3, {"a": 1}), ("s2", 9, {"b": 2})]:
            for ext, payload in [
                ("jpg", b"\xff\xd8fakejpeg"),
                ("cls", str(cls).encode()),
                ("json", json.dumps(meta).encode()),
                ("txt", f"caption of {key}".encode()),
            ]:
                data = payload
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
    rows = ray_tpu.data.read_webdataset(p).take_all()
    assert [r["__key__"] for r in rows] == ["s1", "s2"]
    assert rows[0]["cls"] == 3 and rows[1]["cls"] == 9
    assert rows[0]["json"] == {"a": 1}
    assert rows[0]["txt"] == "caption of s1"
    assert rows[0]["jpg"].startswith(b"\xff\xd8")  # raw bytes kept


def test_from_refs_and_blocks():
    import numpy as np
    import pandas as pd
    import pyarrow as pa

    df = pd.DataFrame({"x": [1, 2, 3]})
    tbl = pa.Table.from_pandas(pd.DataFrame({"x": [4, 5]}),
                               preserve_index=False)
    arr = np.arange(4)
    ds = ray_tpu.data.from_pandas_refs([ray_tpu.put(df)])
    assert [r["x"] for r in ds.take_all()] == [1, 2, 3]
    ds = ray_tpu.data.from_arrow_refs([ray_tpu.put(tbl)])
    assert [r["x"] for r in ds.take_all()] == [4, 5]
    ds = ray_tpu.data.from_numpy_refs([ray_tpu.put(arr)])
    assert [r["data"] for r in ds.take_all()] == [0, 1, 2, 3]
    ds = ray_tpu.data.from_blocks([{"x": np.array([7, 8])}])
    assert [r["x"] for r in ds.take_all()] == [7, 8]


def test_from_tf():
    tf = pytest.importorskip("tensorflow")
    tfds = tf.data.Dataset.from_tensor_slices({"a": [1, 2, 3],
                                               "b": [4.0, 5.0, 6.0]})
    ds = ray_tpu.data.from_tf(tfds)
    rows = ds.take_all()
    assert [int(r["a"]) for r in rows] == [1, 2, 3]
    assert [float(r["b"]) for r in rows] == [4.0, 5.0, 6.0]


def test_webdataset_heterogeneous_and_dirs(tmp_path):
    import io
    import tarfile

    p = str(tmp_path / "s.tar")
    with tarfile.open(p, "w") as tf:
        # a/0001 and b/0001: same basename, different dirs = 2 samples;
        # only a/ has a txt (optional field).
        for name, data in [("a/0001.jpg", b"ja"), ("a/0001.txt", b"ca"),
                           ("b/0001.jpg", b"jb")]:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    rows = ray_tpu.data.read_webdataset(p).take_all()
    assert [r["__key__"] for r in rows] == ["a/0001", "b/0001"]
    assert rows[0]["jpg"] == b"ja" and rows[1]["jpg"] == b"jb"
    assert rows[0]["txt"] == "ca" and rows[1]["txt"] is None


def test_avro_namespaced_named_types(tmp_path):
    import json
    import struct

    # Record with an enum referenced by FULLNAME (what most writers emit).
    schema = {
        "type": "record", "name": "R", "namespace": "com.x",
        "fields": [
            {"name": "color",
             "type": {"type": "enum", "name": "Color",
                      "symbols": ["RED", "BLUE"]}},
            {"name": "again", "type": "com.x.Color"},
        ],
    }
    body = _zigzag(0) + _zigzag(1) + _zigzag(1) + _zigzag(0)  # RED,BLUE,BLUE,RED
    sync = b"S" * 16
    meta = (_zigzag(2)
            + _avro_bytes(b"avro.schema")
            + _avro_bytes(json.dumps(schema).encode())
            + _avro_bytes(b"avro.codec") + _avro_bytes(b"null")
            + _zigzag(0))
    p = str(tmp_path / "ns.avro")
    with open(p, "wb") as f:
        f.write(b"Obj\x01" + meta + sync)
        f.write(_zigzag(2) + _zigzag(len(body)) + body + sync)
    rows = ray_tpu.data.read_avro(p).take_all()
    assert rows == [{"color": "RED", "again": "BLUE"},
                    {"color": "BLUE", "again": "RED"}]


def test_split_at_indices_and_train_test_split():
    ds = rd.range(10)
    parts = ds.split_at_indices([3, 7])
    assert [p.count() for p in parts] == [3, 4, 3]
    assert [r["id"] for r in parts[1].take_all()] == [3, 4, 5, 6]
    train, test = rd.range(8).train_test_split(test_size=0.25)
    assert train.count() == 6 and test.count() == 2
    assert [r["id"] for r in test.take_all()] == [6, 7]
    train, test = rd.range(8).train_test_split(test_size=3, shuffle=True,
                                               seed=0)
    assert train.count() == 5 and test.count() == 3
    ids = sorted(r["id"] for r in train.take_all()) + sorted(
        r["id"] for r in test.take_all())
    assert sorted(ids) == list(range(8))
    with pytest.raises(ValueError):
        rd.range(4).train_test_split(test_size=1.5)


def test_random_sample_and_take_batch():
    ds = rd.range(2000)
    got = ds.random_sample(0.25, seed=7).count()
    assert 350 < got < 650  # ~500 expected
    assert rd.range(5).random_sample(0.0).count() == 0
    batch = rd.range(100).take_batch(8)
    assert list(batch["id"]) == list(range(8))


def test_iter_tf_batches():
    tf = pytest.importorskip("tensorflow")
    batches = list(rd.range(10).iter_tf_batches(batch_size=4))
    assert [int(b["id"].shape[0]) for b in batches] == [4, 4, 2]
    assert batches[0]["id"].dtype == tf.int64


def test_dataset_stats():
    ds = rd.range(1000).map_batches(lambda b: {"id": b["id"] * 2})
    assert "iterate" in ds.stats()
    total = ds.sum("id")
    assert total == 999000
    s = ds.stats()
    assert "1000 rows" in s and "rows/s" in s
