"""Actor-pool map stages (reference: data/_internal/execution/operators/
actor_pool_map_operator.py — pool lifecycle: min/max size, backlog
scale-up, idle scale-down, restart-on-death)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture
def cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()  # e.g. an auto-init leaked by a prior module
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


class AddBias:
    """Class UDF with observable construction cost/count."""

    def __init__(self, bias=100):
        self.bias = bias
        self.pid = os.getpid()

    def __call__(self, batch):
        return {"id": batch["id"] + self.bias,
                "pid": np.full_like(batch["id"], self.pid)}


def test_pool_runs_class_udf(cluster):
    ds = rdata.range(200).map_batches(
        AddBias, batch_size=20, compute="actors")
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == [i + 100 for i in range(200)]


def test_constructor_amortized_across_blocks(cluster):
    """One pool worker handles many blocks through ONE instance: the
    reported pids collapse to at most pool-size distinct values."""
    ds = rdata.range(400).map_batches(
        AddBias, batch_size=10,
        compute=rdata.ActorPoolStrategy(min_size=1, max_size=2))
    pids = {int(r["pid"]) for r in ds.take_all()}
    assert 1 <= len(pids) <= 2  # 40 blocks, <= 2 workers


def test_pool_scales_up_under_backlog(cluster):
    ctx = rdata.DataContext.get_current()
    ds = rdata.range(300).map_batches(
        AddBias, batch_size=10,
        compute=rdata.ActorPoolStrategy(min_size=1, max_size=3))
    out = ds.take_all()
    assert len(out) == 300
    stats = (ctx.stats or {}).get("actor_pool")
    assert stats and stats["spawned"] >= 1
    assert stats["peak_size"] <= 3


class CrashOnce:
    """Dies on the first block of a fresh process (pool must replace
    the worker and replay the block)."""

    MARK = "/tmp/ray_tpu_pool_crash_once"

    def __call__(self, batch):
        if not os.path.exists(self.MARK):
            with open(self.MARK, "w") as f:
                f.write("x")
            os._exit(1)
        return {"id": batch["id"] * 2}


def test_restart_on_death_replays_block(cluster):
    if os.path.exists(CrashOnce.MARK):
        os.remove(CrashOnce.MARK)
    ds = rdata.range(60).map_batches(
        CrashOnce, batch_size=10,
        compute=rdata.ActorPoolStrategy(min_size=1, max_size=1,
                                        max_restarts=2))
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == [i * 2 for i in range(60)]
    os.remove(CrashOnce.MARK)


def test_plain_fn_with_compute_rejected(cluster):
    with pytest.raises(ValueError, match="CLASS UDF"):
        rdata.range(10).map_batches(lambda b: b, compute="actors")
