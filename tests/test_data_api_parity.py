"""Round-5 Dataset API widening (reference: data/dataset.py — show/
num_blocks/size_bytes/input_files/names/types/copy/context/iterator/
randomize_block_order/split_proportionately/to_*_refs/to_tf/to_torch/
write_numpy/write_sql/write_webdataset/write_images/write_datasink)."""

from __future__ import annotations

import os
import sqlite3

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def _teardown():
    yield
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def test_introspection_surface(tmp_path):
    f = tmp_path / "in.csv"
    f.write_text("a,b\n1,x\n2,y\n3,z\n")
    ds = rd.read_csv(str(f))
    assert ds.input_files() == [str(f)]
    assert ds.names() == ["a", "b"]
    types = ds.types()
    assert len(types) == 2 and types[0].kind in "il"
    assert ds.num_blocks() >= 1
    assert ds.size_bytes() > 0
    assert ds.copy()._plan is not ds._plan
    assert ds.copy().take_all() == ds.take_all()
    assert ds.context is rd.DataContext.get_current()


def test_show_prints_rows(capsys):
    rd.range(3).show()
    out = capsys.readouterr().out
    assert "{'id': 0}" in out and "{'id': 2}" in out


def test_randomize_block_order_preserves_rows():
    ds = rd.range(100, parallelism=10)
    plain = [r["id"] for r in ds.take_all()]
    shuffled = [r["id"] for r in ds.randomize_block_order(seed=7).take_all()]
    assert sorted(shuffled) == plain
    # Same seed -> same order; block interiors stay contiguous.
    again = [r["id"] for r in ds.randomize_block_order(seed=7).take_all()]
    assert shuffled == again


def test_split_proportionately():
    parts = rd.range(100).split_proportionately([0.7, 0.2])
    sizes = [p.count() for p in parts]
    assert sizes == [70, 20, 10]
    assert sorted(r["id"] for p in parts for r in p.take_all()) == \
        list(range(100))
    with pytest.raises(ValueError):
        rd.range(10).split_proportionately([0.9, 0.2])


def test_iterator_covers_whole_dataset():
    it = rd.range(20).iterator()
    total = sum(int(b["id"].sum()) for b in it.iter_batches(batch_size=8))
    assert total == sum(range(20))


def test_to_refs_roundtrip():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                 ignore_reinit_error=True)
    ds = rd.range(10, parallelism=2)
    nrefs = ds.to_numpy_refs()
    assert len(nrefs) == 2
    fetched = [ray_tpu.get(r) for r in nrefs]
    assert sorted(int(x) for f in fetched for x in f["id"]) == list(range(10))
    prefs = ds.to_pandas_refs()
    assert sum(len(ray_tpu.get(r)) for r in prefs) == 10
    arefs = ds.to_arrow_refs()
    assert sum(ray_tpu.get(r).num_rows for r in arefs) == 10


def test_to_tf_dataset():
    import tensorflow as tf

    ds = rd.range(32).add_column("label", lambda r: r["id"] % 2)
    tfds = ds.to_tf("id", "label", batch_size=16)
    batches = list(tfds)
    assert len(batches) == 2
    feats, labels = batches[0]
    assert isinstance(feats, tf.Tensor) and int(tf.size(feats)) == 16
    # Dict form with column lists.
    tfds2 = ds.to_tf(["id"], ["label"], batch_size=32)
    feats2, labels2 = next(iter(tfds2))
    assert set(feats2.keys()) == {"id"} and set(labels2.keys()) == {"label"}


def test_to_torch_dataset():
    import torch

    ds = rd.range(12).add_column("y", lambda r: r["id"] * 2)
    loader = ds.to_torch(label_column="y", batch_size=6)
    batches = list(loader)
    assert len(batches) == 2
    feats, label = batches[0]
    assert isinstance(label, torch.Tensor) and len(label) == 6
    assert torch.equal(label, feats["id"] * 2)


def test_write_numpy(tmp_path):
    ds = rd.range(10, parallelism=2)
    outs = ds.write_numpy(str(tmp_path / "col"), column="id")
    assert all(o.endswith(".npy") for o in outs)
    vals = np.concatenate([np.load(o) for o in outs])
    assert sorted(vals.tolist()) == list(range(10))
    outs2 = ds.write_numpy(str(tmp_path / "all"))
    loaded = np.load(outs2[0])
    assert "id" in loaded


def test_write_sql_roundtrip(tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (id INTEGER, name TEXT)")
    conn.commit()
    conn.close()

    ds = rd.from_items([{"id": i, "name": f"n{i}"} for i in range(7)])
    wrote = ds.write_sql("INSERT INTO t VALUES (?, ?)",
                         lambda: sqlite3.connect(db))
    assert wrote == 7
    back = rd.read_sql("SELECT id, name FROM t ORDER BY id",
                       lambda: sqlite3.connect(db))
    rows = back.take_all()
    assert len(rows) == 7 and rows[3]["name"] == "n3"


def test_write_webdataset_roundtrip(tmp_path):
    items = [{"__key__": f"s{i:03d}", "txt": f"text-{i}", "cls": i,
              "bin": bytes([i] * 4)} for i in range(5)]
    outs = rd.from_items(items).write_webdataset(str(tmp_path / "wds"))
    assert all(o.endswith(".tar") for o in outs)
    back = rd.read_webdataset([str(tmp_path / "wds")]).take_all()
    by_key = {r["__key__"]: r for r in back}
    assert len(by_key) == 5
    assert by_key["s002"]["txt"] == "text-2"
    assert by_key["s002"]["cls"] == 2
    assert by_key["s002"]["bin"] == bytes([2] * 4)


def test_write_images_roundtrip(tmp_path):
    arrs = [np.full((4, 4, 3), i * 20, dtype=np.uint8) for i in range(3)]
    ds = rd.from_items([{"image": a} for a in arrs])
    outs = ds.write_images(str(tmp_path / "imgs"))
    assert len(outs) == 3 and all(o.endswith(".png") for o in outs)
    back = rd.read_images(str(tmp_path / "imgs")).take_all()
    vals = sorted(int(np.asarray(r["image"]).flat[0]) for r in back)
    assert vals == [0, 20, 40]


def test_write_datasink_lifecycle():
    events = []

    class Sink(rd.Datasink):
        def on_write_start(self):
            events.append("start")

        def write(self, block):
            events.append(("block", rd.BlockAccessor(block).num_rows()))

        def on_write_complete(self):
            events.append("done")

        def on_write_failed(self, error):
            events.append(("failed", str(error)))

    rd.range(10, parallelism=2).write_datasink(Sink())
    assert events[0] == "start" and events[-1] == "done"
    assert sum(n for tag, n in events[1:-1] if tag == "block") == 10

    class Boom(Sink):
        def write(self, block):
            raise RuntimeError("sink exploded")

    events.clear()
    with pytest.raises(RuntimeError):
        rd.range(4).write_datasink(Boom())
    assert ("failed", "sink exploded") in events
