"""Zero-copy data plane: metadata-only seals, peer-to-peer payload
pulls, device-aware fast paths, and relay-tree broadcast.

Structural guards, not benchmarks:
  * a large task result seals METADATA-ONLY — zero get_meta frames and
    (near-)zero payload bytes on the owner's head connection; the
    payload is pulled straight from the holder node;
  * a large numpy result reaches the caller with at most ONE host-side
    copy (dataplane copy counters + buffer aliasing), and same-node
    consumers get ZERO-copy aliasing views;
  * a colocated jax.Array get() returns the SAME device-resident array
    (no device→host→device round trip) — fails pre-change, when get()
    returned a host numpy copy;
  * completed readers register as relay sources (replica fan-out) and
    the relay gate parks excess pullers until a source appears;
  * holder death re-resolves to a surviving replica or spill copy;
  * the bulk plane's request framing is binary (no pickle on the hot
    path) and corrupt requests close the connection with a typed error.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import dataplane
from ray_tpu._private.worker_context import get_head, global_runtime

def _start_agent(address: str, *, resources: str, node_id: str,
                 env_extra: "dict | None" = None) -> subprocess.Popen:
    cmd = [
        sys.executable, "-m", "ray_tpu._private.node_agent",
        "--address", address, "--num-cpus", "4",
        "--resources", resources, "--node-id", node_id,
        "--force-remote-objects",
    ]
    env = dict(os.environ)
    env.pop("RAY_TPU_REMOTE", None)
    env.update(env_extra or {})
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def _wait_nodes(n: int, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len([x for x in ray_tpu.nodes() if x["alive"]]) >= n:
            return
        time.sleep(0.2)
    raise TimeoutError(f"cluster never reached {n} nodes")


@pytest.fixture(scope="module")
def agent_cluster():
    """Head (2 CPUs) + two agent nodes with private arenas (workers
    forced remote, so payloads ride the p2p plane, not head shm)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    head = get_head()
    address = f"{head.address[0]}:{head.address[1]}"
    agents = [
        _start_agent(address, resources='{"nodeA": 4}', node_id="node-a"),
        _start_agent(address, resources='{"nodeB": 4}', node_id="node-b"),
    ]
    try:
        _wait_nodes(3)
        yield address, agents
    finally:
        for a in agents:
            if a.poll() is None:
                a.kill()
                a.wait(timeout=10)
        ray_tpu.shutdown()


@ray_tpu.remote
def _produce(n):
    return np.arange(n, dtype=np.float64)


@ray_tpu.remote
def _consume(arr):
    from ray_tpu._private import dataplane as dp

    aliased = arr.base is not None
    return {"first": float(arr[0]), "last": float(arr[-1]),
            "aliased": aliased,
            "zero_copy": dp.counters()["bytes"].get("zero_copy", 0),
            "copies": dict(dp.counters()["host_copies"])}


N_BIG = 1_000_000  # 8 MB of float64 — far above every inline threshold


# ---------------------------------------------------------------- seals

def test_metadata_only_seal_zero_head_frames(agent_cluster):
    """A large result produced on an agent node resolves with ZERO
    get_meta frames and near-zero bytes on the owner's head connection
    — the seal carried metadata only, the payload came from the
    holder."""
    rt = global_runtime()
    ray_tpu.get(_produce.options(resources={"nodeA": 1}).remote(8),
                timeout=60)  # warm the worker
    before_meta = rt.conn.sent_kinds.get("get_meta", 0)
    before_bytes = rt.conn.bytes_sent
    ref = _produce.options(resources={"nodeA": 1}).remote(N_BIG)
    val = ray_tpu.get(ref, timeout=60)
    assert val.shape == (N_BIG,) and float(val[-1]) == N_BIG - 1
    assert rt.conn.sent_kinds.get("get_meta", 0) == before_meta, \
        "metadata-only seal should resolve without a head meta lookup"
    sent = rt.conn.bytes_sent - before_bytes
    assert sent < N_BIG * 8 // 100, \
        f"{sent} bytes crossed the head connection for an 8 MB result"


def test_owner_marker_carries_location(agent_cluster):
    """The owner-store slot for a metadata-only seal holds the holder
    location record (nbytes + node + arena identity), not payload."""
    rt = global_runtime()
    ref = _produce.options(resources={"nodeA": 1}).remote(N_BIG)
    deadline = time.monotonic() + 60
    loc = None
    while time.monotonic() < deadline:
        v = rt._owned_store.get(ref.hex())
        if v is not None:
            loc = v[1]
            break
        time.sleep(0.05)
    assert loc, "marker never arrived"
    assert loc["size"] >= N_BIG * 8
    assert loc["node"] == "node-a"
    assert loc.get("store") and loc.get("bulk_port")
    assert loc.get("arr", {}).get("kind") == "ndarray"
    assert tuple(loc["arr"]["shape"]) == (N_BIG,)
    del ref


def test_single_host_copy_and_aliasing(agent_cluster):
    """Acceptance guard: the 8 MB numpy result reaches the caller with
    at most one host-side copy end to end, and the returned array
    aliases the transfer buffer (no hidden deserialization copy)."""
    dataplane.reset_counters()
    ref = _produce.options(resources={"nodeA": 1}).remote(N_BIG)
    val = ray_tpu.get(ref, timeout=60)
    assert val.base is not None, "result should alias the pull buffer"
    snap = dataplane.counters()
    assert sum(snap["host_copies"].values()) <= 1, snap
    assert sum(snap["bytes"].values()) >= N_BIG * 8, snap


def test_same_node_consumer_zero_copy(agent_cluster):
    """A consumer on the holder node reads the payload as an aliasing
    view of the node arena — zero host-side copies."""
    ref = _produce.options(resources={"nodeA": 1}).remote(N_BIG)
    out = ray_tpu.get(
        _consume.options(resources={"nodeA": 1}).remote(ref), timeout=60)
    assert out["first"] == 0.0 and out["last"] == N_BIG - 1
    assert out["aliased"]
    assert out["zero_copy"] >= N_BIG * 8, out


def test_cross_node_jax_rematerializes(agent_cluster):
    """A jax.Array produced on one node comes back as a jax.Array on
    the consumer (device_put from the zero-copy host view), with
    dtype/shape from the seal metadata intact."""
    jax = pytest.importorskip("jax")

    @ray_tpu.remote(resources={"nodeA": 1})
    def produce_jax(n):
        import jax.numpy as jnp

        return jnp.arange(n, dtype=jnp.float32) * 2.0

    val = ray_tpu.get(produce_jax.remote(200_000), timeout=60)
    assert isinstance(val, jax.Array)
    assert val.dtype == np.float32 and val.shape == (200_000,)
    assert float(val[3]) == 6.0


def test_relay_replica_registered_in_wave(agent_cluster):
    """A cross-node reader of a big object registers its copy as a
    relay source immediately (bulk_replicate_delay_s=0), turning later
    pulls into a tree."""
    head = get_head()
    ref = _produce.options(resources={"nodeA": 1}).remote(3_000_000)
    out = ray_tpu.get(
        _consume.options(resources={"nodeB": 1}).remote(ref), timeout=60)
    assert out["last"] == 3_000_000 - 1
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        e = head.objects.get(ref.hex())
        if e is not None and "node-b" in e.replicas:
            return
        time.sleep(0.1)
    e = head.objects.get(ref.hex())
    raise AssertionError(
        f"node-b never registered as a relay source "
        f"(replicas={e and sorted(e.replicas)})")


# ----------------------------------------------------- relay fan-out gate

class _FakeConn:
    def __init__(self, client_id, host=None, node_id="far-node"):
        self.peer_info = {"client_id": client_id, "remote": True,
                          "host": host, "node_id": node_id}
        self.casts = []

    def cast(self, kind, body):
        self.casts.append((kind, body))

    def metas(self):
        return [b["metas"] for k, b in self.casts if k == "objects_ready"]


def test_relay_fanout_gate_parks_and_releases(agent_cluster):
    """Pullers beyond relay_fanout park until a pull slot frees
    (read_done) or a relay source registers (add_replica); the health
    sweep is the safety valve. Exercised against the in-process head
    with synthetic remote clients."""
    head = get_head()
    oid = "deadbeef" * 4
    head._h_put_p2p({"object_id": oid, "node_id": "node-a",
                     "offset": 0, "size": 32 * 1024 * 1024,
                     "owner_id": "tester"}, None)
    old_fanout = head.config.relay_fanout
    head.config.relay_fanout = 2
    try:
        conns = [_FakeConn(f"puller-{i}") for i in range(3)]
        for i, c in enumerate(conns):
            head._h_get_meta({"waiter_id": f"w{i}", "ids": [oid]}, c)
        # Metas go through the send pool; give them a beat.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
                len(conns[0].metas()) < 1 or len(conns[1].metas()) < 1):
            time.sleep(0.02)
        assert conns[0].metas() and conns[1].metas()
        assert not conns[2].metas(), "third puller should be parked"
        with head.lock:
            assert "w2" in head._parked_waiters
        # First puller finishes: slot frees, parked puller released.
        head._h_read_done({"ids": [oid]}, conns[0])
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not conns[2].metas():
            time.sleep(0.02)
        assert conns[2].metas(), "parked puller never released"
        metas = conns[2].metas()[0]
        assert metas[oid][0] == "p2p"
    finally:
        head.config.relay_fanout = old_fanout
        with head.lock:
            head.objects.pop(oid, None)


def test_bulk_pull_retries_under_injected_drops(agent_cluster):
    """Injected drop on bulk_pull: stripes retry per the unified policy
    and the get still lands (chaos matrix row 3). Host mapping is
    disabled for the pull so the bulk plane actually engages."""
    from ray_tpu._private import faultinject

    rt = global_runtime()
    old = rt._host_shm_ok
    rt._host_shm_ok = False
    spec = {"seed": 7, "rules": [{"kind": "bulk_pull", "drop": 0.4}]}
    try:
        with faultinject.inject(spec):
            dataplane.reset_counters()
            ref = _produce.options(resources={"nodeA": 1}).remote(N_BIG)
            val = ray_tpu.get(ref, timeout=120)
            assert float(val[-1]) == N_BIG - 1
        snap = dataplane.counters()["bytes"]
        assert snap.get("p2p", 0) >= N_BIG * 8 or snap.get("inline", 0)
    finally:
        rt._host_shm_ok = old


# ------------------------------------------------------- device fast path

def test_colocated_jax_get_is_device_resident(agent_cluster):
    """Acceptance guard (fails pre-change): put() a device array, get()
    it in the same process — the SAME jax.Array comes back, no
    device→host→device round trip, dtype/shape/sharding intact."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    value = jnp.arange(50_000, dtype=jnp.float32) * 1.5
    ref = ray_tpu.put(value)
    out = ray_tpu.get(ref)
    assert out is value, \
        "colocated get() must return the cached device array"
    assert isinstance(out, jax.Array)
    assert out.dtype == value.dtype and out.shape == value.shape
    assert out.sharding == value.sharding
    # Repeat gets keep hitting the cache.
    assert ray_tpu.get(ref) is value


def test_colocated_actor_chain_keeps_device_buffer(agent_cluster):
    """An actor's tensor result consumed by a later call on the same
    worker rides the device cache: the consumer sees the SAME buffer
    pointer — no host round trip between pipeline stages."""
    pytest.importorskip("jax")

    @ray_tpu.remote
    class Stage:
        def produce(self, n):
            import jax.numpy as jnp

            arr = jnp.arange(n, dtype=jnp.float32)
            self.ptr = arr.unsafe_buffer_pointer()
            return arr

        def consume(self, arr):
            return (getattr(arr, "unsafe_buffer_pointer", lambda: -1)()
                    == self.ptr)

    s = Stage.remote()
    ref = s.produce.remote(100_000)
    assert ray_tpu.get(s.consume.remote(ref), timeout=60)
    ray_tpu.kill(s)


# ------------------------------------------------------ serialization

def test_jax_array_serializes_once_top_level_and_nested():
    """Satellite: serialize() no longer pre-converts top-level arrays —
    reducer_override handles every depth, exactly once. Top-level and
    nested jax arrays round-trip to equal host arrays."""
    pytest.importorskip("jax")
    import jax.numpy as jnp

    from ray_tpu._private import serialization

    arr = jnp.arange(1000, dtype=jnp.float32)
    for value in (arr, {"nested": [arr, 3]}):
        blob = serialization.dumps(value)
        out = serialization.loads(blob)
        got = out if not isinstance(out, dict) else out["nested"][0]
        assert isinstance(got, np.ndarray)
        np.testing.assert_array_equal(np.asarray(arr), got)


def test_array_meta_stamps_dtype_shape():
    pytest.importorskip("jax")
    import jax.numpy as jnp

    meta = dataplane.array_meta(np.zeros((3, 4), dtype=np.int32))
    assert meta == {"kind": "ndarray", "dtype": "int32", "shape": (3, 4)}
    jmeta = dataplane.array_meta(jnp.zeros((2, 2), dtype=jnp.float32))
    assert jmeta["kind"] == "jax" and jmeta["shape"] == (2, 2)
    assert "sharding" in jmeta
    assert dataplane.array_meta({"not": "a tensor"}) is None


# ----------------- destructive chaos matrix (kills the fixture agents —
# keep these LAST in the module)

def test_holder_sigkill_reresolves_to_replica(agent_cluster):
    """Holder node dies mid-life: a relay replica on a surviving node
    is promoted to primary and the get succeeds (chaos matrix row 1)."""
    _address, agents = agent_cluster
    head = get_head()
    ref = _produce.options(resources={"nodeA": 1}).remote(3_000_000)
    # Prime a replica on node-b via a cross-node read.
    out = ray_tpu.get(
        _consume.options(resources={"nodeB": 1}).remote(ref), timeout=60)
    assert out["last"] == 3_000_000 - 1
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        e = head.objects.get(ref.hex())
        if e is not None and "node-b" in e.replicas:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("replica never registered")
    agents[0].kill()
    agents[0].wait(timeout=10)
    # Head declares the node dead on conn close; the entry promotes the
    # node-b replica. The owner-side loc pull fails over via the head.
    val = ray_tpu.get(ref, timeout=60)
    assert float(val[-1]) == 3_000_000 - 1
    e = head.objects.get(ref.hex())
    assert e.location == "node-b"


def test_spill_copy_survives_holder_death(agent_cluster):
    """Memory-watermark spill writes the payload to external storage;
    after the (sole) holder dies, the get restores from the spill copy
    instead of raising ObjectLostError (chaos matrix row 2)."""
    _address, agents = agent_cluster
    head = get_head()
    ref = _produce.options(resources={"nodeB": 1}).remote(2_000_000)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        e = head.objects.get(ref.hex())
        if e is not None and e.state == "SEALED" and e.location == "node-b":
            break
        time.sleep(0.1)
    else:
        raise AssertionError("object never sealed on node-b")
    # PR 5 watermark path: pressure on node-b triggers head-driven
    # spill through the agent's spill-with-consent protocol.
    head._spill_node_objects("node-b", max_objects=4)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        e = head.objects.get(ref.hex())
        if e is not None and e.spill_path:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("object never spilled")
    agents[1].kill()
    agents[1].wait(timeout=10)
    val = ray_tpu.get(ref, timeout=60)
    assert float(val[-1]) == 2_000_000 - 1


def test_device_cache_kill_switch(monkeypatch):
    """RAY_TPU_DATA_PLANE=0 disables the colocated device cache: get()
    falls back to the PR-era host copy."""
    pytest.importorskip("jax")
    import jax.numpy as jnp

    monkeypatch.setenv("RAY_TPU_DATA_PLANE", "0")
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1)
    try:
        value = jnp.arange(50_000, dtype=jnp.float32)
        out = ray_tpu.get(ray_tpu.put(value))
        assert out is not value
        assert np.asarray(out).shape == (50_000,)
    finally:
        ray_tpu.shutdown()
