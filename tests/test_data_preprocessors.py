"""Data preprocessors (reference: ray.data.preprocessors —
preprocessor.py Preprocessor ABC + scaler/encoder/imputer/chain/
concatenator/normalizer/discretizer modules)."""

from __future__ import annotations

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.preprocessors import (
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    Normalizer,
    OneHotEncoder,
    OrdinalEncoder,
    Preprocessor,
    PreprocessorNotFittedException,
    RobustScaler,
    SimpleImputer,
    StandardScaler,
    UniformKBinsDiscretizer,
)


@pytest.fixture(scope="module", autouse=True)
def _teardown():
    yield
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def _col(ds, c):
    return np.asarray([r[c] for r in ds.take_all()])


def test_standard_scaler_fit_transform():
    ds = rd.from_items([{"x": float(i), "y": float(i * 10)}
                        for i in range(10)])
    sc = StandardScaler(["x"])
    out = sc.fit_transform(ds)
    xs = _col(out, "x")
    assert abs(xs.mean()) < 1e-9 and abs(xs.std() - 1.0) < 1e-9
    assert _col(out, "y")[3] == 30.0  # untouched column

    # Serving-time batch path matches the dataset path.
    b = sc.transform_batch({"x": np.arange(10.0), "y": np.zeros(10)})
    np.testing.assert_allclose(b["x"], xs, rtol=1e-12)


def test_unfitted_raises():
    sc = StandardScaler(["x"])
    with pytest.raises(PreprocessorNotFittedException):
        sc.transform(rd.range(3))
    # Stateless preprocessors never require fit.
    c = Concatenator(["id"], output_column_name="f")
    assert "f" in c.transform_batch({"id": np.arange(4)})


def test_min_max_and_robust_scalers():
    ds = rd.from_items([{"x": float(v)} for v in [0, 5, 10]])
    mm = MinMaxScaler(["x"]).fit(ds)
    np.testing.assert_allclose(
        mm.transform_batch({"x": np.array([0.0, 5.0, 10.0])})["x"],
        [0.0, 0.5, 1.0])

    rb = RobustScaler(["x"]).fit(
        rd.from_items([{"x": float(v)} for v in range(1, 102)]))
    out = rb.transform_batch({"x": np.array([51.0])})
    assert abs(out["x"][0]) < 1e-9  # median maps to 0


def test_label_and_ordinal_encoders():
    ds = rd.from_items([{"cls": c, "f": c} for c in
                        ["cat", "dog", "cat", "bird"]])
    le = LabelEncoder("cls").fit(ds)
    enc = le.transform_batch({"cls": np.array(["bird", "cat", "dog"])})
    assert enc["cls"].tolist() == [0, 1, 2]  # sorted-unique codes
    dec = le.inverse_transform_batch(enc)
    assert dec["cls"].tolist() == ["bird", "cat", "dog"]
    with pytest.raises(ValueError, match="unseen"):
        le.transform_batch({"cls": np.array(["fish"])})

    oe = OrdinalEncoder(["f"]).fit(ds)
    assert oe.transform_batch(
        {"f": np.array(["dog", "bird"])})["f"].tolist() == [2, 0]


def test_one_hot_encoder():
    ds = rd.from_items([{"c": v} for v in ["a", "b", "a"]])
    oh = OneHotEncoder(["c"]).fit(ds)
    out = oh.transform_batch({"c": np.array(["b", "a", "zzz"])})
    assert "c" not in out
    assert out["c_a"].tolist() == [0, 1, 0]
    assert out["c_b"].tolist() == [1, 0, 0]  # unseen -> all-zero row


def test_simple_imputer_strategies():
    ds = rd.from_items([{"x": v} for v in [1.0, np.nan, 3.0]])
    mean = SimpleImputer(["x"], strategy="mean").fit(ds)
    assert mean.transform_batch(
        {"x": np.array([np.nan])})["x"][0] == 2.0
    const = SimpleImputer(["x"], strategy="constant", fill_value=-1.0)
    assert const.transform_batch(
        {"x": np.array([np.nan, 5.0])})["x"].tolist() == [-1.0, 5.0]
    with pytest.raises(ValueError):
        SimpleImputer(["x"], strategy="constant")


def test_concatenator_and_normalizer():
    out = Concatenator(["a", "b"], output_column_name="feat").\
        transform_batch({"a": np.array([1.0, 2.0]),
                         "b": np.array([3.0, 4.0]),
                         "keep": np.array([9, 9])})
    assert out["feat"].shape == (2, 2) and out["feat"].dtype == np.float32
    assert "a" not in out and "keep" in out

    nm = Normalizer(["a", "b"], norm="l2").transform_batch(
        {"a": np.array([3.0]), "b": np.array([4.0])})
    np.testing.assert_allclose([nm["a"][0], nm["b"][0]], [0.6, 0.8])


def test_discretizer_bins():
    ds = rd.from_items([{"x": float(v)} for v in range(100)])
    kb = UniformKBinsDiscretizer(["x"], bins=4).fit(ds)
    out = kb.transform_batch({"x": np.array([0.0, 30.0, 60.0, 99.0])})
    assert out["x"].tolist() == [0, 1, 2, 3]
    # NaN must not silently become the top bin.
    with pytest.raises(ValueError, match="NaN"):
        kb.transform_batch({"x": np.array([np.nan])})


def test_nan_in_fit_column_does_not_poison_stats():
    """A NaN at FIT time must not corrupt stats (NaN stats silently
    zeroed columns via the zero-variance branch / top-binned all
    values). Fit aggregates are nan-aware like the reference's
    null-skipping aggregates."""
    ds = rd.from_items([{"x": v} for v in [1.0, np.nan, 3.0]])
    sc = StandardScaler(["x"]).fit(ds)
    out = sc.transform_batch({"x": np.array([1.0, 3.0])})
    np.testing.assert_allclose(out["x"], [-1.0, 1.0])
    mm = MinMaxScaler(["x"]).fit(ds)
    np.testing.assert_allclose(
        mm.transform_batch({"x": np.array([1.0, 3.0])})["x"], [0.0, 1.0])
    kb = UniformKBinsDiscretizer(["x"], bins=2).fit(ds)
    assert kb.transform_batch(
        {"x": np.array([1.0, 3.0])})["x"].tolist() == [0, 1]


def test_imputer_categorical_most_frequent_and_constant():
    ds = rd.from_items([{"c": v} for v in
                        ["sf", "sf", None, "nyc", None]])
    mf = SimpleImputer(["c"], strategy="most_frequent").fit(ds)
    out = mf.transform_batch({"c": np.array(["nyc", None], dtype=object)})
    assert out["c"].tolist() == ["nyc", "sf"]
    const = SimpleImputer(["c"], strategy="constant",
                          fill_value="unknown")
    out2 = const.transform_batch({"c": np.array([None, "sf"],
                                                dtype=object)})
    assert out2["c"].tolist() == ["unknown", "sf"]


def test_stateless_chain_needs_no_fit():
    ch = Chain(Normalizer(["a", "b"]),
               Concatenator(["a", "b"], output_column_name="f"))
    out = ch.transform_batch({"a": np.array([3.0]),
                              "b": np.array([4.0])})
    np.testing.assert_allclose(out["f"], [[0.6, 0.8]])


def test_chain_fits_each_stage_on_prior_output():
    ds = rd.from_items([{"x": float(i), "c": ["a", "b"][i % 2]}
                        for i in range(8)])
    chain = Chain(
        StandardScaler(["x"]),
        OneHotEncoder(["c"]),
        Concatenator(["x", "c_a", "c_b"], output_column_name="features"),
    )
    out = chain.fit_transform(ds)
    rows = out.take_all()
    assert set(rows[0]) == {"features"}
    assert rows[0]["features"].shape == (3,)
    # The batch path composes identically.
    b = chain.transform_batch({"x": np.array([0.0]),
                               "c": np.array(["a"])})
    assert b["features"].shape == (1, 3)


def test_fitted_preprocessor_travels_to_train_workers(tmp_path):
    """The fit-on-driver / transform-on-worker flow Train uses
    (reference: preprocessors serialized into Train checkpoints)."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                 ignore_reinit_error=True)
    ds = rd.from_items([{"x": float(i)} for i in range(16)])
    sc = StandardScaler(["x"]).fit(ds)

    @ray_tpu.remote
    def worker_transform(p: Preprocessor, xs):
        return p.transform_batch({"x": np.asarray(xs)})["x"].mean()

    m = ray_tpu.get(worker_transform.remote(sc, list(range(16))),
                    timeout=60)
    assert abs(m) < 1e-9


def test_tokenizer_and_count_vectorizer():
    from ray_tpu.data.preprocessors import CountVectorizer, Tokenizer

    ds = rd.from_items([{"text": "the cat sat"},
                        {"text": "the dog SAT!"}])
    tok = Tokenizer(["text"]).transform_batch(
        {"text": np.array(["Hello, World"], dtype=object)})
    assert tok["text"][0] == ["hello", "world"]

    cv = CountVectorizer(["text"]).fit(ds)
    out = cv.transform_batch({"text": np.array(["the the cat"],
                                               dtype=object)})
    assert "text" not in out
    assert out["text_the"].tolist() == [2]
    assert out["text_cat"].tolist() == [1]
    assert out["text_dog"].tolist() == [0]

    top = CountVectorizer(["text"], max_features=2).fit(ds)
    # 'the' (2) and 'sat' (2) are the top-2 tokens.
    cols = [k for k in top.transform_batch(
        {"text": np.array(["x"], dtype=object)}) if k.startswith("text_")]
    assert sorted(cols) == ["text_sat", "text_the"]


def test_feature_hasher_and_hashing_vectorizer():
    from ray_tpu.data.preprocessors import (FeatureHasher,
                                            HashingVectorizer, Tokenizer)

    hv = HashingVectorizer(["text"], num_features=16)
    out = hv.transform_batch({"text": np.array(["cat cat dog"],
                                               dtype=object)})
    mat = out["text_hashed"]
    assert mat.shape == (1, 16) and mat.sum() == 3.0 and mat.max() == 2.0

    fh = Chain(Tokenizer(["text"]),
               FeatureHasher(["text"], num_features=8))
    out2 = fh.transform_batch({"text": np.array(["a b a"], dtype=object)})
    assert out2["hashed_features"].shape == (1, 8)
    assert out2["hashed_features"].sum() == 3.0


def test_maxabs_multihot_power():
    from ray_tpu.data.preprocessors import (MaxAbsScaler, MultiHotEncoder,
                                            PowerTransformer)

    ds = rd.from_items([{"x": v} for v in [-4.0, 2.0]])
    ma = MaxAbsScaler(["x"]).fit(ds)
    np.testing.assert_allclose(
        ma.transform_batch({"x": np.array([-4.0, 2.0])})["x"], [-1.0, 0.5])

    genres = rd.from_items([{"g": ["scifi", "drama"]},
                            {"g": ["drama"]}])
    mh = MultiHotEncoder(["g"]).fit(genres)
    out = mh.transform_batch({"g": np.array([["drama", "drama"],
                                             ["scifi"]], dtype=object)})
    assert out["g"].tolist() == [[2, 0], [0, 1]]  # cols: drama, scifi

    pt = PowerTransformer(["x"], power=0.0, method="box-cox")
    np.testing.assert_allclose(
        pt.transform_batch({"x": np.array([1.0, np.e])})["x"], [0.0, 1.0])
    yj = PowerTransformer(["x"], power=1.0)
    np.testing.assert_allclose(
        yj.transform_batch({"x": np.array([-1.0, 0.0, 3.0])})["x"],
        [-1.0, 0.0, 3.0])
    with pytest.raises(ValueError, match="positive"):
        pt.transform_batch({"x": np.array([-1.0])})
