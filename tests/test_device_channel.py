"""Device-resident DAG channels (reference: NCCL tensor channels,
python/ray/experimental/channel/torch_tensor_nccl_channel.py:44).
Array leaves of a with_tensor_transport("device") edge ride the JAX
transfer fabric device-to-device between actor processes; only a tiny
descriptor crosses the host meta channel."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=3, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Producer:
    def make(self, scale):
        import jax.numpy as jnp

        return jnp.arange(1024, dtype=jnp.float32) * scale

    def make_tree(self, scale):
        import jax.numpy as jnp

        return {"w": jnp.ones((8, 8), jnp.float32) * scale,
                "tag": "meta-only-leaf", "n": 3}


@ray_tpu.remote
class Consumer:
    def reduce(self, arr):
        import jax

        # The hand-off must arrive as a DEVICE array (pulled over the
        # transfer fabric), not a host numpy copy.
        assert isinstance(arr, jax.Array), type(arr)
        return float(arr.sum())

    def reduce_tree(self, tree):
        import jax

        assert isinstance(tree["w"], jax.Array), type(tree["w"])
        return float(tree["w"].sum()), tree["tag"], tree["n"]


def test_device_edge_between_actors(cluster):
    p, c = Producer.remote(), Consumer.remote()
    with InputNode() as inp:
        arr = p.make.bind(inp).with_tensor_transport("device")
        out = c.reduce.bind(arr)
    dag = out.experimental_compile()
    assert dag.ensure_compiled() is dag
    assert dag._mode == "channels", dag._compile_failure
    expect = float(np.arange(1024, dtype=np.float32).sum())
    for scale in (1.0, 2.0, 3.0):
        got = ray_tpu.get(dag.execute(scale), timeout=60)
        assert got == pytest.approx(expect * scale)
    dag.teardown()


def test_device_edge_pytree_and_driver_read(cluster):
    """Mixed pytrees (arrays + plain leaves) cross a device edge, and
    the DRIVER can read a device-typed output channel directly."""
    import jax

    p, c = Producer.remote(), Consumer.remote()
    with InputNode() as inp:
        tree = p.make_tree.bind(inp).with_tensor_transport("device")
        red = c.reduce_tree.bind(tree)
        raw = p.make.bind(inp).with_tensor_transport("device")
        out = MultiOutputNode([red, raw])
    dag = out.experimental_compile()
    assert dag.ensure_compiled() is dag
    assert dag._mode == "channels", dag._compile_failure
    red_out, raw_out = ray_tpu.get(dag.execute(2.0), timeout=60)
    assert red_out == (pytest.approx(128.0), "meta-only-leaf", 3)
    # The driver-side read of a device edge lands as a device array.
    assert isinstance(raw_out, jax.Array)
    np.testing.assert_allclose(
        np.asarray(raw_out), np.arange(1024, dtype=np.float32) * 2.0)
    dag.teardown()


def test_device_edge_repeated_executions(cluster):
    """The uuid/sequence machinery survives many executions on one
    compiled DAG (each write registers a fresh transfer uuid)."""
    p, c = Producer.remote(), Consumer.remote()
    with InputNode() as inp:
        out = c.reduce.bind(
            p.make.bind(inp).with_tensor_transport("device"))
    dag = out.experimental_compile()
    dag.ensure_compiled()
    assert dag._mode == "channels", dag._compile_failure
    base = float(np.arange(1024, dtype=np.float32).sum())
    for i in range(10):
        assert ray_tpu.get(dag.execute(float(i)), timeout=60) == (
            pytest.approx(base * i))
    dag.teardown()
