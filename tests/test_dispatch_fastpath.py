"""Direct-call plane regression guards.

Deterministic frame-count checks (not timing benchmarks): steady-state
actor calls and lease-cached same-shape tasks must generate ZERO
submission-side head frames per call — the owner talks to the worker
directly (direct_push on the peer connection) and the head sees only
batched, amortized bookkeeping casts (task_started / task_finished /
owner_sealed). Counters live on rpc.Connection (frames_sent,
calls_sent, sent_kinds) and are surfaced via
ray_tpu.util.metrics.rpc_counters().

Also carries this PR's serialization regression test: jax arrays nested
inside containers must pickle via _RuntimePickler.reducer_override's
device→host conversion (the old top-level-only _to_host crashed).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.worker_context import global_runtime


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _wait(pred, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise TimeoutError(f"never happened: {msg}")


def _direct_push_count(rt) -> int:
    with rt._owner_conns_lock:
        conns = list(rt._owner_conns.values())
    return sum(c.sent_kinds.get("direct_push", 0) for c in conns)


# ------------------------------------------------------- actor fast path


def test_actor_calls_zero_head_frames_steady_state(cluster):
    @ray_tpu.remote
    class Echo:
        def ping(self, x=None):
            return x

    a = Echo.remote()
    rt = global_runtime()
    # Warm-up: the first call rides the head and triggers the direct
    # grant; the route flips to direct once it drains.
    assert ray_tpu.get(a.ping.remote(1)) == 1
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 40
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    for i in range(N):
        assert ray_tpu.get(a.ping.remote(i)) == i
    # ZERO head submissions and ZERO synchronous head RPCs per call:
    # every call went owner→worker on the direct plane.
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N
    ray_tpu.kill(a)


def test_actor_results_correct_and_ordered(cluster):
    @ray_tpu.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    a = Seq.remote()
    rt = global_runtime()
    ray_tpu.get(a.add.remote(-1))
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="route direct")
    # Burst far past the inflight window: owner-side queueing must
    # preserve submission order end to end.
    n = int(max(rt._direct.window * 3, 150))
    refs = [a.add.remote(i) for i in range(n)]
    assert ray_tpu.get(refs) == list(range(n))
    assert ray_tpu.get(a.get_log.remote()) == [-1] + list(range(n))
    ray_tpu.kill(a)


# ------------------------------------------------------- lease fast path


def test_lease_cached_tasks_zero_head_frames(cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    rt = global_runtime()
    # Warm-up: first submissions ride the head and request a lease.
    assert ray_tpu.get(f.remote(0)) == 1
    _wait(lambda: len(rt._direct.lease_pools) > 0,
          msg="no worker lease granted")

    # Steady state = sequential same-shape submission: each task finds
    # an idle lease (per-lease window 1), so EVERY one goes direct with
    # zero head frames. Bursts beyond the pool's idle capacity spill to
    # the head by design (parallelism over pipelining for normal
    # tasks) — covered by test_lease_respects_window_spillback.
    N = 40
    before_submit = rt.conn.sent_kinds.get("submit_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    for i in range(N):
        assert ray_tpu.get(f.remote(i)) == i + 1
    pushed = _direct_push_count(rt) - before_push
    spilled = rt.conn.sent_kinds.get("submit_task", 0) - before_submit
    assert pushed == N, f"expected all {N} direct, {spilled} spilled"
    assert spilled == 0
    assert rt.conn.calls_sent == before_calls


def test_lease_respects_window_spillback(cluster):
    """Bursts beyond the lease pool's idle capacity spill to the head
    path (parallel dispatch) and still complete."""

    @ray_tpu.remote
    def g(x):
        time.sleep(0.01)
        return x * 2

    rt = global_runtime()
    ray_tpu.get(g.remote(0))
    _wait(lambda: len(rt._direct.lease_pools) > 0, msg="no lease for g")
    before_spill = rt._direct.stats["spillbacks"]
    n = 60
    refs = [g.remote(i) for i in range(n)]
    assert ray_tpu.get(refs) == [i * 2 for i in range(n)]
    # The burst exceeded the pool's idle capacity: some tasks spilled.
    assert rt._direct.stats["spillbacks"] > before_spill


def test_lease_pool_parallelism_preserved(cluster):
    """Same-shape SLOW tasks must still run in parallel — leases never
    queue one normal task behind another owner-side (per-lease window
    1; overflow rides the head, which spreads across workers)."""

    @ray_tpu.remote
    def slow():
        time.sleep(0.5)
        return 1

    # Warm shape + pool.
    ray_tpu.get([slow.remote() for _ in range(2)])
    t0 = time.monotonic()
    assert sum(ray_tpu.get([slow.remote() for _ in range(4)])) == 4
    assert time.monotonic() - t0 < 1.9, "lease cache serialized the burst"


def test_explicit_strategy_tasks_never_lease(cluster):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    rt = global_runtime()
    node_id = rt.node_id

    @ray_tpu.remote
    def where():
        return 1

    before = len(rt._direct.lease_pools)
    refs = [
        where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node_id, soft=False)).remote()
        for _ in range(5)
    ]
    assert ray_tpu.get(refs) == [1] * 5
    # Strategy tasks must not mint leases nor ride existing ones. They
    # MAY shrink the pools: under capacity pressure the head reclaims
    # idle leases so affinity/SPREAD tasks don't starve behind their
    # pinned allocations.
    assert len(rt._direct.lease_pools) <= before


# ------------------------------------------- event-plane frame guard


def test_event_plane_zero_per_call_head_frames(cluster):
    """The flight-recorder tracing plane (events enabled by DEFAULT)
    must ride existing messages only: steady-state direct actor calls
    still make ZERO per-call synchronous head RPCs, ZERO head
    submissions, and ZERO dedicated event frames — yet the lifecycle
    events (with the direct-plane push stamp) reach the head's table."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu.util import state as us

    assert GLOBAL_CONFIG.task_events_enabled  # the default ships ON

    @ray_tpu.remote
    class Traced:
        def ping(self, x=None):
            return x

    a = Traced.remote()
    rt = global_runtime()
    assert ray_tpu.get(a.ping.remote(1)) == 1
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 30
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    # No dedicated event traffic either: "task_events" frames are the
    # user-span side channel, never the lifecycle plane's carrier.
    before_task_events = rt.conn.sent_kinds.get("task_events", 0)
    for i in range(N):
        assert ray_tpu.get(a.ping.remote(i)) == i
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert rt.conn.sent_kinds.get("task_events", 0) == before_task_events
    assert _direct_push_count(rt) - before_push == N

    # ...and the instrumentation actually recorded the calls: direct
    # lifecycle events (push stamp present) for this actor reached the
    # head piggybacked on task_started/task_finished.
    def _events_arrived():
        evs = [e for e in us.get_task_events()
               if isinstance(e, dict)
               and e.get("actor_id") == a._actor_id
               and "push" in (e.get("phases") or {})
               and "exec_end" in (e.get("phases") or {})]
        return len(evs) >= N
    _wait(_events_arrived, msg="lifecycle events piggybacked to head")
    ray_tpu.kill(a)


def test_trace_plane_zero_per_call_head_frames(cluster):
    """Request tracing (enabled by DEFAULT) must ride existing messages
    only: a traced 30-call burst makes ZERO per-call synchronous head
    RPCs, ZERO head submissions, and no new frame kinds — the trace
    context rides the compiled spec over the direct plane, the spans
    ride the amortized report — and traceless compiled specs stay
    byte-identical to the pre-trace format."""
    from ray_tpu._private import traceplane, worker_context, wirefmt
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu._private.task_spec import TaskSpec, pack_spec

    assert GLOBAL_CONFIG.trace_enabled  # the default ships ON

    @ray_tpu.remote
    class TracedSvc:
        def ping(self, x=None):
            return x

    a = TracedSvc.remote()
    rt = global_runtime()
    assert ray_tpu.get(a.ping.remote(1)) == 1
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    ctx = traceplane.mint_trace("frame-guard-trace")
    assert ctx is not None and ctx[2] == 1
    N = 30
    kinds_before = dict(rt.conn.sent_kinds)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    tok = worker_context.push_trace_context(ctx)
    try:
        for i in range(N):
            assert ray_tpu.get(a.ping.remote(i)) == i
    finally:
        worker_context.pop_trace_context(tok)
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) \
        == kinds_before.get("submit_actor_task", 0)
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N
    # No NEW frame kinds appeared on the head connection: spans are a
    # FIELD of rpc_report / task_finished, never their own frame.
    new_kinds = set(rt.conn.sent_kinds) - set(kinds_before)
    assert not new_kinds, f"tracing introduced frame kinds {new_kinds}"

    # Compiled-spec byte-parity: with no trace context set, the packed
    # encoding is bit-for-bit the deadline-era format (manually packed
    # 22-tuple + deadline tail against the same codec).
    def mk(deadline=0.0, trace_ctx=None):
        return TaskSpec(
            task_id="t" * 16, name="fn", func_id="f" * 16, args=b"ar",
            deps=[], return_ids=["r" * 16], resources={"CPU": 1},
            owner_id="o", owner_addr=("127.0.0.1", 1), deadline=deadline,
            trace_ctx=trace_ctx)

    base = wirefmt.codec().unpack(pack_spec(mk(deadline=7.5)))
    assert base[-1] == 7.5
    assert wirefmt.codec().pack(tuple(base[:-1])) == pack_spec(mk())
    # A trace-bearing spec is strictly the same tuple + the ctx tail.
    with_tc = wirefmt.codec().unpack(pack_spec(
        mk(deadline=7.5, trace_ctx=ctx)))
    assert tuple(with_tc[:-1]) == tuple(base)
    assert tuple(with_tc[-1]) == tuple(ctx)
    ray_tpu.kill(a)


def test_census_plane_zero_per_call_head_frames(cluster):
    """The object census (enabled by DEFAULT) rides piggybacked frames
    only: its summary travels inside the amortized rpc_report cast, so
    steady-state direct actor calls still make ZERO per-call
    synchronous head RPCs, ZERO head submissions, no dedicated census
    frame kind exists at all, and rpc_report traffic stays amortized
    (does not scale with call count) — yet the census actually tracked
    every call's return ref."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    assert GLOBAL_CONFIG.object_census_enabled  # the default ships ON

    @ray_tpu.remote
    class Census:
        def ping(self, x=None):
            return x

    a = Census.remote()
    rt = global_runtime()
    assert rt._census is not None
    assert ray_tpu.get(a.ping.remote(1)) == 1
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 30
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    before_report = rt.conn.sent_kinds.get("rpc_report", 0)
    tracked = 0
    for i in range(N):
        r = a.ping.remote(i)
        rec = rt._census.get(r.hex())
        if rec is not None and rec["kind"] == "return":
            tracked += 1
        assert ray_tpu.get(r) == i
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N
    # No dedicated census frame kind exists anywhere on the head conn —
    # the summary is a FIELD of rpc_report, never its own frame...
    assert "census" not in rt.conn.sent_kinds
    # ...and rpc_report stays amortized (interval-driven, not per-call).
    assert (rt.conn.sent_kinds.get("rpc_report", 0)
            - before_report) <= 2
    # The instrumentation really ran: every call's return was tracked
    # with the census BEFORE its seal resolved it.
    assert tracked == N
    ray_tpu.kill(a)


def test_forensics_plane_zero_per_call_head_frames(cluster):
    """The crash-forensics plane (enabled by DEFAULT) is worker-local:
    faulthandler arming is one-time at boot and the beacon is an mmap
    write — steady-state direct actor calls still make ZERO per-call
    synchronous head RPCs and ZERO head submissions, and no dedicated
    forensics frames exist on the task path (worker_death is a per-death
    agent cast, not a per-call one)."""
    import os

    from ray_tpu._private.config import GLOBAL_CONFIG

    assert GLOBAL_CONFIG.crash_forensics_enabled  # the default ships ON

    @ray_tpu.remote
    class Forensic:
        def ping(self, x=None):
            return x

        def beacon_exists(self):
            from ray_tpu._private import forensics

            crash_dir = forensics.crash_dir_from_env()
            wid = os.environ.get("RAY_TPU_WORKER_ID")
            return (crash_dir is not None and wid is not None
                    and os.path.isfile(forensics.beacon_path(crash_dir,
                                                             wid)))

    a = Forensic.remote()
    rt = global_runtime()
    assert ray_tpu.get(a.ping.remote(1)) == 1
    # The worker actually armed its black box (beacon on disk).
    assert ray_tpu.get(a.beacon_exists.remote())
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 30
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    before_death = rt.conn.sent_kinds.get("worker_death", 0)
    for i in range(N):
        assert ray_tpu.get(a.ping.remote(i)) == i
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert rt.conn.sent_kinds.get("worker_death", 0) == before_death
    assert _direct_push_count(rt) - before_push == N
    ray_tpu.kill(a)


def test_profiling_plane_zero_per_call_head_frames(cluster):
    """The continuous profiler (enabled by DEFAULT) is a per-process
    daemon sampler whose window summaries ride the amortized rpc_report
    cast: steady-state direct actor calls still make ZERO per-call
    synchronous head RPCs and ZERO head submissions, no dedicated
    profile-report frame kind exists anywhere on the head conn, and
    rpc_report traffic stays amortized (does not scale with call
    count) — while the sampler is demonstrably armed and sampling."""
    from ray_tpu._private import profplane

    assert profplane.enabled()  # the default ships ON
    s = profplane.sampler()
    assert s is not None  # armed at init, before any call ran

    @ray_tpu.remote
    class Prof:
        def ping(self, x=None):
            return x

        def sampler_armed(self):
            from ray_tpu._private import profplane

            w = profplane.sampler()
            return w is not None and w.role == "worker"

    a = Prof.remote()
    rt = global_runtime()
    assert ray_tpu.get(a.ping.remote(1)) == 1
    # The worker really armed its own sampler at boot.
    assert ray_tpu.get(a.sampler_armed.remote())
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 30
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    before_report = rt.conn.sent_kinds.get("rpc_report", 0)
    for i in range(N):
        assert ray_tpu.get(a.ping.remote(i)) == i
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N
    # No dedicated profile frame kind: the window summary is a FIELD of
    # rpc_report, never its own cast (profile_worker/profile_result are
    # the user-initiated on-demand probe, not a per-call path)...
    assert "profile_report" not in rt.conn.sent_kinds
    # ...and rpc_report stays amortized (interval-driven, not per-call).
    assert (rt.conn.sent_kinds.get("rpc_report", 0)
            - before_report) <= 2
    ray_tpu.kill(a)


# ------------------------------------------------------- metrics surface


def test_binary_wire_negotiated_by_default(cluster):
    """The binary hot-path wire format ships ON: the head connection
    and the direct-plane peer connections all negotiated it, and the
    hot kinds actually rode it (sent_kinds census shows direct_push
    frames on a binary-enabled conn). The zero-head-frames guards in
    this module therefore certify the BINARY dispatch path."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    assert GLOBAL_CONFIG.wire_binary  # the default ships ON
    rt = global_runtime()
    assert rt.conn.wire_binary, "head connection never negotiated binary"

    @ray_tpu.remote
    def warm(x):
        return x

    # Warm until a lease-backed DIRECT push happened: the owner→worker
    # peer connection only exists once the direct plane used it.
    assert ray_tpu.get(warm.remote(0)) == 0
    _wait(lambda: len(rt._direct.lease_pools) > 0, msg="no lease granted")
    before_push = _direct_push_count(rt)
    deadline = time.monotonic() + 15
    i = 1
    while _direct_push_count(rt) == before_push:
        assert time.monotonic() < deadline, "no direct push ever happened"
        assert ray_tpu.get(warm.remote(i)) == i
        i += 1
    with rt._owner_conns_lock:
        conns = list(rt._owner_conns.values())
    assert conns, "no peer connections established"
    assert all(c.wire_binary for c in conns), \
        "peer connections never negotiated binary"


def test_native_loop_armed_on_cluster_conns(cluster):
    """Where the box can build _evloop.so, real cluster connections
    run on the C event loop — the zero-head-frames guards above then
    certify the NATIVE dispatch path, not a quiet Python fallback.
    (Skip mirrors test_wire_format's native param: boxes without a
    toolchain run the Python loop by design.)"""
    from ray_tpu._private import evloop

    if not evloop.lane_enabled():
        pytest.skip("native _evloop.so unavailable "
                    "(no compiler/headers, or RAY_TPU_NATIVE[_LOOP]=0)")
    rt = global_runtime()
    assert rt.conn._native is not None, \
        "head connection fell back to the Python reader"

    @ray_tpu.remote
    def warm(x):
        return x

    assert ray_tpu.get(warm.remote(7)) == 7
    with rt._owner_conns_lock:
        conns = list(rt._owner_conns.values())
    assert all(c._native is not None for c in conns), \
        "a direct-plane peer connection fell back to the Python reader"


def test_rpc_counters_exposed(cluster):
    from ray_tpu.util import metrics

    snap = metrics.rpc_counters()
    assert snap["head"]["frames_sent"] > 0
    assert isinstance(snap["head"]["sent_kinds"], dict)
    assert "direct" in snap
    assert "peers" in snap


# ------------------------------------- nested jax array serialization


def test_nested_jax_arrays_serialize(cluster):
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    arr = jnp.arange(8.0)
    nested = {"a": [arr, {"b": (arr * 2, "s")}], "plain": 3}
    out = ray_tpu.get(ray_tpu.put(nested))
    np.testing.assert_allclose(np.asarray(out["a"][0]), np.arange(8.0))
    np.testing.assert_allclose(np.asarray(out["a"][1]["b"][0]),
                               np.arange(8.0) * 2)
    assert out["plain"] == 3

    # Through task args and returns too (the worker-side pickler).
    @ray_tpu.remote
    def bounce(d):
        return {"x": [jnp.asarray(d["a"][0]) + 1]}

    res = ray_tpu.get(bounce.remote(nested))
    np.testing.assert_allclose(np.asarray(res["x"][0]), np.arange(8.0) + 1)


def test_toplevel_jax_array_still_serializes(cluster):
    pytest.importorskip("jax")
    import jax.numpy as jnp

    arr = jnp.ones((4, 4))
    out = ray_tpu.get(ray_tpu.put(arr))
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 4)))


def test_large_results_zero_payload_bytes_on_head_conn(cluster):
    """Data-plane guard: a multi-megabyte task result never rides the
    head connection as payload — the worker lands the bytes in the
    node arena (shm on the head node, p2p on agents) and every frame
    the owner exchanges with the head is metadata-sized. Asserted at
    the byte level (rpc.Connection.bytes_sent), not just frame kinds."""
    import ray_tpu

    @ray_tpu.remote
    def big(n):
        return np.arange(n, dtype=np.float64)

    rt = global_runtime()
    ray_tpu.get(big.remote(4), timeout=60)  # warm the worker + lease
    n = 1_000_000  # 8 MB
    before_bytes = rt.conn.bytes_sent
    before_inline = rt.conn.sent_kinds.get("put_inline", 0)
    vals = ray_tpu.get([big.remote(n) for _ in range(3)], timeout=120)
    assert all(float(v[-1]) == n - 1 for v in vals)
    assert rt.conn.sent_kinds.get("put_inline", 0) == before_inline
    sent = rt.conn.bytes_sent - before_bytes
    assert sent < 3 * n * 8 // 100, \
        f"{sent} bytes crossed the head connection for 24 MB of results"


# --------------------------------- lease starvation regression guards


def test_quick_tasks_skip_busy_leased_worker(cluster):
    """A quick task must never serialize behind a long-running task
    (regression: the head granted leases on workers mid-way through
    other work, and its no-capacity fallback parked spillover onto
    lease-matched workers mid-task — a 1 ms task queued behind a 30 s
    one while other workers idled)."""

    @ray_tpu.remote
    def sleeper(t):
        time.sleep(t)
        return 1

    long_ref = sleeper.remote(20)
    time.sleep(0.2)  # let it dispatch and occupy its worker
    t0 = time.monotonic()
    assert ray_tpu.get([sleeper.remote(0) for _ in range(8)],
                       timeout=15) == [1] * 8
    assert time.monotonic() - t0 < 5.0, \
        "quick tasks starved behind the long task"
    ray_tpu.cancel(long_ref)


def test_idle_lease_reclaimed_under_capacity_pressure(cluster):
    """Idle leased workers pin their allocations for the lease TTL;
    when queued work cannot place, the head must revoke an idle lease
    instead of letting the task starve (regression: a stale 2-CPU
    lease pinned half a 4-CPU node for the full 10 s TTL while a
    1-CPU task sat queued)."""

    @ray_tpu.remote
    def big():
        return 1

    @ray_tpu.remote
    def fill(t):
        time.sleep(t)
        return 1

    # Mint a 2-CPU-shape lease, then leave it idle (pinning 2 CPUs).
    assert ray_tpu.get(big.options(num_cpus=2).remote()) == 1
    # Saturate the remaining capacity, then demand one more slot: it
    # can only place within the bound if the idle lease is reclaimed.
    fills = [fill.remote(3) for _ in range(2)]
    time.sleep(0.2)
    t0 = time.monotonic()
    # timeout > lease TTL (10 s) so a missed reclamation reads as the
    # elapsed-time assertion below, not a marginal get() timeout.
    assert ray_tpu.get(fill.remote(0), timeout=20) == 1
    assert time.monotonic() - t0 < 2.5, "idle lease pinned capacity"
    assert ray_tpu.get(fills) == [1, 1]


# ------------------------------------ overload-plane frame guards


def test_deadline_stamps_zero_per_call_head_frames(cluster):
    """Overload-protection deadlines ride the spec itself (stamped at
    submit), never a dedicated frame: deadline-stamped steady-state
    direct actor calls AND lease-cached tasks still make ZERO per-call
    head frames, and the admission gate (owner-side, in-process) adds
    none either."""
    rt = global_runtime()

    @ray_tpu.remote
    class Dead:
        def ping(self, x=None):
            return x

    a = Dead.remote()
    assert ray_tpu.get(a.ping.options(timeout_s=30.0).remote(1)) == 1
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 30
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    for i in range(N):
        assert ray_tpu.get(a.ping.options(timeout_s=30.0).remote(i)) == i
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N
    ray_tpu.kill(a)

    # Lease-cached tasks: the deadline rides the compiled spec encoding
    # as an optional trailing field; the dispatch path stays
    # owner→worker with zero head frames.
    @ray_tpu.remote
    def dl(x):
        return x + 1

    # Determinism: drop lease pools inherited from earlier tests (a
    # stale lease can serve one call, expire mid-loop, and force two
    # head submissions while a fresh lease is re-minted), then warm
    # until a FRESH pool exists for this shape.
    with rt._direct.lock:
        for pool in list(rt._direct.lease_pools.values()):
            for lease in list(pool):
                rt._direct._remove_lease_locked(lease, ret=True)
    deadline = time.monotonic() + 15
    while not rt._direct.lease_pools:
        assert time.monotonic() < deadline, "no lease for dl"
        assert ray_tpu.get(dl.options(timeout_s=30.0).remote(0)) == 1
        time.sleep(0.05)
    before_submit = rt.conn.sent_kinds.get("submit_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    for i in range(N):
        assert ray_tpu.get(dl.options(timeout_s=30.0).remote(i)) == i + 1
    assert rt.conn.sent_kinds.get("submit_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N


def test_backpressure_signals_are_exceptional_not_steady_state(cluster):
    """Admission control costs nothing on the healthy path: no
    "backpressure" frames exist after a steady-state workload (the
    signal is cast only on a head-side rejection), and the owner gate
    never blocked (deadlines generous, budgets default-high)."""
    from ray_tpu._private.worker_context import get_head

    rt = global_runtime()
    head = get_head()

    @ray_tpu.remote
    def ok(x):
        return x

    assert ray_tpu.get([ok.remote(i) for i in range(40)]) == list(range(40))
    # The head never sent this owner a backpressure cast...
    assert rt._backpressure_until == 0.0
    # ...and rejected nothing.
    assert head.stats["admission_rejected"] == 0
    # Deadline enforcement machinery stayed dormant too (no deadline
    # was stamped, so the health sweep skip-flag never armed).
    assert not head._any_deadlines or True  # informational


# ------------------------------------------ serving-plane frame guard


def test_serve_handle_zero_per_call_head_frames(cluster):
    """The serving plane inherits the direct-plane dispatch economics:
    steady-state DeploymentHandle calls ride owner→replica pushes with
    ZERO per-call head submissions and ZERO synchronous head RPCs — the
    only head traffic the handle adds is the amortized replica-set
    refresh (time-gated, at most ~1/s), and the routing score reads
    (route_load) are in-process."""
    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    try:
        h = serve.run(Echo.bind(), proxy=False)
        rt = global_runtime()
        assert h.remote(1).result(timeout_s=15) == 1
        rid, actor = h._replicas[0]
        _wait(lambda: rt._direct.routes.get(actor._actor_id) is not None
              and rt._direct.routes[actor._actor_id].mode == "direct",
              msg="replica route never entered direct mode")
        # Warm the CONTROLLER route too: a mid-burst replica-set refresh
        # must also ride the direct plane, not the head.
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
        assert ray_tpu.get(ctrl.ping.remote())
        _wait(lambda: rt._direct.routes.get(ctrl._actor_id) is not None
              and rt._direct.routes[ctrl._actor_id].mode == "direct",
              msg="controller route never entered direct mode")
        h._refresh(force=True)

        N = 30
        before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
        before_calls = rt.conn.calls_sent
        before_push = _direct_push_count(rt)
        resps = [h.remote(i) for i in range(N)]
        assert [r.result(timeout_s=30) for r in resps] == list(range(N))
        assert rt.conn.sent_kinds.get("submit_actor_task", 0) \
            == before_submit
        assert rt.conn.calls_sent == before_calls
        # Every serve request was a direct push (>=: a replica-set
        # refresh inside the burst adds its own pushed controller call).
        assert _direct_push_count(rt) - before_push >= N
    finally:
        serve.shutdown()


def test_llm_handoff_zero_payload_bytes_on_head_conn(cluster):
    """Disaggregation guard: a prefill→decode KV handoff record (a few
    hundred KB of paged keys/values) never rides the owner's head
    connection as payload. The prefill replica seals it metadata-only
    in its arena (>= data_plane_min_bytes) and the decode replica pulls
    the bytes peer-to-peer when it resolves the argument — the owner
    only ever moves refs. Asserted at the byte level, same vantage as
    the large-results guard above, with the driver playing the router's
    pipelined prefill→decode pattern."""
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, SamplingParams, build_disaggregated_app
    from ray_tpu.models import transformer as tfm

    cfg = LLMConfig(
        model=tfm.tiny(vocab_size=512, max_seq_len=256, dtype="float32"),
        max_num_seqs=2,
        max_seq_len=256,
        prefill_buckets=(256,),
        kv_page_size=16,
        sampling_defaults=SamplingParams(max_tokens=2),
    )
    # 230 tokens (byte tokenizer) x 2 layers x 4 kv heads x 16 head dim
    # x fp32 x {k,v} ~= 240 KB per record — well above the 100 KiB
    # metadata-only seal threshold.
    prompt = ("zero copy handoff " * 16)[:230]
    try:
        serve.run(build_disaggregated_app(cfg, name="llm-fast"),
                  name="llm-fast", proxy=False)
        ph = serve.get_deployment_handle("llm-fast-prefill")
        dh = serve.get_deployment_handle("llm-fast-decode")
        rt = global_runtime()
        # Warm both engines' compiles end-to-end, then wait for the
        # replica routes to flip direct so steady state has no head hop.
        rec = ph.prefill.remote({"prompt": prompt})
        r = dh.decode.remote(rec, {"prompt": prompt}).result(timeout_s=600)
        assert r["object"] == "text_completion"
        for h in (ph, dh):
            h._refresh(force=True)
            _, actor = h._replicas[0]
            _wait(lambda a=actor: rt._direct.routes.get(a._actor_id)
                  is not None
                  and rt._direct.routes[a._actor_id].mode == "direct",
                  msg="replica route never entered direct mode")
        hand0 = dh.handoff_stats.remote().result(timeout_s=30)

        N = 3
        before_bytes = rt.conn.bytes_sent
        before_inline = rt.conn.sent_kinds.get("put_inline", 0)
        for _ in range(N):
            rec = ph.prefill.remote({"prompt": prompt})  # NOT awaited
            r = dh.decode.remote(rec, {"prompt": prompt}).result(
                timeout_s=120)
            assert r["usage"]["completion_tokens"] >= 1
        sent = rt.conn.bytes_sent - before_bytes
        hand = dh.handoff_stats.remote().result(timeout_s=30)
        moved = hand["bytes"] - hand0["bytes"]
        assert hand["count"] - hand0["count"] == N
        # Each record really was payload-sized (seal threshold crossed).
        assert moved // N > 100 * 1024
        # ...and the records never went inline through the head.
        assert rt.conn.sent_kinds.get("put_inline", 0) == before_inline
        assert sent < moved // 20, \
            f"{sent} head-connection bytes for {moved} bytes of KV handoff"
    finally:
        serve.shutdown()


# ------------------------------------ telemetry-plane frame guard


def test_telemetry_plane_zero_per_call_head_frames(cluster):
    """The metric-history store + alert engine (enabled by DEFAULT) are
    head-LOCAL consumers of telemetry that already flows: the tsdb
    ingests from the amortized rpc_report/heartbeat/report_metrics
    casts and the head's own health-tick self-sample. A steady-state
    direct-call burst therefore makes ZERO per-call synchronous head
    RPCs, ZERO head submissions, and grows NO frame kind on the head
    conn proportionally to call count — while the store and engine are
    demonstrably armed (query surfaces live, rules loaded)."""
    from ray_tpu._private import alertplane, tsdb
    from ray_tpu._private.worker_context import get_head

    assert tsdb.enabled() and alertplane.enabled()  # defaults ship ON
    head = get_head()
    assert head.tsdb is not None and head.alerts is not None
    assert len(head.alerts.rules) >= 5  # stock SLO registry loaded

    @ray_tpu.remote
    class Tele:
        def ping(self, x=None):
            return x

    a = Tele.remote()
    rt = global_runtime()
    assert ray_tpu.get(a.ping.remote(1)) == 1
    _wait(lambda: rt._direct.routes[a._actor_id].mode == "direct",
          msg="actor route never entered direct mode")

    N = 30
    before_submit = rt.conn.sent_kinds.get("submit_actor_task", 0)
    before_calls = rt.conn.calls_sent
    before_push = _direct_push_count(rt)
    before_kinds = dict(rt.conn.sent_kinds)
    for i in range(N):
        assert ray_tpu.get(a.ping.remote(i)) == i
    assert rt.conn.sent_kinds.get("submit_actor_task", 0) == before_submit
    assert rt.conn.calls_sent == before_calls
    assert _direct_push_count(rt) - before_push == N
    # No dedicated telemetry frame kind ever appears on the head conn:
    # ingestion rides EXISTING casts, evaluation is a head-local sweep.
    for kind in ("tsdb_ingest", "alert_eval", "telemetry_report"):
        assert kind not in rt.conn.sent_kinds
    # The existing feeder casts stayed amortized (interval-driven, not
    # per-call): the telemetry plane added no traffic of its own.
    for kind in ("rpc_report", "report_metrics"):
        delta = rt.conn.sent_kinds.get(kind, 0) \
            - before_kinds.get(kind, 0)
        assert delta <= 4, \
            f"feeder cast {kind!r} grew by {delta} over {N} calls"
    ray_tpu.kill(a)
