"""Doc-code runs green in CI (reference: SURVEY.md §4 "doc tests" —
runnable snippets under doc/source/*/doc_code executed in CI)."""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "docs", "examples", "*.py")))


@pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_doc_example_runs(path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run([sys.executable, path], capture_output=True,
                         text=True, timeout=300, env=env)
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-2500:])
    assert "OK" in out.stdout


def test_examples_exist():
    assert len(EXAMPLES) >= 4
