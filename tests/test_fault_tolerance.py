"""Fault tolerance: lineage reconstruction, chaos worker-killing.

Modeled on the reference's fault-injection strategy (SURVEY.md §4 —
RayletKiller/WorkerKillerActor in _private/test_utils.py:1449, lineage
tests test_reconstruction*.py)."""

from __future__ import annotations

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.util import state as us


@pytest.fixture()
def cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# lineage reconstruction


def test_freed_object_is_reconstructed(cluster):
    @ray_tpu.remote
    def produce():
        return np.arange(50_000)  # big enough to live in shm, not inline

    ref = produce.remote()
    first = ray_tpu.get(ref)
    ray_tpu.free([ref], force=True)
    # The value is gone; lineage re-executes `produce`.
    again = ray_tpu.get(ref, timeout=30)
    np.testing.assert_array_equal(first, again)


def test_chain_reconstruction_recreates_deps(cluster):
    calls = []

    @ray_tpu.remote
    def base():
        return np.full(30_000, 7)

    @ray_tpu.remote
    def double(x):
        return x * 2

    b = base.remote()
    d = double.remote(b)
    assert ray_tpu.get(d)[0] == 14
    # Lose BOTH: reconstructing `double` must first re-run `base`.
    ray_tpu.free([b, d], force=True)
    out = ray_tpu.get(d, timeout=30)
    assert out[0] == 14 and len(out) == 30_000


def test_put_objects_are_not_reconstructable(cluster):
    ref = ray_tpu.put(np.arange(40_000))
    ray_tpu.free([ref], force=True)
    # No lineage for ray.put data: the get can only time out.
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(ref, timeout=1.5)


def test_reconstruction_cap(cluster):
    @ray_tpu.remote
    def produce():
        return np.arange(30_000)

    ref = produce.remote()
    ray_tpu.get(ref)
    for _ in range(3):  # default max_object_reconstructions = 3
        ray_tpu.free([ref], force=True)
        ray_tpu.get(ref, timeout=30)
    ray_tpu.free([ref], force=True)
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(ref, timeout=1.5)


def test_reconstruction_is_transparent_to_wait(cluster):
    @ray_tpu.remote
    def produce():
        return np.arange(30_000)

    ref = produce.remote()
    ray_tpu.get(ref)
    ray_tpu.free([ref], force=True)
    # get triggers reconstruction; wait then sees it ready.
    ray_tpu.get(ref, timeout=30)
    ready, _ = ray_tpu.wait([ref], timeout=5)
    assert ready == [ref]


# ---------------------------------------------------------------------------
# chaos: random worker killing under retries


def test_tasks_survive_chaos_worker_killing(cluster):
    """WorkerKiller analogue: SIGKILL random busy workers while a wave of
    retryable tasks runs; every task must still complete."""

    @ray_tpu.remote(max_retries=10)
    def chunk(i):
        time.sleep(0.15)
        return i

    refs = [chunk.remote(i) for i in range(12)]
    deadline = time.monotonic() + 20
    killed = 0
    my_pid = os.getpid()
    while killed < 3 and time.monotonic() < deadline:
        busy = [w for w in us.list_workers(filters=[("busy", "=", "True")])
                if w["pid"] not in (None, my_pid) and not w["actor_id"]]
        if busy:
            try:
                os.kill(busy[0]["pid"], signal.SIGKILL)
                killed += 1
            except ProcessLookupError:
                pass
        time.sleep(0.2)
    results = ray_tpu.get(refs, timeout=60)
    assert sorted(results) == list(range(12))
    assert killed >= 1, "chaos loop never found a worker to kill"


def test_multi_return_tasks_survive_chaos(cluster):
    """Multi-return tasks under worker SIGKILL: a task whose seals die
    unconfirmed registers ALL its return ids as pending — recovery must
    mark every lost sibling before reconstructing so the spec is
    enqueued once, not once per return id (regression: round-5 lost-
    seal recovery; both values must arrive and match)."""

    @ray_tpu.remote(max_retries=10, num_returns=2)
    def pair(i):
        time.sleep(0.12)
        return i, i * 10

    pairs = [pair.remote(i) for i in range(10)]
    deadline = time.monotonic() + 20
    killed = 0
    my_pid = os.getpid()
    while killed < 3 and time.monotonic() < deadline:
        busy = [w for w in us.list_workers(filters=[("busy", "=", "True")])
                if w["pid"] not in (None, my_pid) and not w["actor_id"]]
        if busy:
            try:
                os.kill(busy[0]["pid"], signal.SIGKILL)
                killed += 1
            except ProcessLookupError:
                pass
        time.sleep(0.2)
    flat = ray_tpu.get([r for pr in pairs for r in pr], timeout=60)
    for i in range(10):
        assert flat[2 * i] == i and flat[2 * i + 1] == i * 10
    assert killed >= 1, "chaos loop never found a worker to kill"


def test_actor_restart_then_named_lookup(cluster):
    @ray_tpu.remote(max_restarts=2, name="phoenix")
    class Phoenix:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def crash(self):
            os._exit(1)

    a = Phoenix.remote()
    assert ray_tpu.get(a.bump.remote()) == 1
    try:
        ray_tpu.get(a.crash.remote(), timeout=10)
    except Exception:
        pass
    # Restarted actor: fresh state, same identity, still named.
    deadline = time.monotonic() + 15
    val = None
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(a.bump.remote(), timeout=5)
            break
        except Exception:
            time.sleep(0.2)
    assert val == 1  # state reset by restart
    b = ray_tpu.get_actor("phoenix")
    assert ray_tpu.get(b.bump.remote()) == 2


def test_actor_max_task_retries(cluster, tmp_path):
    """@remote(max_restarts, max_task_retries): a method call in flight
    when the actor dies replays on the restarted incarnation instead of
    raising ActorDiedError (reference: max_task_retries at-least-once
    actor-call semantics). Without the option, in-flight calls still
    die with the actor."""
    import os
    import time

    marker = str(tmp_path / "attempted")

    @ray_tpu.remote(max_restarts=2, max_task_retries=2)
    class Flaky:
        def work(self, marker):
            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)  # die mid-call on the first attempt
            return "recovered"

        def ping(self):
            return 1

    a = Flaky.remote()
    ray_tpu.get(a.ping.remote(), timeout=30)
    assert ray_tpu.get(a.work.remote(marker), timeout=60) == "recovered"
    # The actor restarted exactly once and still serves.
    assert ray_tpu.get(a.ping.remote(), timeout=30) == 1
    ray_tpu.kill(a)

    # Default (max_task_retries=0): the in-flight call errors.
    marker2 = str(tmp_path / "attempted2")

    @ray_tpu.remote(max_restarts=2)
    class Fatal:
        def work(self, marker):
            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)
            return "never retried"

        def ping(self):
            return 1

    b = Fatal.remote()
    ray_tpu.get(b.ping.remote(), timeout=30)
    with pytest.raises(Exception, match="ActorDied"):
        ray_tpu.get(b.work.remote(marker2), timeout=60)
    ray_tpu.kill(b)
