"""Crash-forensics plane.

Modeled on the reference's structured worker-death diagnostics
(WorkerExitType + exit_detail through the GCS death path, OOM
attribution in the raylet): unit tests for the exit classifier and the
black-box primitives (beacon, stack excerpts, speedscope export), and
chaos-driven end-to-end tests asserting that injected SIGKILL/SIGSEGV
deaths produce correctly classified, retrievable crash reports whose
classification also rides the user-facing errors.
"""

from __future__ import annotations

import json
import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private import forensics
from ray_tpu._private.worker_context import global_runtime
from ray_tpu.util import metrics as um
from ray_tpu.util import state as us


def _wait(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError(f"never happened: {msg}")


# ======================================================= classification


def test_classify_clean_and_intent_exits():
    assert forensics.classify_exit(exit_code=0)[0] == forensics.CLEAN_EXIT
    assert forensics.classify_exit(
        exit_code=0, expected=("retired", "max_calls"))[0] \
        == forensics.RETIRED
    assert forensics.classify_exit(
        exit_code=0, expected=("shutdown", ""))[0] == forensics.SHUTDOWN
    reason, detail = forensics.classify_exit(
        exit_code=0, expected=("intended_kill", "ray_tpu.kill()"))
    assert reason == forensics.INTENDED_KILL and "kill" in detail


def test_classify_sigkill_paths():
    # Unattributed SIGKILL.
    reason, detail = forensics.classify_exit(term_signal=signal.SIGKILL)
    assert reason == forensics.SIGKILL and "unattributed" in detail
    # Kernel OOM evidence wins.
    assert forensics.classify_exit(
        term_signal=signal.SIGKILL, oom_killed=True)[0] \
        == forensics.KERNEL_OOM
    # Memory-monitor intent wins even over OOM evidence ordering.
    assert forensics.classify_exit(
        term_signal=signal.SIGKILL,
        expected=("memory_monitor", "policy kill"))[0] \
        == forensics.MEMORY_MONITOR_KILL
    # Intent-marked SIGKILL (ray_tpu.kill).
    assert forensics.classify_exit(
        term_signal=signal.SIGKILL, expected=("intended_kill", ""))[0] \
        == forensics.INTENDED_KILL


def test_classify_fatal_signal_and_exceptions():
    reason, detail = forensics.classify_exit(
        term_signal=signal.SIGSEGV,
        crash_text="Fatal Python error: Segmentation fault\n"
                   "Thread 0x01 (most recent call first):\n")
    assert reason == forensics.FATAL_SIGNAL
    assert "SIGSEGV" in detail and "captured" in detail
    assert forensics.classify_exit(term_signal=signal.SIGABRT)[0] \
        == forensics.FATAL_SIGNAL
    assert forensics.classify_exit(term_signal=signal.SIGTERM)[0] \
        == forensics.TERMINATED
    assert forensics.classify_exit(
        exit_code=1, crash_text="Uncaught exception in thread x:\n"
                                "Traceback (most recent call last):")[0] \
        == forensics.UNCAUGHT_EXCEPTION
    assert forensics.classify_exit(exit_code=3)[0] == forensics.UNKNOWN
    assert forensics.classify_exit()[0] == forensics.UNKNOWN


def test_classify_node_and_spawn_intents():
    reason, detail = forensics.classify_exit(
        expected=("node_death", "presumed dead: 31.0s"))
    assert reason == forensics.NODE_DEATH and "presumed" in detail
    assert forensics.classify_exit(
        expected=("spawn_failure", "never registered"))[0] \
        == forensics.SPAWN_FAILURE


def test_reason_rank_orders_intent_over_evidence_over_guess():
    r = forensics.REASON_RANK
    assert r[forensics.UNKNOWN] < r[forensics.SIGKILL] \
        < r[forensics.FATAL_SIGNAL] < r[forensics.MEMORY_MONITOR_KILL]
    assert r[forensics.KERNEL_OOM] > r[forensics.SIGKILL]


def test_split_status():
    assert forensics.split_status(None) == (None, None)
    assert forensics.split_status(0) == (0, None)
    assert forensics.split_status(9) == (None, 9)        # SIGKILL
    assert forensics.split_status(11) == (None, 11)      # SIGSEGV
    assert forensics.split_status(3 << 8) == (3, None)   # exit(3)


# ========================================================= black box


def test_beacon_roundtrip_and_torn_read(tmp_path):
    path = str(tmp_path / "w.beacon")
    b = forensics.Beacon(path)
    b.update("task-1", "f", "exec")
    rec = forensics.read_beacon(path)
    assert rec["task_id"] == "task-1" and rec["phase"] == "exec"
    assert rec["pid"] == os.getpid() and rec["rss"] > 0
    # The beacon is a plain file: readable with no process behind it.
    b.close()
    assert forensics.read_beacon(path)["task_id"] == "task-1"
    # Garbage (torn write) reads as "no beacon", never raises.
    with open(path, "wb") as f:
        f.write(b"RTB1" + (9999).to_bytes(4, "little") + b"junk")
    assert forensics.read_beacon(path) is None
    assert forensics.read_beacon(str(tmp_path / "missing")) is None


def test_stack_excerpt_anchors_last_dump():
    text = ("boot noise\nFatal Python error: Aborted\n"
            "Thread 0x01 (most recent call first):\n  File \"a.py\"\n")
    ex = forensics.stack_excerpt(text)
    assert ex[0].startswith("Fatal Python error")
    assert forensics.stack_excerpt("") == []
    assert forensics.stack_excerpt("no markers at all") == []


def test_collect_report_without_evidence(tmp_path):
    report = forensics.collect_report(
        "w-1", "node-1", 123, exit_code=0, crash_dir=str(tmp_path),
        log_path=str(tmp_path / "nope.log"))
    assert report["exit_type"] == forensics.CLEAN_EXIT
    assert report["stack"] == [] and report["log_tail"] == []
    assert report["beacon"] is None


def test_oom_watch_counts_and_deltas(tmp_path):
    ev = tmp_path / "memory.events"
    ev.write_text("low 0\nhigh 2\noom 1\noom_kill 1\noom_group_kill 0\n")
    w = forensics.OomWatch((str(ev),))
    assert w.delta() == 0  # baseline established at construction
    ev.write_text("low 0\nhigh 2\noom 3\noom_kill 3\noom_group_kill 0\n")
    assert w.delta() == 2
    assert w.delta() == 0


def test_speedscope_and_flamegraph_export(tmp_path):
    prof = {"worker_id": "w-1",
            "folded": {"a.py:main;b.py:inner": 7, "a.py:main": 3}}
    sc = us.to_speedscope(prof)
    assert sc["profiles"][0]["endValue"] == 10
    assert len(sc["shared"]["frames"]) == 2  # main deduped across stacks
    fg = us.save_flamegraph(prof, str(tmp_path / "fg.txt"))
    lines = open(fg).read().splitlines()
    assert "a.py:main;b.py:inner 7" in lines
    p = us.save_speedscope(prof, str(tmp_path / "sc.json"))
    assert json.load(open(p))["shared"]["frames"]


# ==================================================== end-to-end (chaos)


@pytest.fixture(scope="module")
def cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_sigkilled_worker_classified_with_last_task(cluster):
    """Acceptance: a chaos-plane SIGKILL'd worker yields a retrievable
    crash report with a classified exit reason, and the user-facing
    error for its in-flight task carries that reason plus last-task
    provenance."""

    @ray_tpu.remote(max_retries=0)
    def doomed_sleep():
        time.sleep(30)
        return 1

    ref = doomed_sleep.remote()
    busy = _wait(
        lambda: [w for w in us.list_workers()
                 if w["busy"] and not w["actor_id"] and w["pid"]],
        msg="task never occupied a worker")
    victim = busy[0]
    time.sleep(0.3)  # let the exec-phase beacon stamp land
    os.kill(victim["pid"], signal.SIGKILL)
    with pytest.raises(Exception) as ei:
        ray_tpu.get(ref, timeout=20)
    msg = str(ei.value)
    assert "reason: sigkill" in msg
    assert "last task doomed_sleep" in msg
    assert victim["node_id"] in msg

    report = _wait(lambda: us.get_crash_report(victim["worker_id"]),
                   msg="crash report never appeared")
    assert report["exit_type"] == "sigkill"
    assert report["term_signal"] == signal.SIGKILL
    assert report["signal_name"] == "SIGKILL"
    assert report["last_task"]["name"] == "doomed_sleep"
    # The beacon froze at the instant of death: mid-exec on this task.
    assert report["beacon"] is not None
    assert report["beacon"]["phase"] == "exec"
    assert report["beacon"]["task_id"] == report["last_task"]["task_id"]


def test_sigsegv_actor_carries_stack_excerpt(cluster):
    """Acceptance: injected SIGSEGV classifies as fatal_signal and the
    ActorDiedError carries a faulthandler stack excerpt; subsequent
    calls fail with the same classified death cause."""

    @ray_tpu.remote
    class Segfaulter:
        def ping(self):
            return 1

        def segv(self):
            os.kill(os.getpid(), signal.SIGSEGV)
            time.sleep(30)  # the signal kills us mid-call

    a = Segfaulter.remote()
    assert ray_tpu.get(a.ping.remote()) == 1
    actor_row = us.get_actor(a._actor_id)
    wid = actor_row["worker_id"]
    ref = a.segv.remote()
    with pytest.raises(Exception) as ei:
        ray_tpu.get(ref, timeout=20)
    msg = str(ei.value)
    assert "reason: fatal_signal" in msg
    assert "SIGSEGV" in msg
    assert "Fatal Python error" in msg  # stack excerpt rode the error

    # Subsequent calls carry the classified death cause too.
    with pytest.raises(Exception) as ei2:
        ray_tpu.get(a.ping.remote(), timeout=10)
    assert "fatal_signal" in str(ei2.value)

    report = _wait(lambda: us.get_crash_report(wid),
                   msg="segv crash report")
    assert report["exit_type"] == "fatal_signal"
    assert report["term_signal"] == signal.SIGSEGV
    assert any("Fatal Python error" in ln for ln in report["stack"])
    # Flight-recorder cross-link: the dead worker's last events ride
    # the report.
    assert report.get("events")


def test_intended_kill_and_retirement_classify_clean(cluster):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return 1

    a = Victim.remote()
    assert ray_tpu.get(a.ping.remote()) == 1
    wid = us.get_actor(a._actor_id)["worker_id"]
    ray_tpu.kill(a)
    report = _wait(lambda: us.get_crash_report(wid),
                   msg="kill report")
    assert report["exit_type"] == "intended_kill"

    # max_calls retirement: a clean, classified death — not noise.
    @ray_tpu.remote(max_calls=1)
    def one_shot():
        return os.environ.get("RAY_TPU_WORKER_ID")

    retiree = ray_tpu.get(one_shot.remote())
    report = _wait(lambda: us.get_crash_report(retiree),
                   msg="retirement report")
    assert report["exit_type"] == "retired"
    assert "max_calls" in report["exit_detail"]


def test_memory_monitor_kill_classified(cluster):
    """A memory-monitor victim classifies as memory_monitor_kill (the
    head records its intent before the SIGKILL), never as an anonymous
    external kill."""
    from ray_tpu._private.memory_monitor import MemoryMonitor
    from ray_tpu._private.worker_context import get_head

    head = get_head()
    mon = MemoryMonitor(head, threshold=0.9, min_kill_interval_s=0.0,
                        usage_fn=lambda: (95, 100))

    marker = f"/tmp/ray_tpu_forensics_oom_{os.getpid()}"

    @ray_tpu.remote(max_retries=1)
    def hog(path):
        # First attempt sleeps long (the kill victim); the retry after
        # the kill returns immediately.
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("1")
            time.sleep(30)
        return 1

    try:
        ref = hog.remote(marker)
        victim = _wait(
            lambda: [w for w in us.list_workers()
                     if w["busy"] and not w["actor_id"]],
            msg="hog never occupied a worker")[0]
        _wait(lambda: os.path.exists(marker), msg="hog never started")
        assert mon.tick(), "monitor should have killed the busy worker"
        report = _wait(lambda: us.get_crash_report(victim["worker_id"]),
                       msg="memory-monitor kill report")
        assert report["exit_type"] == "memory_monitor_kill"
        assert "OOM policy" in report["exit_detail"]
        assert "hog" in report["exit_detail"]  # running tasks named
        assert ray_tpu.get(ref, timeout=30) == 1  # retried cleanly
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_death_counters_and_prometheus_labels(cluster):
    snap = global_runtime().conn.call("runtime_stats", {})
    deaths = snap.get("worker_deaths") or {}
    # Earlier tests produced at least these classifications.
    assert deaths.get("sigkill", 0) >= 1
    assert deaths.get("fatal_signal", 0) >= 1
    text = um.runtime_stats_text()
    assert 'ray_tpu_worker_deaths_total{reason="sigkill"}' in text
    assert 'ray_tpu_worker_deaths_total{reason="fatal_signal"}' in text
    assert "ray_tpu_rpc_head_frames_total" in text


def test_crash_listing_and_timeline_instants(cluster):
    rows = us.list_crash_reports()
    assert rows and all("exit_type" in r for r in rows)
    # Summary rows are bounded: no stacks/log tails ride the listing.
    assert all("stack" not in r and "log_tail" not in r for r in rows)
    trace = us.timeline()
    deaths = [t for t in trace if t.get("cat") == "death"]
    assert any(t["name"].startswith("death:sigkill") for t in deaths)
    assert any(t["name"].startswith("death:fatal_signal")
               for t in deaths)


def test_profile_worker_state_api(cluster):
    @ray_tpu.remote
    class Spinner:
        def spin(self, dt):
            t0 = time.monotonic()
            n = 0
            while time.monotonic() - t0 < dt:
                n += 1
            return n

    a = Spinner.remote()
    # Creation must complete first: a mid-creation worker has no head
    # connection yet and profile_start would bounce.
    _wait(lambda: (us.get_actor(a._actor_id) or {}).get("state")
          == "ALIVE", msg="spinner actor alive")
    wid = us.get_actor(a._actor_id)["worker_id"]
    ref = a.spin.remote(1.2)
    prof = us.profile_worker(wid, duration_s=0.5)
    assert prof.get("samples", 0) > 0, prof
    assert isinstance(prof.get("folded"), dict)
    ray_tpu.get(ref)
    ray_tpu.kill(a)


def test_cpu_time_stamp_shows_blocked_tasks(cluster):
    """Satellite: wall-vs-CPU skew rides the event plane (cpu_time on
    the lifecycle event, exec_cpu in summarize_tasks) instead of the
    old RAY_TPU_WORKER_TASK_TIMING stderr prints."""

    @ray_tpu.remote
    def blocked_nap():
        time.sleep(0.4)
        return 1

    assert ray_tpu.get(blocked_nap.remote()) == 1

    def _ev():
        evs = [e for e in us.get_task_events()
               if isinstance(e, dict) and e.get("name") == "blocked_nap"
               and e.get("cpu_time") is not None]
        return evs
    evs = _wait(_ev, msg="cpu_time-stamped event")
    phases = evs[-1]["phases"]
    wall = phases["exec_end"] - phases["exec_start"]
    assert evs[-1]["cpu_time"] < wall / 4  # slept, didn't burn CPU
    summ = us.summarize_tasks()
    lat = summ["blocked_nap"]["phase_latency_s"]
    assert "exec_cpu" in lat and lat["exec_cpu"]["count"] >= 1
    assert lat["exec_cpu"]["p50"] < lat["exec"]["p50"]


# ------------------------------------------------- remote (agent) path


@pytest.mark.slow
def test_agent_worker_death_report_reaches_head(cluster):
    """The node agent's reaper classifies ITS workers' exits from the
    real wait status and ships the report to the head (worker_death),
    upgrading the head's thin conn-close classification."""
    from tests import chaos_utils

    agent = chaos_utils.start_agent(
        ray_tpu.get_runtime_context().gcs_address,
        node_id="forensics-node", num_cpus=2,
        resources={"forensics": 2.0})
    try:
        chaos_utils.wait_nodes(2)

        @ray_tpu.remote(max_retries=0, resources={"forensics": 1.0})
        def remote_sleep():
            time.sleep(30)
            return 1

        ref = remote_sleep.remote()

        def _busy_remote():
            return [w for w in us.list_workers()
                    if w["busy"] and w["node_id"] == "forensics-node"
                    and w["pid"]]
        victim = _wait(_busy_remote, msg="remote worker busy")[0]
        os.kill(victim["pid"], signal.SIGKILL)
        with pytest.raises(Exception):
            ray_tpu.get(ref, timeout=20)

        def _classified():
            r = us.get_crash_report(victim["worker_id"])
            return r if r and r.get("term_signal") == signal.SIGKILL \
                else None
        report = _wait(_classified, timeout=15,
                       msg="agent report never upgraded the record")
        assert report["exit_type"] == "sigkill"
        # Now kill the whole agent: node death gets its own report.
        chaos_utils.stop_agent(agent)
        agent = None
        node_report = _wait(
            lambda: us.get_crash_report("node:forensics-node"),
            timeout=60, msg="node death report")
        assert node_report["exit_type"] == "node_death"
        assert "presumed dead" in node_report["exit_detail"]
    finally:
        if agent is not None:
            chaos_utils.stop_agent(agent)
