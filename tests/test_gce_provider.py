"""GCE/TPU-pod node provider against a mock cloud HTTP API.

Reference: autoscaler/_private/gcp/node_provider.py (REST provider) +
the fake-cloud unit-test strategy (fake_multi_node/node_provider.py) —
here the REAL provider code runs, only the cloud endpoint is mocked."""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from ray_tpu.autoscaler.gce import GCENodeProvider


class MockCloud:
    """Minimal GCE instances + TPU queuedResources API. TPU queued
    resources start WAITING_FOR_RESOURCES and flip to ACTIVE after
    ``tpu_provision_delay_s`` (the queued-resources lifecycle)."""

    def __init__(self, tpu_provision_delay_s: float = 0.0):
        self.instances: dict[str, dict] = {}
        self.queued: dict[str, dict] = {}
        self.tpu_delay = tpu_provision_delay_s
        self.requests: list[tuple[str, str]] = []

        mock = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, payload: dict, code: int = 200):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def do_POST(self):
                mock.requests.append(("POST", self.path))
                if "/instances" in self.path:
                    body = self._read_body()
                    mock.instances[body["name"]] = {
                        "name": body["name"], "status": "RUNNING",
                        "labels": body.get("labels", {}),
                    }
                    return self._send({"name": "op"})
                m = re.search(r"queued_resource_id=([\w\-]+)", self.path)
                if "/queuedResources" in self.path and m:
                    qid = m.group(1)
                    body = self._read_body()
                    mock.queued[qid] = {
                        "name": f"projects/x/locations/y/queuedResources/{qid}",
                        "state": {"state": "WAITING_FOR_RESOURCES"},
                        "tpu": body.get("tpu", {}),
                        "created": time.monotonic(),
                    }
                    return self._send({"name": "op"})
                self._send({"error": "bad path"}, 404)

            def do_GET(self):
                mock.requests.append(("GET", self.path))
                if self.path.endswith("/instances"):
                    return self._send(
                        {"items": list(mock.instances.values())})
                m = re.search(r"/instances/([\w\-]+)$", self.path)
                if m:
                    inst = mock.instances.get(m.group(1))
                    if inst is None:
                        return self._send({"error": "notFound"}, 404)
                    return self._send(inst)
                if self.path.endswith("/queuedResources"):
                    return self._send(
                        {"queuedResources": [self._qr(q)
                                             for q in mock.queued.values()]})
                m = re.search(r"/queuedResources/([\w\-]+)", self.path)
                if m:
                    q = mock.queued.get(m.group(1))
                    if q is None:
                        return self._send({"error": "notFound"}, 404)
                    return self._send(self._qr(q))
                self._send({"error": "bad path"}, 404)

            def _qr(self, q: dict) -> dict:
                state = dict(q["state"])
                if (state["state"] == "WAITING_FOR_RESOURCES"
                        and time.monotonic() - q["created"] >= mock.tpu_delay):
                    state = {"state": "ACTIVE"}
                    q["state"] = state
                return {**q, "state": state}

            def do_DELETE(self):
                mock.requests.append(("DELETE", self.path))
                m = re.search(r"/instances/([\w\-]+)$", self.path)
                if m:
                    mock.instances.pop(m.group(1), None)
                    return self._send({"name": "op"})
                m = re.search(r"/queuedResources/([\w\-]+)", self.path)
                if m:
                    mock.queued.pop(m.group(1), None)
                    return self._send({"name": "op"})
                self._send({"error": "bad path"}, 404)

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_port}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()


NODE_TYPES = {
    "cpu-worker": {"kind": "vm", "machine_type": "n2-standard-8"},
    "tpu-v5e-8": {"kind": "tpu", "accelerator_type": "v5litepod-8",
                  "runtime_version": "v2-alpha-tpuv5-lite"},
}


@pytest.fixture
def cloud():
    m = MockCloud()
    yield m
    m.stop()


def _provider(m: MockCloud) -> GCENodeProvider:
    return GCENodeProvider("proj", "us-central2-b", NODE_TYPES,
                           api_endpoint=m.url, tpu_api_endpoint=m.url)


def test_vm_lifecycle(cloud):
    p = _provider(cloud)
    [nid] = p.create_node("cpu-worker")
    assert nid in cloud.instances
    assert p.non_terminated_nodes() == [nid]
    assert p.is_running(nid)
    assert p.node_type_of(nid) == "cpu-worker"
    p.terminate_node(nid)
    assert p.non_terminated_nodes() == []
    assert not p.is_running(nid)


def test_tpu_queued_resource_lifecycle():
    cloud = MockCloud(tpu_provision_delay_s=0.5)
    try:
        p = _provider(cloud)
        [nid] = p.create_node("tpu-v5e-8")
        assert nid in cloud.queued
        # Queued: visible but not running until the slice is ACTIVE.
        assert p.non_terminated_nodes() == [nid]
        assert not p.is_running(nid)
        deadline = time.time() + 10
        while time.time() < deadline and not p.is_running(nid):
            time.sleep(0.1)
        assert p.is_running(nid)
        assert p.node_type_of(nid) == "tpu-v5e-8"
        p.terminate_node(nid)
        assert p.non_terminated_nodes() == []
    finally:
        cloud.stop()


def test_provider_rediscovers_externally_listed_nodes(cloud):
    """A fresh provider instance (head restart) re-learns node types
    from cloud labels."""
    p1 = _provider(cloud)
    [vm] = p1.create_node("cpu-worker")
    [tpu] = p1.create_node("tpu-v5e-8")
    p2 = _provider(cloud)
    nodes = set(p2.non_terminated_nodes())
    assert nodes == {vm, tpu}
    assert p2.node_type_of(vm) == "cpu-worker"
    assert p2.node_type_of(tpu) == "tpu-v5e-8"


def test_v2_reconciler_end_to_end_with_gce_provider():
    """The REAL v2 reconciler drives the REAL GCE provider against the
    mock cloud: demand launches a TPU slice through the queued-resource
    lifecycle, then idle scale-down terminates it."""
    from ray_tpu.autoscaler import AutoscalerConfig, NodeType
    from ray_tpu.autoscaler.v2 import AutoscalerV2

    cloud = MockCloud(tpu_provision_delay_s=0.3)
    try:
        provider = _provider(cloud)
        cfg = AutoscalerConfig(
            node_types=[NodeType("tpu-v5e-8", {"TPU": 8},
                                 min_workers=0, max_workers=2)],
            idle_timeout_s=0.0,
        )
        demands_cell = [[{"TPU": 8}]]
        scaler = AutoscalerV2(provider, cfg,
                              demand_source=lambda: demands_cell[0])

        def tick():
            return scaler.update(
                ray_running=provider.is_running,
                node_is_idle=lambda cid: not demands_cell[0],
            )

        tick()
        assert len(cloud.queued) == 1
        # Becomes ACTIVE; the reconciler folds it into RAY_RUNNING.
        deadline = time.time() + 10
        r = {}
        while time.time() < deadline:
            r = tick()
            if r["instances"].get("RAY_RUNNING"):
                break
            time.sleep(0.1)
        assert r["instances"].get("RAY_RUNNING") == 1, r
        # Demand drains: idle node terminates via the cloud API.
        demands_cell[0] = []
        deadline = time.time() + 10
        while time.time() < deadline and cloud.queued:
            tick()
            time.sleep(0.1)
        assert not cloud.queued
    finally:
        cloud.stop()
