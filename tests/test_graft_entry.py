"""Driver contract tests: __graft_entry__.entry / dryrun_multichip."""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_compiles_tiny():
    """entry() is the full GPT-2 124M — too slow for CPU CI to *run*, but
    it must trace/lower cleanly."""
    import __graft_entry__ as ge

    fn, args = ge.entry()
    jax.jit(fn).lower(*args)  # trace + lower only, no compile/execute
